import json, sys, collections
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
from jax import lax
import bench
import mxnet_tpu as mx
import mxnet_tpu.numpy_extension as npx
from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

BS = 128
K = 24          # iterations fused into one executable (amortizes dispatch)
peak = bench._chip_peak(jax.devices()[0])

sigs = collections.Counter()
orig = npx.convolution
def spy(x, w, b=None, **kw):
    sigs[(tuple(x.shape), tuple(w.shape), tuple(kw.get("stride") or (1,1)),
          tuple(kw.get("pad") or (0,0)))] += 1
    return orig(x, w, b, **kw)
npx.convolution = spy
net = resnet50_v1(); net.initialize()
net(mx.np.zeros((BS, 3, 224, 224), dtype="float32"))
npx.convolution = orig

def time_fn(f, *args):
    def step(c, *a):
        def body(i, c):
            out = f(a[0] + c.astype(a[0].dtype), *a[1:])
            return jnp.sum(out, dtype=jnp.float32) * 1e-30
        c = lax.fori_loop(0, K, body, c)
        return c, c
    j = jax.jit(step)
    j, _ = bench._compile(j, jax.ShapeDtypeStruct((), jnp.float32),
                          *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args])
    sec, _ = bench._measure(j, (jnp.zeros(()), *args), n_state=1, target_s=0.8)
    return sec / K

rows = []
total = {"fwd_ms": 0.0, "dgrad_ms": 0.0, "wgrad_ms": 0.0, "flops": 0.0}
for (xs, ws, stride, pad), count in sorted(sigs.items()):
    x = jax.random.normal(jax.random.PRNGKey(0), xs, jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), ws, jnp.bfloat16) * 0.05
    dn = lax.conv_dimension_numbers(xs, ws, ("NCHW", "OIHW", "NCHW"))
    def conv(x, w, stride=stride, pad=pad, dn=dn):
        return lax.conv_general_dilated(
            x, w, stride, [(pad[0], pad[0]), (pad[1], pad[1])],
            dimension_numbers=dn)
    o_shape = jax.eval_shape(conv, x, w).shape
    do = jax.random.normal(jax.random.PRNGKey(2), o_shape, jnp.bfloat16)
    flops = 2 * o_shape[0]*o_shape[1]*o_shape[2]*o_shape[3] * ws[1]*ws[2]*ws[3]

    t_fwd = time_fn(conv, x, w)
    dgrad = lambda do, w: jax.vjp(lambda x_: conv(x_, w), x)[1](do)[0]
    wgrad = lambda do, x: jax.vjp(lambda w_: conv(x, w_), w)[1](do)[0]
    t_dg = time_fn(dgrad, do, w)
    t_wg = time_fn(wgrad, do, x)
    row = {"x": xs, "w": ws, "s": stride, "n": count,
           "gflops": round(flops/1e9, 1),
           "fwd_tf": round(flops/t_fwd/1e12, 1),
           "dgrad_tf": round(flops/t_dg/1e12, 1),
           "wgrad_tf": round(flops/t_wg/1e12, 1),
           "fwd_ms": round(t_fwd*1e3*count, 3),
           "dgrad_ms": round(t_dg*1e3*count, 3),
           "wgrad_ms": round(t_wg*1e3*count, 3)}
    rows.append(row)
    for k2 in ("fwd_ms", "dgrad_ms", "wgrad_ms"):
        total[k2] += row[k2]
    total["flops"] += flops * count
    print(json.dumps(row), file=sys.stderr, flush=True)
total = {k: round(v, 2) for k, v in total.items()}
total["peak_tf"] = peak/1e12
print(json.dumps({"bs": BS, "total": total, "rows": rows}))

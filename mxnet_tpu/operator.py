"""mx.operator — custom operator API.

Reference parity: python/mxnet/operator.py (CustomOp:434 with
forward/backward + assign, CustomOpProp:487 declaring shapes/types,
register:710 decorator; executed via src/operator/custom/custom.cc on a
dedicated async thread).  TPU-native: a registered custom op dispatches
through the normal `_invoke` path — forward runs the user's python (host
callback semantics, like the reference's custom-op thread), backward is
wired into the autograd tape through the same mechanism as
autograd.Function.

    class Relu(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], mx.np.maximum(in_data[0], 0))
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0],
                        out_grad[0] * (in_data[0] > 0))

    @mx.operator.register("my_relu")
    class ReluProp(mx.operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Relu()

    y = mx.nd.Custom(x, op_type="my_relu")
"""
from __future__ import annotations

from . import autograd
from .base import MXNetError
from .numpy.multiarray import ndarray, _wrap

__all__ = ["CustomOp", "CustomOpProp", "register", "Custom", "get"]

_registry = {}


class CustomOp:
    """User op instance (reference: operator.py:434)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write per grad_req (reference: CustomOp.assign)."""
        if req in ("null",):
            return
        src = src if isinstance(src, ndarray) else _wrap(src)
        if req == "add":
            dst._rebind((dst + src)._data)
        else:   # write / inplace
            dst._rebind(src._data)


class CustomOpProp:
    """Op metadata: shapes/dtypes/number of outputs
    (reference: operator.py:487)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


def register(reg_name):
    """Register a CustomOpProp subclass under a name
    (reference: operator.py:710)."""
    def deco(prop_cls):
        _registry[reg_name] = prop_cls
        return prop_cls
    return deco


def get(reg_name):
    if reg_name not in _registry:
        raise MXNetError(f"custom op {reg_name!r} not registered; "
                         f"known: {sorted(_registry)}")
    return _registry[reg_name]


def Custom(*inputs, op_type=None, **kwargs):
    """Invoke a registered custom op (the `mx.nd.Custom` entry point,
    reference: src/operator/custom/custom.cc)."""
    if op_type is None:
        raise MXNetError("Custom needs op_type=")
    prop = get(op_type)(**kwargs)
    in_shapes = [tuple(x.shape) for x in inputs]
    out_shapes = prop.infer_shape(in_shapes)[1]
    in_types = [str(x.dtype) for x in inputs]
    out_types = prop.infer_type(in_types)[1]
    op = prop.create_operator(None, in_shapes + out_shapes,
                              in_types + out_types)

    from .numpy import zeros
    n_out = len(prop.list_outputs())
    outputs = [zeros(s, dtype=t) for s, t in zip(out_shapes, out_types)]

    is_train = autograd.is_recording() and autograd.is_training()
    with autograd.pause():
        op.forward(is_train, ["write"] * n_out, list(inputs), outputs, [])

    if autograd.is_recording():
        fwd_inputs = list(inputs)
        fwd_outputs = list(outputs)

        class _Bridge(autograd.Function):
            def forward(self, *xs):
                return tuple(fwd_outputs) if n_out > 1 else fwd_outputs[0]

            def backward(self, *ograds):
                import jax.numpy as jnp
                in_grads = [_wrap(jnp.zeros(x.shape, x._data.dtype))
                            for x in fwd_inputs]
                op.backward(["write"] * len(in_grads), list(ograds),
                            fwd_inputs, fwd_outputs, in_grads, [])
                return tuple(in_grads) if len(in_grads) > 1 else in_grads[0]

        result = _Bridge()(*inputs)
        return result
    return outputs[0] if n_out == 1 else outputs

"""mx.viz — network visualization.

Reference parity: python/mxnet/visualization.py (print_summary:46 layer
table with shapes/params, plot_network: graphviz Digraph of the symbol
DAG).  Works on both Symbol graphs and Gluon Blocks; plot_network
returns DOT source text (and a graphviz.Digraph when the package is
importable — it is optional here, as in the reference).
"""
from __future__ import annotations

import numpy as onp

from .base import MXNetError

__all__ = ["print_summary", "plot_network", "dot_graph"]


def _block_rows(block, input_shape):
    """(name, type, out_shape, n_params) per direct child.

    Shapes are captured with forward hooks during ONE full forward of the
    parent block, so branching/residual architectures report each child's
    true output shape (a sequential probe would mis-thread them)."""
    from . import numpy as mxnp
    shapes = {}
    hooks = []
    for name, child in block._children.items():
        def mk(name):
            def hook(blk, args, out):
                o = out[0] if isinstance(out, (list, tuple)) else out
                shapes[name] = tuple(getattr(o, "shape", ()))
            return hook
        hooks.append((child, child.register_forward_hook(mk(name))))
    try:
        block(mxnp.zeros(input_shape))
    except Exception:
        pass  # partial rows are still useful; missing shapes print '?'
    finally:
        for child, h in hooks:
            try:
                child._forward_hooks.remove(h)
            except (ValueError, AttributeError):
                pass
    rows = []
    for name, child in block._children.items():
        params = sum(
            int(onp.prod(p.shape)) for p in child.collect_params().values()
            if p._data is not None or p._shape_known())
        rows.append((name, type(child).__name__,
                     shapes.get(name, "?"), params))
    return rows


def _symbol_rows(symbol, shape=None):
    rows = []
    shapes = {}
    if shape:
        try:
            args = symbol.list_arguments()
            arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
            shapes = dict(zip(args, arg_shapes))
        except Exception:
            pass
    for node in symbol._topo():
        if node._op is None:
            rows.append((node.name, "Variable",
                         shapes.get(node.name, ""), 0))
        else:
            rows.append((node.name, node._op, "", 0))
    return rows


def print_summary(symbol, shape=None, line_length=98,
                  positions=(.44, .64, .74, 1.)):
    """Print a layer table (reference: visualization.py print_summary).

    `symbol` may be a Symbol (pass `shape` = dict name->shape) or a Gluon
    Block (pass `shape` = the input shape tuple).
    """
    from .gluon.block import Block

    if isinstance(symbol, Block):
        if shape is None:
            raise MXNetError("print_summary(Block) needs the input shape")
        rows = _block_rows(symbol, shape)
    elif hasattr(symbol, "_topo"):
        rows = _symbol_rows(symbol, shape)
    else:
        raise MXNetError(f"cannot summarize {type(symbol)}")

    cols = [int(line_length * p) for p in positions]
    heads = ["Layer (type)", "Output Shape", "Param #", ""]

    def fmt(fields):
        line = ""
        for f, c in zip(fields, cols):
            line = (line + str(f))[:c].ljust(c)
        return line.rstrip()

    sep = "=" * line_length
    print(sep)
    print(fmt(heads))
    print(sep)
    total = 0
    for name, typ, shp, nparam in rows:
        print(fmt([f"{name} ({typ})", shp, nparam, ""]))
        total += nparam
    print(sep)
    print(f"Total params: {total}")
    print(sep)
    return total


def dot_graph(symbol, title="plot"):
    """DOT source for a Symbol DAG (the text behind plot_network)."""
    if not hasattr(symbol, "_topo"):
        raise MXNetError("dot_graph needs a Symbol")
    lines = [f'digraph "{title}" {{', "  rankdir=BT;"]
    ids = {}
    for i, node in enumerate(symbol._topo()):
        ids[id(node)] = f"n{i}"
        if node._op is None:
            style = 'shape=oval, fillcolor="#8dd3c7", style=filled'
            label = node.name
        else:
            style = 'shape=box, fillcolor="#fb8072", style=filled'
            label = f"{node.name}\\n{node._op}"
        lines.append(f'  n{i} [label="{label}", {style}];')
    for node in symbol._topo():
        for inp in node._inputs:
            lines.append(f"  {ids[id(inp)]} -> {ids[id(node)]};")
    lines.append("}")
    return "\n".join(lines)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 **kwargs):
    """Graphviz Digraph of the symbol DAG (reference: plot_network).
    Returns a graphviz.Digraph when graphviz is installed, else the DOT
    source string (same content either way)."""
    src = dot_graph(symbol, title)
    try:
        import graphviz
        return graphviz.Source(src, filename=title, format=save_format)
    except ImportError:
        return src

"""mx.rtc — runtime kernel compilation.

Reference parity: python/mxnet/rtc.py (CudaModule/CudaKernel over NVRTC,
src/common/rtc.cc).  On TPU there is no user-facing runtime C codegen:
XLA is the JIT and custom kernels are Pallas (see
mxnet_tpu/ops/pallas/ and mx.library for registration).  The classes
exist so 1.x scripts fail with a pointer instead of an AttributeError.
"""
from __future__ import annotations

from .base import MXNetError

_MSG = ("CUDA RTC is not applicable on the TPU stack: XLA compiles the "
        "graph and custom kernels are written with JAX Pallas — register "
        "them via mx.library / mxnet_tpu.ops.registry instead")


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)


class CudaKernel:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)

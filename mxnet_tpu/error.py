"""mx.error — typed error hierarchy.

Reference parity: python/mxnet/error.py (MXNetError base registered
against the C++ error codes, with InternalError/IndexError/ValueError/
TypeError/AttributeError/NotImplementedForSymbol subclasses).  Here the
hierarchy is pure python; each class also inherits its builtin
counterpart so `except ValueError` catches mx.error.ValueError too.
"""
from __future__ import annotations

import builtins

from .base import MXNetError  # noqa: F401

__all__ = ["MXNetError", "InternalError", "IndexError", "ValueError",
           "TypeError", "AttributeError", "NotImplementedForSymbol",
           "register_error"]

_ERROR_TYPES = {}


def register_error(cls):
    """Register an error class by name (reference: error.py
    register_error)."""
    _ERROR_TYPES[cls.__name__] = cls
    return cls


@register_error
class InternalError(MXNetError):
    pass


@register_error
class IndexError(MXNetError, builtins.IndexError):
    pass


@register_error
class ValueError(MXNetError, builtins.ValueError):
    pass


@register_error
class TypeError(MXNetError, builtins.TypeError):
    pass


@register_error
class AttributeError(MXNetError, builtins.AttributeError):
    pass


@register_error
class NotImplementedForSymbol(MXNetError):
    def __init__(self, function, alias=None, *args):
        super().__init__(f"function {getattr(function, '__name__', function)}"
                         " is not supported for Symbol")

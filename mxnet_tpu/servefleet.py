"""mx.servefleet — multi-replica serving control plane.

Reference parity: none — the reference stops at the single-process
engine.  Production serving needs the layer above it: N replicas of ONE
model behind a router, surviving the three events that kill a naive
deployment:

- **Failover.**  Sessions ride consistent-hash (rendezvous/HRW)
  affinity: when a replica dies (``serve.replica_crash``) or wedges
  while its lease stays fresh (``serve.replica_stall``), only THAT
  replica's sessions move.  Every incomplete request re-dispatches to a
  survivor under its idempotency key, re-prefilling from the original
  prompt — the KV cache died with the replica.  A late completion
  racing the re-dispatch (the stalled engine's already-dispatched
  device work is drained AFTER re-dispatch, deliberately) is suppressed
  by the completion ledger: every accepted request completes exactly
  once, never zero, never twice.
- **Rolling weight updates.**  A training fleet publishes a checkpoint
  (:func:`publish_checkpoint` — versioned data dir + atomic symlink
  swap, never a torn or missing read);
  :meth:`ServeFleet.rolling_update` walks the replicas one at a time:
  drain (``stop(drain=True)``), swap weights in place
  (:meth:`~mxnet_tpu.serve.engine.ServeEngine.update_weights` — same
  quantize mode, validated shapes, so the AOT grid stays hot),
  re-``warmup()`` (a cache hit: zero compiles), then a greedy-parity
  canary on pinned prompts against the checkpoint's canary card.  A
  divergent canary or ANY post-warmup compile auto-rolls the replica
  back to the old weights and aborts the rollout — the group never
  drops below ``servefleet.min_replicas`` live replicas.
- **SLO-driven scaling.**  The supervisor tick watches the per-engine
  error-budget burn gauges (PR 17): sustained burn past
  ``goodput.burn_threshold`` scales out (unpark first, then build up to
  ``servefleet.max_replicas``); sustained occupancy under
  ``servefleet.occupancy_floor`` drains and parks a replica, never
  below the floor.  ``servefleet.scale_patience`` debounces both
  directions and doubles as the post-action cooldown.

Every replica holds a :class:`~mxnet_tpu.fleet.HealthPlane` lease when
the fleet is built with a ``lease_dir`` — the same file-backed lease
the training fleet uses — so a multi-process drill
(tests/servefleet_worker.py) detects a SIGKILLed replica by lease
expiry exactly like ``fleet.host_loss``.

Disabled cost: the only hot-path hook is one module-attribute read in
``ServeEngine.step`` (``if _servefleet._active: note_step(engine)``) —
the same discipline as mx.fault/mx.goodput, re-gated by
benchmark/telemetry_overhead.py.
"""
from __future__ import annotations

import collections
import hashlib
import itertools
import json
import os
import time
import weakref

from . import config as _config
from . import fault as _fault
from . import fleet as _fleet
from . import goodput as _goodput
from . import telemetry as _telemetry
from . import trace as _trace
from .base import MXNetError

__all__ = ["ServeFleet", "FleetRequest", "Replica", "rendezvous_route",
           "canary_card", "publish_checkpoint", "load_checkpoint",
           "note_step", "endpoint_report"]

_telemetry.declare_metric(
    "servefleet.replicas_live", "gauge",
    "serving replicas currently live (routable) in the fleet group")
_telemetry.declare_metric(
    "servefleet.requests_total", "counter",
    "requests accepted by the fleet router (each carries an idempotency "
    "key; duplicate submits of the same key are absorbed, not re-run)")
_telemetry.declare_metric(
    "servefleet.completed_total", "counter",
    "fleet requests whose FIRST completion was recorded in the ledger — "
    "exactly one per accepted request, however many replicas raced it")
_telemetry.declare_metric(
    "servefleet.failovers_total", "counter",
    "replicas declared dead by the supervisor, by cause (crash: lease "
    "expiry / serve.replica_crash; stall: no decode progress past "
    "servefleet.stall_deadline with a fresh lease)")
_telemetry.declare_metric(
    "servefleet.redispatched_total", "counter",
    "incomplete requests re-dispatched from a dead replica to a "
    "survivor under their idempotency key (re-prefilled from the "
    "original prompt — the KV died with the replica)")
_telemetry.declare_metric(
    "servefleet.duplicates_suppressed_total", "counter",
    "late completions discarded by the idempotency ledger because the "
    "request already completed elsewhere (a stalled replica's drained "
    "device work racing its own re-dispatch)")
_telemetry.declare_metric(
    "servefleet.rolling_updates_total", "counter",
    "replicas successfully rolled to a new weight generation (drain -> "
    "in-place swap -> re-warmup with zero compiles -> canary parity)")
_telemetry.declare_metric(
    "servefleet.rollbacks_total", "counter",
    "rolling updates auto-rolled back on this replica: greedy canary "
    "diverged from the checkpoint's card, or re-warmup compiled")
_telemetry.declare_metric(
    "servefleet.scale_events_total", "counter",
    "autoscaler actions, by dir (out: sustained SLO burn past "
    "goodput.burn_threshold; in: sustained occupancy under "
    "servefleet.occupancy_floor)")
_telemetry.declare_metric(
    "servefleet.router_moves_total", "counter",
    "sessions whose rendezvous-hash route changed replica (failover or "
    "scaling) — affinity means this stays near zero in steady state")
_telemetry.declare_metric(
    "servefleet.prefix_routed_total", "counter",
    "sessionless requests routed by prompt-prefix fingerprint (hash of "
    "the first serve.prefix_block tokens), steering shared-prefix "
    "traffic to the replica whose radix cache already holds the rows")

#: hot-path gate — ``ServeEngine.step`` reads this one attribute per
#: decode step; False (no fleet constructed) keeps the hook a no-op
_active = False
#: id(engine) -> Replica, the step-progress watch the stall detector
#: reads (see :func:`note_step`)
_watch: dict[int, "Replica"] = {}
#: live fleets, for the /servefleet ops endpoint
_fleets: "weakref.WeakSet[ServeFleet]" = weakref.WeakSet()

CHECKPOINT_FORMAT = "mx.servefleet.checkpoint.v1"


def note_step(engine):
    """Record decode-step progress for the replica hosting ``engine`` —
    called from ``ServeEngine.step`` behind the ``_active`` gate.  This
    timestamp is what separates *stalled* (pending work, no progress
    past ``servefleet.stall_deadline``) from merely idle."""
    rep = _watch.get(id(engine))
    if rep is not None:
        rep.last_step = time.monotonic()
        rep.steps += 1


def _gauge(name, value, **labels):
    if _telemetry._active:
        _telemetry.set_gauge(name, value, **labels)


def _count(name, n=1, **labels):
    if _telemetry._active:
        _telemetry.inc(name, n, **labels)


# ---------------------------------------------------------------------------
# rendezvous (HRW) routing
# ---------------------------------------------------------------------------

def _score(session, rid):
    h = hashlib.blake2b(f"{session}|{rid}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


def rendezvous_route(session, replica_ids):
    """Highest-random-weight (rendezvous) hash: pick the replica with
    the max keyed score.  The property the router needs: when a replica
    leaves, ONLY the sessions it owned re-rank — every other session
    keeps its replica (no modulo reshuffle), so failover moves the
    minimum number of KV-affine sessions.  Deterministic across
    processes (blake2b, no seed) so the multi-process drill's driver
    and any observer agree on placement."""
    ids = list(replica_ids)
    if not ids:
        raise MXNetError("rendezvous_route: no live replicas")
    return max(ids, key=lambda rid: _score(session, rid))


def _route_order(session, replica_ids):
    """All live replicas, best rendezvous score first — the spill order
    when the affine replica rejects with EngineBusy."""
    return sorted(replica_ids, key=lambda rid: _score(session, rid),
                  reverse=True)


# ---------------------------------------------------------------------------
# request + replica records
# ---------------------------------------------------------------------------

class FleetRequest:
    """One accepted request's fleet-level record: the idempotency key,
    the session it routes under, the original prompt (re-dispatch
    re-prefills from it), the current engine-level request, and any
    orphaned engine requests left behind on a dead replica whose
    already-dispatched device work may still complete (the dedupe
    race).  ``tokens`` is None until the FIRST completion lands."""

    __slots__ = ("key", "session", "prompt", "max_new_tokens", "eos_id",
                 "slo_class", "engine_req", "orphans", "replica_id",
                 "redispatches", "tokens", "t_submit", "t_done")

    def __init__(self, key, session, prompt, max_new_tokens, eos_id,
                 slo_class=None):
        self.key = str(key)
        self.session = str(session)
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.slo_class = slo_class
        self.engine_req = None
        self.orphans = []
        self.replica_id = None
        self.redispatches = 0
        self.tokens = None
        self.t_submit = time.monotonic()
        self.t_done = None

    @property
    def done(self):
        return self.tokens is not None

    def __repr__(self):
        state = "done" if self.done else f"replica{self.replica_id}"
        return (f"FleetRequest(key={self.key!r}, session={self.session!r},"
                f" {state}, redispatches={self.redispatches})")


class Replica:
    """One engine + its lease + supervisor-visible state.

    States: ``live`` (routable), ``updating`` (mid rolling update,
    excluded from routing), ``parked`` (drained by scale-in, engine
    kept warm for instant unpark), ``dead`` (failed over, never
    revived — scale-out builds a fresh replica instead)."""

    __slots__ = ("rid", "engine", "plane", "state", "wedged",
                 "last_step", "steps", "generation", "__weakref__")

    def __init__(self, rid, engine, plane=None):
        self.rid = int(rid)
        self.engine = engine
        self.plane = plane
        self.state = "live"
        #: the serve.replica_stall injection wedges the step loop while
        #: the lease keeps renewing — progress stops, liveness doesn't
        self.wedged = False
        self.last_step = time.monotonic()
        self.steps = 0
        self.generation = 0

    def occupancy(self):
        live = sum(1 for s in self.engine._slots if s is not None)
        return live / max(1, self.engine.max_slots)

    def snapshot(self):
        return {"rid": self.rid, "state": self.state,
                "generation": self.generation, "steps": self.steps,
                "wedged": self.wedged,
                "occupancy": round(self.occupancy(), 4),
                "queued": len(self.engine._queue),
                "post_warmup_compiles": self.engine.post_warmup_compiles,
                "prefix_hits": self.engine.prefix_hits}


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------

class ServeFleet:
    """N replicas of one model behind a rendezvous-hash router.

    Usage::

        fleet = mx.servefleet.ServeFleet(lambda: build_model(),
                                         replicas=3, eos_id=50256)
        fr = fleet.submit(ids, max_new_tokens=64, session="user-7")
        fleet.run()                     # supervisor tick loop
        fr.tokens                       # exactly-once result
        fleet.rolling_update(new_params, canary=card)
        fleet.close()

    ``model_factory`` builds one model instance per replica (replicas
    must not share parameter state — a rolling update swaps one replica
    at a time).  Engine keyword arguments (``max_slots``, ``buckets``,
    ``eos_id``, ``temperature``, ``quantize``...) pass through to every
    :class:`~mxnet_tpu.serve.engine.ServeEngine`.  With ``lease_dir``
    each replica holds a :class:`~mxnet_tpu.fleet.HealthPlane` lease;
    a lease stale past ``fleet.lease_timeout`` is a detected crash.
    """

    def __init__(self, model_factory, replicas=2, min_replicas=None,
                 max_replicas=None, lease_dir=None, warmup=True,
                 **engine_kwargs):
        if not callable(model_factory):
            raise MXNetError("ServeFleet needs a model_factory callable "
                             "(one fresh model per replica)")
        replicas = int(replicas)
        if replicas < 1:
            raise MXNetError("ServeFleet needs at least one replica")
        self._model_factory = model_factory
        self._engine_kwargs = dict(engine_kwargs)
        self._lease_dir = lease_dir
        self._warmup = bool(warmup)
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else _config.get("servefleet.min_replicas"))
        cap = int(max_replicas if max_replicas is not None
                  else _config.get("servefleet.max_replicas"))
        self.max_replicas = cap if cap > 0 else replicas
        if self.min_replicas > replicas:
            raise MXNetError(
                f"servefleet.min_replicas={self.min_replicas} exceeds the "
                f"constructed replica count {replicas}")
        self._replicas: dict[int, Replica] = {}
        #: the exactly-once ledger, split so its cost stays bounded on a
        #: long-running fleet: in-flight requests (plus done ones still
        #: owed a duplicate-suppression sweep) live in ``_inflight``;
        #: settled requests move to ``_completed``, an LRU capped at
        #: ``servefleet.ledger_retain`` keys kept to absorb duplicate
        #: client submits.  Lifetime totals ride separate counters so
        #: :meth:`report` never needs the full history.
        self._inflight: dict[str, FleetRequest] = {}
        self._completed: "collections.OrderedDict[str, FleetRequest]" = \
            collections.OrderedDict()
        self._accepted_total = 0
        self._completed_total = 0
        self._redispatched_total = 0
        self._session_map: dict[str, int] = {}
        self._overflow = collections.deque()
        self._next_rid = 0
        self._next_key = 0
        self._tick = 0
        self._generation = 0
        self._current_params = None
        # autoscaler debounce/cooldown state
        self._burn_ticks = 0
        self._idle_ticks = 0
        self._cooldown = 0
        self._scale_events = {"out": 0, "in": 0}
        for _ in range(replicas):
            self._build_replica()
        _fleets.add(self)
        self._sync_gauges()

    # -- replica lifecycle ----------------------------------------------

    def _build_replica(self):
        from .serve.engine import ServeEngine
        global _active
        rid = self._next_rid
        self._next_rid += 1
        eng = ServeEngine(self._model_factory(), **self._engine_kwargs)
        if self._current_params is not None:
            # a scale-out after a rolling update must serve the CURRENT
            # generation, not whatever the factory initialized
            eng.update_weights(self._current_params)
        if self._warmup:
            eng.warmup()
        plane = None
        if self._lease_dir:
            plane = _fleet.HealthPlane(
                rank=rid, nprocs=self.max_replicas,
                lease_dir=self._lease_dir).start()
        rep = Replica(rid, eng, plane)
        rep.generation = self._generation
        self._replicas[rid] = rep
        _watch[id(eng)] = rep
        _active = True
        return rep

    def _live(self):
        return [r for r in self._replicas.values() if r.state == "live"]

    def _parked(self):
        return [r for r in self._replicas.values() if r.state == "parked"]

    def _sync_gauges(self):
        _gauge("servefleet.replicas_live", len(self._live()))

    # -- routing + submission -------------------------------------------

    def submit(self, prompt, max_new_tokens=32, session=None, key=None,
               eos_id="engine", slo_class=None):
        """Accept one request under an idempotency ``key`` (generated
        when omitted) and route it by rendezvous hash of ``session``.
        A sessionless request routes by *prompt-prefix fingerprint* —
        the blake2b hash of its first ``serve.prefix_block`` tokens —
        so shared-prefix traffic converges on the replica whose radix
        prefix cache already holds those KV rows.  Re-submitting an
        accepted key returns the SAME :class:`FleetRequest` — the
        idempotent accept that makes client retries safe.  Raises
        :class:`~mxnet_tpu.serve.engine.EngineBusy` (with the max
        ``retry_after_hint`` across replicas) only when EVERY live
        replica rejects.  ``slo_class`` rides through to the engine's
        priority admission (serve.slo_classes)."""
        if key is None:
            key = f"req-{self._next_key}"
            self._next_key += 1
        key = str(key)
        if key in self._inflight:
            return self._inflight[key]
        if key in self._completed:
            return self._completed[key]
        import numpy as onp
        prompt = [int(t) for t in onp.asarray(prompt).reshape(-1)]
        if session is None:
            block = max(1, int(_config.get("serve.prefix_block")))
            h = hashlib.blake2b(
                ",".join(str(t) for t in prompt[:block]).encode(),
                digest_size=8)
            session = f"px-{h.hexdigest()}"
            _count("servefleet.prefix_routed_total")
        eos = (self._engine_kwargs.get("eos_id")
               if eos_id == "engine" else eos_id)
        fr = FleetRequest(key, session, prompt, max_new_tokens, eos,
                          slo_class=slo_class)
        self._dispatch(fr, queue_on_busy=False)
        self._inflight[key] = fr
        self._accepted_total += 1
        _count("servefleet.requests_total")
        return fr

    def _dispatch(self, fr, queue_on_busy=True):
        """Route ``fr`` to the best live replica (rendezvous order,
        spilling on EngineBusy).  With ``queue_on_busy`` an all-busy
        fleet parks the request in the overflow queue (retried every
        tick) instead of raising — a failover re-dispatch must never
        drop an accepted request."""
        from .serve.engine import EngineBusy
        live = self._live()
        if not live:
            # the last replica just died: queueing keeps the "never
            # drop an accepted request" promise — the supervisor tick
            # rebuilds capacity and retries the overflow queue
            if queue_on_busy:
                self._overflow.append(fr)
                return False
            raise MXNetError("servefleet: no live replicas "
                             f"(min_replicas={self.min_replicas})")
        last = None
        for rid in _route_order(fr.session, [r.rid for r in live]):
            rep = self._replicas[rid]
            try:
                req = rep.engine.submit(fr.prompt, fr.max_new_tokens,
                                        eos_id=fr.eos_id,
                                        slo_class=fr.slo_class)
            except EngineBusy as e:
                last = e if last is None or \
                    e.retry_after_hint > last.retry_after_hint else last
                continue
            fr.engine_req = req
            fr.replica_id = rid
            prev = self._session_map.get(fr.session)
            if prev is not None and prev != rid:
                _count("servefleet.router_moves_total")
            self._session_map[fr.session] = rid
            return True
        if queue_on_busy:
            self._overflow.append(fr)
            return False
        raise last

    # -- the supervisor tick --------------------------------------------

    def step(self):
        """One supervisor tick: probe the chaos points, retry overflow,
        advance every live replica one engine step, detect stalls and
        stale leases, collect completions into the ledger, run the
        autoscaler.  The fleet analog of ``ServeEngine.step`` — online
        callers own this loop."""
        self._tick += 1
        now = time.monotonic()
        if _fault._active:
            if _fault.fire("serve.replica_crash", step=self._tick):
                victim = self._victim()
                if victim is not None:
                    self._fail(victim, "crash")
            if _fault.fire("serve.replica_stall", step=self._tick):
                victim = self._victim()
                if victim is not None:
                    victim.wedged = True
                    _fault.record("servefleet.replica_wedged")
        self._check_leases()
        if not self._live() and self.pending:
            # every replica is dead but accepted work is still owed:
            # dead replicas are never revived — unpark or build a fresh
            # one so the overflow queue can drain
            self._scale_out(reason="fleet_dead")
        for _ in range(len(self._overflow)):
            fr = self._overflow.popleft()
            if not fr.done:
                self._dispatch(fr)
        for rep in self._live():
            if rep.wedged:
                continue  # the stall drill: lease fresh, loop frozen
            if rep.engine.pending:
                rep.engine.step()  # note_step() stamps rep.last_step
            else:
                rep.last_step = now  # idle is not a stall
        deadline = float(_config.get("servefleet.stall_deadline"))
        for rep in list(self._live()):
            if rep.engine.pending and \
                    time.monotonic() - rep.last_step > deadline:
                self._fail(rep, "stall")
        self._collect()
        self._autoscale()
        return self

    @property
    def pending(self):
        return bool(self._overflow) or \
            any(not fr.done for fr in self._inflight.values())

    def run(self, max_ticks=None, tick_interval=0.0):
        """Tick until every accepted request completed (or ``max_ticks``
        elapsed).  Completion is ledger-level: a request survives its
        replica dying mid-stream.  ``tick_interval`` paces the loop
        (seconds of sleep per tick) — wall-clock detectors like the
        ``servefleet.stall_deadline`` watchdog need real time to pass,
        not just iterations."""
        ticks = 0
        while self.pending:
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
            if tick_interval > 0:
                time.sleep(tick_interval)
        return self

    def _victim(self):
        """Pick the chaos victim deterministically: the live replica
        carrying the most work (fails the most interesting one)."""
        live = self._live()
        if not live:
            return None
        return max(live, key=lambda r: (
            sum(1 for s in r.engine._slots if s is not None)
            + len(r.engine._queue), -r.rid))

    # -- failover --------------------------------------------------------

    def _check_leases(self):
        """A live replica whose lease file is stale past the plane
        timeout is a detected crash — the multi-host analog of
        ``fleet.host_loss``, driven by the same file-backed lease."""
        if not self._lease_dir:
            return
        timeout = float(_config.get("fleet.lease_timeout"))
        for rep in list(self._live()):
            if rep.plane is not None:
                timeout = rep.plane.timeout
            path = os.path.join(self._lease_dir,
                                f"host-{rep.rid}.lease")
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue  # never published / torn mid-write: not proof
            if time.time() - float(payload.get("time", 0)) > timeout:
                _count("fleet.lease_expiries_total")
                self._fail(rep, "crash")

    def _fail(self, rep, cause):
        """Declare ``rep`` dead and make its work whole: re-dispatch
        every incomplete request to a survivor under its idempotency
        key, THEN (stall only) drain the dead engine's already-
        dispatched device work — deliberately after, so a late orphan
        completion races its own re-dispatch and the ledger's dedupe is
        exercised for real, not just in theory.  A crash drops the
        window outright: the KV and in-flight emits died with the
        host."""
        if rep.state == "dead":
            return
        with _trace.span("servefleet.failover", category="servefleet",
                         replica=rep.rid, cause=cause):
            rep.state = "dead"
            rep.wedged = False
            _count("servefleet.failovers_total", cause=cause)
            _fault.record(f"servefleet.failover_{cause}")
            if rep.plane is not None:
                rep.plane.stop()
            victims = [fr for fr in self._inflight.values()
                       if not fr.done and fr.replica_id == rep.rid]
            for fr in victims:
                orphan = fr.engine_req
                fr.engine_req = None
                if cause == "stall" and orphan is not None:
                    fr.orphans.append(orphan)
                fr.redispatches += 1
                self._redispatched_total += 1
                self._dispatch(fr)
                _count("servefleet.redispatched_total")
            if not self._live():
                # the whole group is down; victims sit safely in the
                # overflow queue and the next tick rebuilds capacity —
                # record the condition once rather than raising out of
                # the victims loop with failover half-done
                _fault.record("servefleet.fleet_dead")
            if cause == "stall":
                # flush what the wedged engine had already dispatched:
                # orphans may complete here and beat their re-dispatch
                rep.engine.drain()
            self._collect()
            # anything a dead-and-drained replica didn't finish never
            # will — stop watching those orphans
            for fr in victims:
                fr.orphans = [o for o in fr.orphans if o.finished]
        self._sync_gauges()

    # -- the exactly-once ledger ----------------------------------------

    def _record(self, fr, ereq):
        if fr.tokens is None:
            fr.tokens = list(ereq.generated)
            fr.t_done = time.monotonic()
            self._completed_total += 1
            _count("servefleet.completed_total")
        else:
            _count("servefleet.duplicates_suppressed_total")

    def _collect(self):
        """Sweep engine-level completions into the fleet ledger.  First
        finish wins; every later finish of the same key (an orphan or a
        raced re-dispatch) is counted suppressed and discarded.  A
        request with no engine-level copy left in flight settles into
        the capped completed LRU (``servefleet.ledger_retain``) so the
        per-tick sweep only ever walks genuinely open work."""
        retain = max(0, int(_config.get("servefleet.ledger_retain")))
        settled = []
        for fr in self._inflight.values():
            req = fr.engine_req
            if req is not None and req.finished:
                self._record(fr, req)
                fr.engine_req = None
            if fr.orphans:
                still = []
                for o in fr.orphans:
                    if o.finished:
                        self._record(fr, o)
                    else:
                        still.append(o)
                fr.orphans = still
            # done with no copy still running anywhere: nothing left to
            # suppress, safe to leave the hot sweep
            if fr.done and fr.engine_req is None and not fr.orphans:
                settled.append(fr.key)
        for key in settled:
            self._completed[key] = self._inflight.pop(key)
            self._completed.move_to_end(key)
        while len(self._completed) > retain:
            self._completed.popitem(last=False)

    # -- rolling weight updates -----------------------------------------

    def rolling_update(self, params, canary=None):
        """Roll every live replica to ``params`` (a flat
        ``{name: array}`` tree, e.g. a training fleet's published
        checkpoint) one replica at a time, never dropping the group
        below ``servefleet.min_replicas`` live replicas.

        Per replica, inside a goodput ``rollover`` bracket: mark
        ``updating`` (router excludes it), ``stop(drain=True)`` (every
        accepted request on it finishes under the OLD weights —
        generations never mix inside one request), swap weights in
        place, ``resume()`` + ``warmup()`` (an executable-cache hit:
        zero compiles), then replay the ``canary`` card's pinned
        prompts greedily and compare token-for-token.  Divergence or
        any post-warmup compile restores the old weights, counts
        ``servefleet.rollbacks_total`` and ABORTS the rollout, so a bad
        checkpoint stops at one replica and the fleet keeps serving the
        old generation everywhere.

        ``canary`` is a card from :func:`canary_card` /
        :func:`publish_checkpoint`: ``{"prompts": [...], "expected":
        [[tok, ...], ...], "tokens": n}``.  Returns a report dict;
        ``report["rolled_back"]`` tells the publisher its checkpoint
        was rejected."""
        params = dict(params)
        if canary is not None:
            # validate the card and the engines UP FRONT, before any
            # replica is drained or its weights swapped: failing later
            # (inside _canary_check) would strand one replica live on
            # un-canaried new weights with no rollback
            if not isinstance(canary, dict) or \
                    "prompts" not in canary or "expected" not in canary:
                raise MXNetError(
                    "rolling_update canary must be a canary_card dict "
                    "with 'prompts' and 'expected'")
            hot = [r.rid for r in self._replicas.values()
                   if r.state in ("live", "parked", "updating")
                   and r.engine.temperature != 0]
            if hot:
                raise MXNetError(
                    "canary parity requires greedy decoding "
                    "(temperature=0); build the fleet engines greedy "
                    f"or pass canary=None (sampling replicas: {hot})")
        target = self._generation + 1
        updated, report = [], None
        # re-derive the worklist every iteration instead of snapshotting
        # it: a replica added or unparked mid-rollout (the floor-guard
        # _scale_out below) comes up on the OLD generation and must be
        # rolled too — a successful rollout leaves EVERY live replica on
        # the new generation, never a silent mix
        while report is None:
            stale = [r for r in self._live() if r.generation < target]
            if not stale:
                break
            rep = stale[0]
            if len(self._live()) - 1 < self.min_replicas:
                # taking this replica out for the update would breach
                # the floor: bring capacity up first or refuse
                if self._scale_out(reason="rolling_update") is None:
                    raise MXNetError(
                        "rolling_update would drop the group below "
                        f"servefleet.min_replicas={self.min_replicas} "
                        "and no scale-out capacity remains")
            tok = _goodput.begin("rollover") if _goodput._active else None
            with _trace.span("servefleet.rolling_update",
                             category="servefleet", replica=rep.rid,
                             generation=self._generation + 1):
                try:
                    rep.state = "updating"
                    self._sync_gauges()
                    rep.engine.stop(drain=True)
                    self._collect()
                    before = rep.engine.post_warmup_compiles
                    old = rep.engine.update_weights(params)
                    rep.engine.resume()
                    rep.engine.warmup()
                    ok = rep.engine.post_warmup_compiles == before
                    reason = None if ok else "post_warmup_compiles"
                    if ok and canary is not None:
                        ok, reason = self._canary_check(rep, canary)
                    if not ok:
                        rep.engine.restore_weights(old)
                        _count("servefleet.rollbacks_total")
                        _fault.record("servefleet.rollback")
                        report = {"updated": updated, "rolled_back": True,
                                  "replica": rep.rid, "reason": reason}
                        break
                    rep.generation = target
                    _count("servefleet.rolling_updates_total")
                    updated.append(rep.rid)
                finally:
                    rep.state = "live" if rep.state == "updating" \
                        else rep.state
                    self._sync_gauges()
                    _goodput.end(tok)
        if report is None:
            self._generation = target
            self._current_params = params
            report = {"updated": updated, "rolled_back": False,
                      "generation": self._generation}
        return report

    def _canary_check(self, rep, canary):
        """Greedy parity on the pinned prompts: the new weights must
        reproduce the checkpoint's canary card token-for-token.

        Never raises: ``rolling_update`` validated the card and engine
        temperatures before touching any replica, so a failure here is
        a verdict — returned as ``(False, reason)`` and routed through
        the normal restore_weights rollback path, never an exception
        that would strand the replica on un-canaried weights."""
        if rep.engine.temperature != 0:
            return False, (
                f"replica {rep.rid} engine is sampling "
                "(temperature != 0); canary parity requires greedy "
                "decoding")
        n = int(canary.get("tokens")
                or _config.get("servefleet.canary_tokens"))
        for prompt, expected in zip(canary["prompts"],
                                    canary["expected"]):
            req = rep.engine.submit(prompt, max_new_tokens=n)
            rep.engine.run()
            if list(req.generated) != list(expected):
                return False, (
                    f"canary diverged on replica {rep.rid}: "
                    f"{list(req.generated)} != {list(expected)}")
        return True, None

    # -- SLO-driven scaling ---------------------------------------------

    def _autoscale(self):
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        patience = max(1, int(_config.get("servefleet.scale_patience")))
        thresh = float(_config.get("goodput.burn_threshold"))
        live = self._live()
        if not live:
            return
        burns = [max(r.engine.slo_burn().values() or [0.0])
                 for r in live]
        if max(burns) > thresh:
            self._burn_ticks += 1
        else:
            self._burn_ticks = 0
        if self._burn_ticks >= patience:
            self._burn_ticks = 0
            if self._scale_out(reason="slo_burn") is not None:
                self._cooldown = patience
            return
        floor = float(_config.get("servefleet.occupancy_floor"))
        occ = sum(r.occupancy() for r in live) / len(live)
        if occ < floor and len(live) > self.min_replicas \
                and not self.pending:
            self._idle_ticks += 1
        else:
            self._idle_ticks = 0
        if self._idle_ticks >= patience:
            self._idle_ticks = 0
            if self._scale_in() is not None:
                self._cooldown = patience

    def _scale_out(self, reason="slo_burn"):
        """Add capacity: unpark a drained replica (instant — its grid
        is still hot) before building a fresh one, bounded by
        ``servefleet.max_replicas``.  Returns the replica or None."""
        with _trace.span("servefleet.scale", category="servefleet",
                         dir="out", reason=reason):
            parked = self._parked()
            if parked:
                rep = parked[0]
                rep.engine.resume()
                if rep.generation != self._generation and \
                        self._current_params is not None:
                    # parked through a completed rolling update: bring
                    # it onto the current generation before it takes
                    # traffic (mid-rollout unparks keep the old weights
                    # and are rolled by the update's own worklist)
                    rep.engine.update_weights(self._current_params)
                    rep.generation = self._generation
                if rep.plane is not None:
                    rep.plane.start()
                rep.state = "live"
                rep.last_step = time.monotonic()
            elif len(self._live()) < self.max_replicas:
                rep = self._build_replica()
            else:
                return None
            _count("servefleet.scale_events_total", dir="out")
            self._scale_events["out"] += 1
            self._sync_gauges()
            return rep

    def _scale_in(self):
        """Drain and park the least-occupied live replica (engine and
        compiled grid kept warm; lease withdrawn).  Refuses below
        ``servefleet.min_replicas``.  Returns the replica or None."""
        live = self._live()
        if len(live) <= self.min_replicas:
            return None
        with _trace.span("servefleet.scale", category="servefleet",
                         dir="in"):
            rep = min(live, key=lambda r: (r.occupancy(), r.rid))
            rep.state = "parked"
            rep.engine.stop(drain=True)
            self._collect()
            if rep.plane is not None:
                rep.plane.stop()
            _count("servefleet.scale_events_total", dir="in")
            self._scale_events["in"] += 1
            self._sync_gauges()
            return rep

    # -- reporting / shutdown -------------------------------------------

    def report(self):
        return {
            "replicas": [r.snapshot() for r in self._replicas.values()],
            "live": len(self._live()),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "generation": self._generation,
            "requests": self._accepted_total,
            "completed": self._completed_total,
            "pending": self._accepted_total - self._completed_total,
            "overflow": len(self._overflow),
            "redispatched": self._redispatched_total,
            "ledger_retained": len(self._completed),
            "sessions": len(self._session_map),
            "scale_events": dict(self._scale_events),
            "ticks": self._tick,
        }

    def close(self, drain=False):
        """Tear the group down: stop every lease, stop every engine
        (``drain=True`` finishes accepted work first), detach the
        step-progress watch.  The module hot-path gate drops back to
        False when the last fleet closes."""
        global _active
        if drain:
            self.run()
        for rep in self._replicas.values():
            if rep.plane is not None:
                rep.plane.stop()
            if rep.state != "dead":
                try:
                    rep.engine.stop(drain=False)
                except Exception:  # noqa: BLE001 - teardown is best-effort
                    pass
            _watch.pop(id(rep.engine), None)
        self._replicas.clear()
        _fleets.discard(self)
        _active = bool(_watch)
        _gauge("servefleet.replicas_live", 0)
        return self


# ---------------------------------------------------------------------------
# canary cards + staged checkpoint publish
# ---------------------------------------------------------------------------

def canary_card(model_or_engine, prompts, tokens=None, **engine_kwargs):
    """Compute the greedy-parity card a rolling update validates
    against: for each pinned prompt, the exact token ids the published
    weights generate greedily.  The publisher runs this ONCE per
    checkpoint (a scratch engine's compiles are warmup compiles, not
    serving-path compiles) and ships the card in the checkpoint
    manifest."""
    from .serve.engine import ServeEngine
    n = int(tokens if tokens is not None
            else _config.get("servefleet.canary_tokens"))
    eng = model_or_engine
    if not isinstance(eng, ServeEngine):
        engine_kwargs.setdefault("temperature", 0.0)
        eng = ServeEngine(model_or_engine, **engine_kwargs)
    if eng.temperature != 0:
        raise MXNetError("canary_card requires greedy decoding "
                         "(temperature=0)")
    expected = []
    for prompt in prompts:
        req = eng.submit(prompt, max_new_tokens=n)
        eng.run()
        expected.append([int(t) for t in req.generated])
    return {"prompts": [list(map(int, p)) for p in prompts],
            "tokens": n, "expected": expected}


#: per-process publish counter — makes every versioned data directory
#: name unique (pid disambiguates across processes)
_publish_seq = itertools.count()


def publish_checkpoint(path, params, canary=None, step=None):
    """Staged checkpoint publish for serving fleets: write the flat
    param tree + manifest into a versioned data directory
    (``<path>.g<pid>.<seq>``), fsync, then atomically swap a symlink at
    ``path`` over it (``os.replace`` of a prepared link is ONE rename)
    — a replica polling ``path`` resolves either the previous complete
    checkpoint or the new complete one; ``path`` is never missing and
    never a torn directory, however the reader races the publisher.
    The superseded data directory is removed after the swap.  ``canary``
    (a :func:`canary_card` dict) rides in the manifest so every
    consumer validates against the SAME pinned outputs."""
    import jax
    import numpy as onp
    import shutil
    path = str(path)
    data = f"{path}.g{os.getpid()}.{next(_publish_seq)}"
    os.makedirs(data, exist_ok=True)
    arrays = {k: onp.asarray(jax.device_get(v))
              for k, v in dict(params).items()}
    onp.savez(os.path.join(data, "params.npz"), **arrays)
    manifest = {"format": CHECKPOINT_FORMAT, "step": step,
                "params": sorted(arrays), "canary": canary}
    mpath = os.path.join(data, "manifest.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    # prepare the link first, then swap: the replace is the publish
    lnk = f"{path}.lnk.{os.getpid()}"
    if os.path.lexists(lnk):
        os.remove(lnk)
    os.symlink(os.path.basename(data), lnk)
    prev = None
    if os.path.islink(path):
        prev = os.path.join(os.path.dirname(path) or ".",
                            os.readlink(path))
    elif os.path.isdir(path):
        # legacy in-place directory (pre-symlink layout): a link can't
        # be renamed over a real directory, so move it aside first —
        # the only case with a (syscall-wide) missing window, which
        # load_checkpoint's bounded retry absorbs; every publish from
        # here on leaves a symlink and swaps atomically
        prev = f"{path}.g{os.getpid()}.legacy{next(_publish_seq)}"
        os.rename(path, prev)
    os.replace(lnk, path)
    if prev is not None:
        shutil.rmtree(prev, ignore_errors=True)
    return path


def load_checkpoint(path):
    """-> ``(params, canary)`` from a :func:`publish_checkpoint`
    directory.  Raises :class:`MXNetError` on a missing or
    wrong-format manifest (a torn publish can never look valid: the
    link swap is atomic, so a readable manifest implies complete
    params).  A transiently missing manifest is retried briefly before
    failing — the one racy window left is a publisher migrating a
    legacy pre-symlink checkpoint directory into the versioned
    layout."""
    import jax.numpy as jnp
    import numpy as onp
    mpath = os.path.join(str(path), "manifest.json")
    manifest, err = None, None
    for _ in range(3):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            break
        except FileNotFoundError as e:
            err = e
            time.sleep(0.01)
        except (OSError, ValueError) as e:
            raise MXNetError(
                f"unreadable checkpoint manifest {mpath}: {e}") from e
    if manifest is None:
        raise MXNetError(
            f"unreadable checkpoint manifest {mpath}: {err}") from err
    if manifest.get("format") != CHECKPOINT_FORMAT:
        raise MXNetError(
            f"checkpoint {path} has format {manifest.get('format')!r}, "
            f"expected {CHECKPOINT_FORMAT!r}")
    data = onp.load(os.path.join(str(path), "params.npz"))
    params = {k: jnp.asarray(data[k]) for k in data.files}
    return params, manifest.get("canary")


def endpoint_report():
    """The /servefleet ops endpoint payload: one report per live fleet
    group in this process."""
    return {"active": _active,
            "fleets": [f.report() for f in list(_fleets)]}

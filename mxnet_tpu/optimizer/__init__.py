"""mx.optimizer (reference: python/mxnet/optimizer/).

Optimizer registry + the reference's optimizer set. Each update rule is a
pure jitted function (weight, grad, states, scalar hypers) -> (new weight,
new states) — the analog of the fused update ops in
src/operator/optimizer_op.cc (sgd_update, adam_update, lamb_update_phase1/2),
with XLA doing the fusion that the reference hand-writes in CUDA.
"""
from .optimizer import (  # noqa: F401
    Optimizer, register, create, Updater, get_updater, Test,
    SGD, SGLD, Signum, NAG, Adam, AdamW, AdaBelief, AdaGrad, AdaDelta,
    RMSProp, Ftrl, LAMB, LARS, LANS, Nadam, DCASGD, Adamax, FTML,
)
from . import contrib  # noqa: F401
from .contrib import GroupAdaGrad  # noqa: F401

"""Optimizers.

Reference parity: python/mxnet/optimizer/optimizer.py (base :91-140, registry,
aggregate_num multi-tensor batching) + per-optimizer files (sgd.py, adam.py,
adamw.py, lamb.py, lars.py, ...). Fused multi-tensor updates (the reference's
multi_sgd_update / multi_lamb, src/operator/optimizer_op.cc:352-1130) are
subsumed here by jitting one update per parameter — XLA fuses the arithmetic;
Trainer additionally batches updates into one dispatch window.

State layout matches the reference (e.g. Adam state = (mean, var)), so
Trainer.save_states/load_states round-trips.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError, _Registry
from ..numpy.multiarray import ndarray, _wrap

_registry = _Registry("optimizer")


def register(klass):
    _registry.register()(klass)
    return klass


def create(name, **kwargs):
    return _registry.get(name)(**kwargs)


class Optimizer:
    """Base optimizer (reference: optimizer.py:91)."""

    # Fused multi-tensor family (reference: multi_sgd_update / multi_mp_sgd /
    # multi_lamb, src/operator/optimizer_op.cc:352-1130). Classes whose
    # ``_rule`` is pure w.r.t. traced (lr, wd, t) opt in; Trainer then runs
    # ALL parameter updates as one jitted XLA program per step.
    #   "sgd":  _rule(w, g, mom,  lr, wd, momentum, rescale, clip)
    #   "adam": _rule(w, g, m, v, lr, wd, t, beta1, beta2, eps, rescale, clip)
    _FUSED_FAMILY = None

    # Whether the update rule is elementwise, i.e. computing it on an
    # arbitrary 1-D shard of the (weight, grad, state) tensors yields the
    # same values as on the whole tensor. ZeRO partitioning
    # (parallel.ShardedTrainStep(zero=...)) requires this; layer-norm-scaled
    # rules (LAMB/LANS/LARS: jnp.linalg.norm over the full layer) and rules
    # drawing fresh host RNG per tensor (SGLD) opt out.
    _zero_partitionable = True

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=1, use_fused_step=True,
                 **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        self.param_dict = param_dict or {}
        self.idx2name = param_idx2name or {}
        self.lr_mult = {}
        self.wd_mult = {}
        self._master_weights = {}

    # -- bookkeeping (reference: optimizer.py _update_count/learning_rate) --
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        p = self.param_dict.get(index)
        if p is not None:
            lr *= p.lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        p = self.param_dict.get(index)
        if p is not None:
            wd *= p.wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            self.lr_scheduler.base_lr = lr
        self.lr = lr

    @property
    def learning_rate(self):
        return self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr

    @staticmethod
    def _bc_t(t):
        """Bias-correction step count as fed to update rules: a python float
        in the eager path, a traced f32 scalar when the compiled train step
        (parallel.ShardedTrainStep) threads the count through the jit
        boundary so warmup/bias correction advance without retracing."""
        if isinstance(t, jax.Array):
            return jnp.maximum(t.astype(jnp.float32), 1.0)
        return float(max(t, 1))

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master = _wrap(weight._data.astype(jnp.float32))
            self._master_weights[index] = master
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    # -- update ------------------------------------------------------------
    def _prep_grad(self, g):
        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def step(self, indices, weights, grads, states):
        for i, w, g, s in zip(indices, weights, grads, states):
            self.update(i, w, g, s)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if getattr(self, "lazy_update", False):
                # reference: sgd.py lazy_update=True — only rows present in
                # the sparse grad are read/updated (O(nnz) work)
                new_w, new_s = self._lazy_update_impl(
                    weight._data, grad, state, lr, wd)
                weight._rebind(new_w.astype(weight.dtype))
                return new_s
            grad = grad.tostype("default")  # standard update: densify
        new_w, new_s = self._update_impl(
            weight._data, grad._data, state, lr, wd)
        weight._rebind(new_w.astype(weight.dtype))
        return new_s

    def _lazy_update_impl(self, w, rsp_grad, state, lr, wd):
        raise NotImplementedError(
            f"{type(self).__name__} has no lazy sparse update; use "
            "lazy_update=False to densify row_sparse gradients")

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master, inner = state
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            from ..ndarray.sparse import RowSparseNDArray
            if isinstance(grad, RowSparseNDArray):
                if getattr(self, "lazy_update", False):
                    new_w, new_s = self._lazy_update_impl(
                        master._data, grad.astype(jnp.float32), inner, lr, wd)
                    master._rebind(new_w)
                    weight._rebind(new_w.astype(weight.dtype))
                    return (master, new_s)
                grad = grad.tostype("default")
            new_w, new_s = self._update_impl(
                master._data, grad._data.astype(jnp.float32), inner, lr, wd)
            master._rebind(new_w)
            weight._rebind(new_w.astype(weight.dtype))
            return (master, new_s)
        return self.update(index, weight, grad, state)

    def _update_impl(self, w, g, state, lr, wd):
        """Return (new_weight_raw, new_state). state entries are ndarrays
        (mutated by _rebind) so Updater state dicts serialize like the
        reference's."""
        raise NotImplementedError


def _jit_rule(fn):
    # Update rules stay un-jitted at this layer: hyperparameters arrive as
    # python scalars used in python control flow. The jit boundary for
    # training is the whole train step (hybridized forward/backward +
    # Trainer's batched update dispatch); XLA fuses the update arithmetic
    # there, which is the analog of the reference's fused optimizer kernels.
    return staticmethod(fn).__func__ if isinstance(fn, staticmethod) else fn


@register
class Test(Optimizer):
    """reference: optimizer.py Test optimizer (for kvstore tests)."""

    def create_state(self, index, weight):
        return _wrap(jnp.zeros(weight.shape, weight.dtype))

    def _update_impl(self, w, g, state, lr, wd):
        new = w + g * self.rescale_grad
        state._rebind(new)
        return new, state


@register
class SGD(Optimizer):
    """Reference: optimizer/sgd.py over optimizer_op.cc sgd_update /
    sgd_mom_update: state = momentum buffer."""

    _FUSED_FAMILY = "sgd"

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _wrap(jnp.zeros(weight.shape, weight.dtype))

    @staticmethod
    @_jit_rule
    def _rule(w, g, mom, lr, wd, momentum, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        if mom is None:
            return w - lr * g, None
        new_mom = momentum * mom - lr * g
        return w + new_mom, new_mom

    def _update_impl(self, w, g, state, lr, wd):
        mom = state._data if state is not None else None
        new_w, new_mom = self._rule(w, g, mom, lr, wd, self.momentum,
                                    self.rescale_grad,
                                    self.clip_gradient or -1.0)
        if state is not None:
            state._rebind(new_mom)
        return new_w, state

    def _lazy_update_impl(self, w, rsp, state, lr, wd):
        """Row-wise sgd(_mom) touching only rsp.indices rows (reference:
        sgd.py lazy_update over optimizer_op.cc SGDUpdateRspImpl).  Sentinel
        padding rows (index == n_rows, see sparse.dedupe_coo) drop out of
        the scatters."""
        idx = rsp.indices._data
        g = rsp.data._data.astype(w.dtype) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w_rows = jnp.take(w, idx, axis=0, mode="clip")
        g = g + wd * w_rows
        if state is None:
            return w.at[idx].set(w_rows - lr * g, mode="drop"), None
        mom_rows = jnp.take(state._data, idx, axis=0, mode="clip")
        new_mom_rows = self.momentum * mom_rows - lr * g
        state._rebind(state._data.at[idx].set(new_mom_rows, mode="drop"))
        return w.at[idx].set(w_rows + new_mom_rows, mode="drop"), state


@register
class NAG(SGD):
    """Nesterov SGD (reference: optimizer/nag.py)."""

    @staticmethod
    @_jit_rule
    def _rule(w, g, mom, lr, wd, momentum, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        if mom is None:
            return w - lr * g, None
        new_mom = momentum * mom + g
        return w - lr * (g + momentum * new_mom), new_mom


@register
class Signum(Optimizer):
    """Reference: optimizer/sgd.py Signum (sign of momentum step)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _wrap(jnp.zeros(weight.shape, weight.dtype))

    def _update_impl(self, w, g, state, lr, wd):
        g = self._prep_grad(g)
        if state is not None:
            mom = self.momentum * state._data - (1 - self.momentum) * g
            new_w = (1 - lr * self.wd_lh) * w + lr * jnp.sign(mom) - lr * wd * w
            state._rebind(mom)
            return new_w, state
        return (1 - lr * self.wd_lh) * w - lr * jnp.sign(g + wd * w), None


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer/sgld.py)."""

    _zero_partitionable = False  # fresh host RNG per full tensor

    def _update_impl(self, w, g, state, lr, wd):
        from .. import random as _random
        g = self._prep_grad(g) + wd * w
        noise = jax.random.normal(_random._next_key(), w.shape, w.dtype) \
            * jnp.sqrt(lr)
        return w - 0.5 * lr * g + noise, state


@register
class Adam(Optimizer):
    """Reference: optimizer/adam.py over adam_update (optimizer_op.cc)."""

    _FUSED_FAMILY = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (_wrap(jnp.zeros(weight.shape, weight.dtype)),
                _wrap(jnp.zeros(weight.shape, weight.dtype)))

    @staticmethod
    @_jit_rule
    def _rule(w, g, m, v, lr, wd, t, beta1, beta2, eps, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        lr_t = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        return w - lr_t * m / (jnp.sqrt(v) + eps), m, v

    def _update_impl(self, w, g, state, lr, wd):
        m, v = state
        t = self._index_update_count.get(self._cur_index, self.num_update) \
            if hasattr(self, "_cur_index") else self.num_update
        new_w, nm, nv = self._rule(w, g, m._data, v._data, lr, wd,
                                   self._bc_t(t), self.beta1, self.beta2,
                                   self.epsilon, self.rescale_grad,
                                   self.clip_gradient or -1.0)
        m._rebind(nm)
        v._rebind(nv)
        return new_w, state

    def update(self, index, weight, grad, state):
        self._cur_index = index
        try:
            return super().update(index, weight, grad, state)
        finally:
            del self._cur_index

    def _lazy_update_impl(self, w, rsp, state, lr, wd):
        """Row-wise adam on grad rows only (reference: adam.py
        lazy_update over AdamUpdateRspImpl: m/v of untouched rows stay)."""
        m, v = state
        t = self._index_update_count.get(self._cur_index, self.num_update) \
            if hasattr(self, "_cur_index") else self.num_update
        t = float(max(t, 1))
        idx = rsp.indices._data
        g = rsp.data._data.astype(w.dtype) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w_rows = jnp.take(w, idx, axis=0, mode="clip")
        g = g + wd * w_rows
        m_rows = self.beta1 * jnp.take(m._data, idx, 0, mode="clip") \
            + (1 - self.beta1) * g
        v_rows = self.beta2 * jnp.take(v._data, idx, 0, mode="clip") \
            + (1 - self.beta2) * g * g
        lr_t = lr * jnp.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        new_rows = w_rows - lr_t * m_rows / (jnp.sqrt(v_rows) + self.epsilon)
        m._rebind(m._data.at[idx].set(m_rows, mode="drop"))
        v._rebind(v._data.at[idx].set(v_rows, mode="drop"))
        return w.at[idx].set(new_rows, mode="drop"), state


@register
class AdamW(Adam):
    """Decoupled weight decay (reference: optimizer/adamw.py / contrib
    adamw.cc fused op)."""

    @staticmethod
    @_jit_rule
    def _rule(w, g, m, v, lr, wd, t, beta1, beta2, eps, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
        return w - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * w), m, v


@register
class Adamax(Adam):
    """AdaMax: Adam with the infinity norm (Kingma 2014 §7; reference:
    optimizer/adamax.py). u tracks max(beta2*u, |g|) instead of the
    second moment."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kwargs)

    @staticmethod
    @_jit_rule
    def _rule(w, g, m, u, lr, wd, t, beta1, beta2, eps, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        u = jnp.maximum(beta2 * u, jnp.abs(g))
        return w - lr / (1 - beta1 ** t) * m / (u + eps), m, u

    def _lazy_update_impl(self, w, rsp_grad, state, lr, wd):
        # Adam's row-wise lazy rule would misuse the infinity-norm state
        raise NotImplementedError(
            "Adamax has no lazy sparse update; use lazy_update=False")


@register
class FTML(Optimizer):
    """Follow The Moving Leader (Zheng & Kwok 2017; reference:
    optimizer/ftml.py over FTMLKernel, src/operator/optimizer_op-inl.h:1256).
    States: (prev_d, v, z)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_wrap(jnp.zeros(weight.shape, weight.dtype)),   # d
                _wrap(jnp.zeros(weight.shape, weight.dtype)),   # v
                _wrap(jnp.zeros(weight.shape, weight.dtype)))   # z

    @staticmethod
    @_jit_rule
    def _rule(w, g, d, v, z, lr, wd, t, beta1, beta2, eps, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        v = beta2 * v + (1 - beta2) * g * g
        d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(v / (1 - beta2 ** t)) + eps)
        z = beta1 * z + (1 - beta1) * g - (d_t - beta1 * d) * w
        return -z / d_t, d_t, v, z

    def _update_impl(self, w, g, state, lr, wd):
        d, v, z = state
        t = self._index_update_count.get(self._cur_index, self.num_update) \
            if hasattr(self, "_cur_index") else self.num_update
        new_w, nd, nv, nz = self._rule(w, g, d._data, v._data, z._data, lr,
                                       wd, self._bc_t(t), self.beta1,
                                       self.beta2, self.epsilon,
                                       self.rescale_grad,
                                       self.clip_gradient or -1.0)
        d._rebind(nd)
        v._rebind(nv)
        z._rebind(nz)
        return new_w, state

    def update(self, index, weight, grad, state):
        self._cur_index = index
        try:
            return super().update(index, weight, grad, state)
        finally:
            del self._cur_index


@register
class AdaBelief(Adam):
    """Reference: optimizer/adabelief.py (variance of surprise)."""

    @staticmethod
    @_jit_rule
    def _rule(w, g, m, v, lr, wd, t, beta1, beta2, eps, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * (g - m) ** 2 + eps
        lr_t = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        return w - lr_t * m / (jnp.sqrt(v) + eps), m, v


@register
class Nadam(Adam):
    """Reference: optimizer/nadam.py."""

    @staticmethod
    @_jit_rule
    def _rule(w, g, m, v, lr, wd, t, beta1, beta2, eps, rescale, clip):
        g = g * rescale
        g = jnp.clip(g, -clip, clip) if clip == clip and clip > 0 else g
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
        m_bar = beta1 * mhat + (1 - beta1) * g / (1 - beta1 ** t)
        return w - lr * m_bar / (jnp.sqrt(vhat) + eps), m, v


@register
class AdaGrad(Optimizer):
    """Reference: optimizer/adagrad.py."""

    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return _wrap(jnp.zeros(weight.shape, weight.dtype))

    def _update_impl(self, w, g, state, lr, wd):
        g = self._prep_grad(g) + wd * w
        hist = state._data + g * g
        state._rebind(hist)
        return w - lr * g / (jnp.sqrt(hist) + self.epsilon), state


@register
class AdaDelta(Optimizer):
    """Reference: optimizer/adadelta.py."""

    def __init__(self, learning_rate=1.0, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_wrap(jnp.zeros(weight.shape, weight.dtype)),
                _wrap(jnp.zeros(weight.shape, weight.dtype)))

    def _update_impl(self, w, g, state, lr, wd):
        acc_g, acc_d = state
        g = self._prep_grad(g) + wd * w
        ag = self.rho * acc_g._data + (1 - self.rho) * g * g
        delta = jnp.sqrt(acc_d._data + self.epsilon) / \
            jnp.sqrt(ag + self.epsilon) * g
        ad = self.rho * acc_d._data + (1 - self.rho) * delta * delta
        acc_g._rebind(ag)
        acc_d._rebind(ad)
        return w - lr * delta, state


@register
class RMSProp(Optimizer):
    """Reference: optimizer/rmsprop.py (centered=Graves variant supported)."""

    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum = rho, momentum
        self.epsilon, self.centered = epsilon, centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return tuple(_wrap(jnp.zeros(weight.shape, weight.dtype))
                         for _ in range(3))
        return _wrap(jnp.zeros(weight.shape, weight.dtype))

    def _update_impl(self, w, g, state, lr, wd):
        g = self._prep_grad(g) + wd * w
        if self.centered:
            n, mg, delta = state
            nn = self.rho * n._data + (1 - self.rho) * g * g
            nmg = self.rho * mg._data + (1 - self.rho) * g
            nd = self.momentum * delta._data - lr * g / \
                jnp.sqrt(nn - nmg * nmg + self.epsilon)
            n._rebind(nn)
            mg._rebind(nmg)
            delta._rebind(nd)
            new_w = w + nd
        else:
            n = state
            nn = self.rho * n._data + (1 - self.rho) * g * g
            n._rebind(nn)
            new_w = w - lr * g / (jnp.sqrt(nn) + self.epsilon)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return new_w, state


@register
class Ftrl(Optimizer):
    """Reference: optimizer/ftrl.py."""

    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (_wrap(jnp.zeros(weight.shape, weight.dtype)),
                _wrap(jnp.zeros(weight.shape, weight.dtype)))

    def _update_impl(self, w, g, state, lr, wd):
        z, n = state
        g = self._prep_grad(g)
        sigma = (jnp.sqrt(n._data + g * g) - jnp.sqrt(n._data)) / lr
        nz = z._data + g - sigma * w
        nn = n._data + g * g
        z._rebind(nz)
        n._rebind(nn)
        new_w = jnp.where(
            jnp.abs(nz) <= self.lamda1, jnp.zeros_like(w),
            -(nz - jnp.sign(nz) * self.lamda1)
            / ((self.beta + jnp.sqrt(nn)) / lr + wd))
        return new_w, state


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments (reference: optimizer/lamb.py over
    lamb_update_phase1/2, optimizer_op.cc:1039-1130)."""

    _zero_partitionable = False  # layer-wise norms need the whole tensor

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_wrap(jnp.zeros(weight.shape, weight.dtype)),
                _wrap(jnp.zeros(weight.shape, weight.dtype)))

    def _update_impl(self, w, g, state, lr, wd):
        m, v = state
        t = self.num_update
        g = self._prep_grad(g)
        nm = self.beta1 * m._data + (1 - self.beta1) * g
        nv = self.beta2 * v._data + (1 - self.beta2) * g * g
        m._rebind(nm)
        v._rebind(nv)
        if self.bias_correction:
            mhat = nm / (1 - self.beta1 ** t)
            vhat = nv / (1 - self.beta2 ** t)
        else:
            mhat, vhat = nm, nv
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w
        w_norm = jnp.linalg.norm(w)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        if self.lower_bound is not None:
            ratio = jnp.maximum(ratio, self.lower_bound)
        if self.upper_bound is not None:
            ratio = jnp.minimum(ratio, self.upper_bound)
        return w - lr * ratio * r, state


@register
class LANS(LAMB):
    """Reference: optimizer/lans.py (normalized-gradient LAMB variant)."""

    def _update_impl(self, w, g, state, lr, wd):
        g_norm = jnp.linalg.norm(g)
        g = jnp.where(g_norm > 0, g / g_norm, g)
        return super()._update_impl(w, g, state, lr, wd)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference: optimizer/lars.py)."""

    _zero_partitionable = False  # trust ratio needs whole-tensor norms

    def __init__(self, learning_rate=0.1, momentum=0.0, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _wrap(jnp.zeros(weight.shape, weight.dtype))

    def _update_impl(self, w, g, state, lr, wd):
        g = self._prep_grad(g)
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where((w_norm > 0) & (g_norm > 0),
                          self.eta * w_norm / (g_norm + wd * w_norm
                                               + self.epsilon), 1.0)
        g = g + wd * w
        if state is not None:
            mom = self.momentum * state._data + lr * trust * g
            state._rebind(mom)
            return w - mom, state
        return w - lr * trust * g, None


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer/dcasgd.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        return (_wrap(jnp.zeros(weight.shape, weight.dtype)),
                _wrap(weight._data))

    def _update_impl(self, w, g, state, lr, wd):
        mom, prev_w = state
        g = self._prep_grad(g) + wd * w
        new_mom = self.momentum * mom._data - lr * (
            g + self.lamda * g * g * (w - prev_w._data))
        mom._rebind(new_mom)
        prev_w._rebind(w + new_mom)
        return w + new_mom, state


class Updater:
    """Applies per-key optimizer states (reference: optimizer/updater.py —
    runs on the kvstore server side for update_on_kvstore)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import copy
        import pickle
        serial = {}
        for k, s in self.states.items():
            serial[k] = jax.tree_util.tree_map(
                lambda a: a.asnumpy() if isinstance(a, ndarray) else a, s,
                is_leaf=lambda a: isinstance(a, ndarray))
        if dump_optimizer:
            opt_copy = copy.copy(self.optimizer)
            opt_copy.param_dict = {}  # live Parameters aren't serialized
            return pickle.dumps((serial, opt_copy))
        return pickle.dumps(serial)

    def set_states(self, states):
        import pickle
        data = pickle.loads(states)
        if isinstance(data, tuple):
            data, self.optimizer = data
        from ..numpy import array

        def _to_nd(a):
            return array(a) if isinstance(a, onp.ndarray) else a
        self.states = {
            k: jax.tree_util.tree_map(_to_nd, v) for k, v in data.items()}


def get_updater(optimizer):
    return Updater(optimizer)

"""Contrib optimizers.

Reference parity: python/mxnet/optimizer/contrib.py (GroupAdaGrad over
src/operator/contrib/optimizer_op.cc group_adagrad_update: AdaGrad with
one learning-rate history cell per ROW — the embedding-training
optimizer, O(rows) state instead of O(elements)).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..numpy.multiarray import _wrap
from .optimizer import Optimizer, register

__all__ = ["GroupAdaGrad"]


@register
class GroupAdaGrad(Optimizer):
    """AdaGrad with row-wise accumulators (reference contrib.py:26).

    update:
        history += mean(grad**2, axis=1, keepdims=True)
        weight  -= lr * grad / (sqrt(history) + epsilon)

    Weight decay is not supported (reference asserts the same).
    """

    def __init__(self, learning_rate=0.01, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon
        self.lazy_update = True  # sparse grads update touched rows only

    def create_state(self, index, weight):
        if len(weight.shape) != 2:
            raise MXNetError(
                "GroupAdaGrad expects 2-D (row-partitioned) weights, got "
                f"shape {tuple(weight.shape)}")
        return _wrap(jnp.zeros((weight.shape[0], 1), weight.dtype))

    def _update_impl(self, w, g, state, lr, wd):
        if wd != 0:
            raise MXNetError(
                "Weight decay is not supported for GroupAdaGrad")
        g = self._prep_grad(g)
        hist = state._data + jnp.mean(g * g, axis=1, keepdims=True)
        state._rebind(hist)
        return w - lr * g / (jnp.sqrt(hist) + self.epsilon), state

    def _lazy_update_impl(self, w, rsp, state, lr, wd):
        """O(nnz-rows) update for row-sparse gradients — the whole point
        of the row-wise history (reference group_adagrad_update sparse
        path). Sentinel padding rows drop out of the scatters."""
        if wd != 0:
            raise MXNetError(
                "Weight decay is not supported for GroupAdaGrad")
        idx = rsp.indices._data
        g = self._prep_grad(rsp.data._data.astype(w.dtype))
        hist_rows = jnp.take(state._data, idx, axis=0, mode="clip")
        hist_rows = hist_rows + jnp.mean(g * g, axis=1, keepdims=True)
        state._rebind(state._data.at[idx].set(hist_rows, mode="drop"))
        w_rows = jnp.take(w, idx, axis=0, mode="clip")
        new_rows = w_rows - lr * g / (jnp.sqrt(hist_rows) + self.epsilon)
        return w.at[idx].set(new_rows, mode="drop"), state

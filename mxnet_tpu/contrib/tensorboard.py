"""TensorBoard logging bridge.

Reference parity: python/mxnet/contrib/tensorboard.py — a thin
LogMetricsCallback that forwards `mx.gluon.metric` values to a
SummaryWriter.  Like the reference, the tensorboard package is imported
lazily and a clear error is raised when it is not installed.
"""
from __future__ import annotations


class LogMetricsCallback:
    """Log metric values each time the callback fires.

    Works as an epoch/batch-end callback: accepts either an object with
    ``.eval_metric`` (estimator-style) or an EvalMetric directly via
    ``__call__(metric)``.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        try:
            from tensorboard.summary import Writer  # type: ignore
            self.summary_writer = Writer(logging_dir)
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self.summary_writer = SummaryWriter(logging_dir)
            except ImportError as e:
                raise ImportError(
                    "LogMetricsCallback requires a tensorboard writer "
                    "(pip install tensorboard, or torch with tensorboard "
                    "support)") from e
        self.step = 0

    def __call__(self, param):
        metric = getattr(param, "eval_metric", param)
        if metric is None:
            return
        name_value = metric.get_name_value() \
            if hasattr(metric, "get_name_value") else [metric]
        self.step += 1
        for name, value in name_value:
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)

"""mx.contrib.text — vocabulary + pretrained token embeddings.

Reference parity: python/mxnet/contrib/text/ (vocab.py Vocabulary,
embedding.py TokenEmbedding/GloVe/FastText/CustomEmbedding,
utils.py count_tokens_from_str).  This environment has no egress, so the
named pretrained classes load from locally provisioned files under
``MXNET_HOME/embeddings/<cls>/`` instead of downloading.
"""
from __future__ import annotations

import io
import os
import re

import numpy as onp

from ..base import MXNetError


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Reference: text/utils.py count_tokens_from_str."""
    import collections
    source_str = re.sub(
        f"({re.escape(token_delim)})|({re.escape(seq_delim)})", " ",
        source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = (collections.Counter() if counter_to_update is None
               else counter_to_update)
    counter.update(source_str.split())
    return counter


class Vocabulary:
    """Indexed vocabulary from a token counter (reference: text/vocab.py).

    Index 0 is the unknown token; reserved tokens follow; then counted
    tokens by frequency (ties broken alphabetically)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens or \
                len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved tokens must be unique and must not "
                             "contain the unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            skip = set(self._idx_to_token)
            for tok, freq in pairs:
                if freq >= min_freq and tok not in skip:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"token index {i} out of range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out


class TokenEmbedding(Vocabulary):
    """Token -> vector table (reference: text/embedding.py TokenEmbedding).

    ``idx_to_vec`` is an mx ndarray (len(vocab), dim); unknown tokens map
    to ``init_unknown_vec`` (zeros by default)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._idx_to_vec = None
        self._vec_len = 0

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_txt(self, path, elem_delim=" ",
                            init_unknown_vec=onp.zeros, encoding="utf8"):
        tokens, vecs = [], []
        with io.open(path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2 and \
                        all(p.isdigit() for p in parts):
                    continue  # fastText header "count dim"
                if len(parts) < 3:
                    continue
                tokens.append(parts[0])
                vecs.append(onp.asarray([float(x) for x in parts[1:]],
                                        "float32"))
        if not tokens:
            raise MXNetError(f"no embedding vectors found in {path}")
        self._vec_len = len(vecs[0])
        table = {t: v for t, v in zip(tokens, vecs)}
        # extend the index with embedding tokens not already present
        for t in tokens:
            if t not in self._token_to_idx:
                self._token_to_idx[t] = len(self._idx_to_token)
                self._idx_to_token.append(t)
        mat = onp.stack(
            [table.get(t, init_unknown_vec(self._vec_len).astype("float32"))
             for t in self._idx_to_token])
        from ..numpy import array
        self._idx_to_vec = array(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t, 0)
            if i == 0 and lower_case_backup:
                i = self._token_to_idx.get(t.lower(), 0)
            idxs.append(i)
        vecs = self._idx_to_vec[onp.asarray(idxs)]
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        from ..numpy import array
        toks = [tokens] if isinstance(tokens, str) else tokens
        mat = onp.array(self._idx_to_vec.asnumpy())  # writable copy
        new = onp.asarray(new_vectors.asnumpy()
                          if hasattr(new_vectors, "asnumpy")
                          else new_vectors, "float32").reshape(len(toks), -1)
        for t, v in zip(toks, new):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is not in the vocabulary")
            mat[self._token_to_idx[t]] = v
        self._idx_to_vec = array(mat)


class CustomEmbedding(TokenEmbedding):
    """Embedding from a user text file: '<token> <v0> <v1> ...' per line
    (reference: embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=onp.zeros, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 init_unknown_vec, encoding)


class _ProvisionedEmbedding(TokenEmbedding):
    """Named pretrained source loading from MXNET_HOME/embeddings/<name>/
    (no egress here; the reference downloads from its repo)."""

    _source_dir = None

    def __init__(self, pretrained_file_name, init_unknown_vec=onp.zeros,
                 **kwargs):
        super().__init__(**kwargs)
        from .. import config
        root = os.path.join(os.path.expanduser(config.get("home")),
                            "embeddings", self._source_dir)
        path = os.path.join(root, pretrained_file_name)
        if not os.path.exists(path):
            raise MXNetError(
                f"pretrained embedding file {path} not found; this "
                "environment has no egress — provision the file offline")
        self._load_embedding_txt(path,
                                 init_unknown_vec=init_unknown_vec)


class GloVe(_ProvisionedEmbedding):
    _source_dir = "glove"


class FastText(_ProvisionedEmbedding):
    _source_dir = "fasttext"


def get_pretrained_file_names(embedding_name=None):
    """Reference: embedding.py get_pretrained_file_names — here it lists
    locally provisioned files."""
    from .. import config
    base = os.path.join(os.path.expanduser(config.get("home")), "embeddings")
    names = {"glove": [], "fasttext": []}
    for k in names:
        d = os.path.join(base, k)
        if os.path.isdir(d):
            names[k] = sorted(os.listdir(d))
    if embedding_name is not None:
        return names.get(embedding_name, [])
    return names

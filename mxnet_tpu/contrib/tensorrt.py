"""mx.contrib.tensorrt — pointer-stub (documented N/A on TPU).

Reference parity: python/mxnet/contrib/tensorrt.py (set_use_fp16 /
get_use_fp16 / init_tensorrt_params driving the TensorRT subgraph backend,
src/operator/subgraph/tensorrt/). TensorRT is a CUDA inference runtime;
the TPU-native equivalent of "hand the graph to an inference engine" is
XLA itself — use ``HybridBlock.optimize_for(backend=...)`` (gluon/block.py)
or AMP bf16 policies for reduced-precision inference. These functions keep
the import path alive and fail with that guidance.
"""
from ..base import MXNetError

_MSG = ("TensorRT is a CUDA-only inference runtime with no TPU analog; "
        "inference here is XLA-compiled already. Use "
        "HybridBlock.optimize_for(backend=...) for custom rewrite hooks "
        "or mx.amp for reduced-precision inference.")


def set_use_fp16(status):  # noqa: ARG001 — parity signature
    raise MXNetError(_MSG)


def get_use_fp16():
    raise MXNetError(_MSG)


def init_tensorrt_params(sym, arg_params, aux_params):  # noqa: ARG001
    raise MXNetError(_MSG)

"""mx.contrib — experimental / auxiliary drivers.

Reference parity: python/mxnet/contrib/ (quantization.py calibration
driver, tensorboard.py logging bridge, plus onnx/tensorrt drivers whose
roles live in mx.onnx and the XLA pipeline here).
"""
from . import quantization
from . import tensorboard
from . import text  # noqa: F401,E402 (vocab + pretrained embeddings)

"""mx.contrib — experimental / auxiliary drivers.

Reference parity: python/mxnet/contrib/ (quantization.py calibration
driver, tensorboard.py logging bridge, io.py DataLoaderIter, the
ndarray/symbol contrib op namespaces, and the onnx/tensorrt drivers whose
real implementations live in mx.onnx and the XLA pipeline here).
"""
from . import io
from . import ndarray
from . import onnx
from . import quantization
from . import symbol
from . import tensorboard
from . import tensorrt
from . import text  # noqa: F401,E402 (vocab + pretrained embeddings)

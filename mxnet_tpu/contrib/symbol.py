"""mx.contrib.symbol — 1.x import-path alias for symbolic contrib ops.

Reference parity: python/mxnet/contrib/symbol.py (empty namespace the op
generator filled with `_contrib_*` symbol wrappers). Symbolic ops in this
build all resolve through the shared CamelCase table in symbol/symbol.py's
module ``__getattr__``; this module forwards there, so
``mx.contrib.symbol.MultiBoxPrior(...)`` builds the same graph node as
``mx.sym.contrib`` style calls.
"""
from .. import symbol as _sym


def __getattr__(name):
    return getattr(_sym, name)

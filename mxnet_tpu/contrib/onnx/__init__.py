"""mx.contrib.onnx — deprecated 1.x import path for the ONNX tools.

Reference parity: python/mxnet/contrib/onnx/__init__.py (forwards to
mx.onnx with a deprecation notice). The real implementation lives in
mxnet_tpu/onnx/ (jaxpr→ONNX exporter + runtime). Imports are lazy so this
facade inherits the parent package's protobuf-missing degradation
(mxnet_tpu/__init__.py guards `from . import onnx`): without protobuf the
package still imports and only these calls raise.
"""
import warnings as _warnings


def _onnx():
    import mxnet_tpu
    return mxnet_tpu.onnx  # the guarded module (or _OnnxUnavailable shim)


def export_model(*args, **kwargs):
    _warnings.warn("mx.contrib.onnx is deprecated; use mx.onnx",
                   DeprecationWarning, stacklevel=2)
    return _onnx().export_model(*args, **kwargs)


def import_model(*args, **kwargs):
    _warnings.warn("mx.contrib.onnx is deprecated; use mx.onnx",
                   DeprecationWarning, stacklevel=2)
    return _onnx().import_model(*args, **kwargs)


def __getattr__(name):
    return getattr(_onnx(), name)

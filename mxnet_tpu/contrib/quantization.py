"""INT8 post-training quantization driver.

Reference parity: python/mxnet/contrib/quantization.py — `quantize_net`
(graph rewrite + calibration), `_LayerOutputMinMaxCollector` ('naive'
mode) and `_LayerHistogramCollector` + `_get_optimal_threshold` (KL /
'entropy' mode, the algorithm of src/operator/quantization/calibrate.cc).

TPU-native design: instead of an NNVM graph-rewrite pass producing
`quantized_conv`/`quantized_fully_connected` symbol nodes, target Gluon
layers are replaced by Quantized blocks whose forwards call the
npx.quantized_* ops (int8 MXU matmul with int32 accumulation, see
mxnet_tpu/ops/quantization.py).  The reference's requantize-fusion passes
are unnecessary: XLA fuses the scale arithmetic around the matmuls.

    qnet = quantize_net(net, calib_data=batches, calib_mode='entropy')
    y = qnet(x)          # conv/dense run int8 on the MXU
"""
from __future__ import annotations

import copy
import logging

import numpy as onp

from .. import numpy_extension as npx
from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Constant
from ..numpy.multiarray import ndarray

__all__ = ["quantize_net", "QuantizedDense", "QuantizedConv",
           "optimal_threshold"]

_INT8_MAX = 127.0


# --------------------------------------------------------------------------
# calibration collectors
# --------------------------------------------------------------------------

class _Stats:
    """Per-layer input statistics: abs-max always; histogram for
    entropy/percentile modes (reference: _LayerHistogramCollector)."""

    def __init__(self, num_bins=2048):
        self.num_bins = num_bins
        self.abs_max = 0.0
        self.hist = None
        self.hist_edges = None

    def update(self, arr: onp.ndarray, want_hist: bool):
        a = onp.abs(arr.astype(onp.float32)).ravel()
        m = float(a.max()) if a.size else 0.0
        if m > self.abs_max:
            old_max = self.abs_max
            self.abs_max = m
            if self.hist is not None:
                # re-bin the existing histogram into the wider range
                old_centers = 0.5 * (self.hist_edges[:-1]
                                     + self.hist_edges[1:])
                new_hist, new_edges = onp.histogram(
                    old_centers, bins=self.num_bins, range=(0, m),
                    weights=self.hist)
                self.hist, self.hist_edges = new_hist, new_edges
        if want_hist:
            h, edges = onp.histogram(a, bins=self.num_bins,
                                     range=(0, self.abs_max or 1e-8))
            if self.hist is None:
                self.hist, self.hist_edges = h.astype(onp.float64), edges
            else:
                self.hist += h


def _smooth_distribution(p, eps=0.0001):
    """Move a little mass onto zero entries so KL is finite (reference:
    contrib/quantization.py _smooth_distribution)."""
    is_zeros = (p == 0).astype(onp.float64)
    is_nonzeros = (p != 0).astype(onp.float64)
    n_zeros = is_zeros.sum()
    n_nonzeros = p.size - n_zeros
    if n_nonzeros == 0:
        raise ValueError("all-zero distribution")
    eps1 = eps * float(n_zeros) / float(n_nonzeros)
    return p.astype(onp.float64) + eps * is_zeros - eps1 * is_nonzeros


def _kl(p, q):
    p = p / p.sum()
    q = q / q.sum()
    mask = p > 0
    return float((p[mask] * onp.log(p[mask] / q[mask])).sum())


def optimal_threshold(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence-minimizing threshold (reference:
    contrib/quantization.py _get_optimal_threshold /
    src/operator/quantization/calibrate.cc).

    `hist` is a histogram of |x| over [0, max].  For each candidate i the
    first i bins are taken as the reference distribution P (outlier mass
    clipped into the last bin) and Q is P merged down to
    num_quantized_bins levels and re-expanded; the i minimizing KL(P||Q)
    gives the threshold.
    """
    hist = onp.asarray(hist, onp.float64)
    n = len(hist)
    if hist.sum() == 0:
        return float(hist_edges[-1])
    best_kl, best_i = onp.inf, n
    for i in range(num_quantized_bins, n + 1):
        sliced = hist[:i]
        p = sliced.copy()
        p[i - 1] += hist[i:].sum()           # clip outliers into last bin
        is_nonzero = sliced != 0
        num_merged = i // num_quantized_bins
        q = onp.zeros(i, onp.float64)
        for j in range(num_quantized_bins):
            start = j * num_merged
            stop = i if j == num_quantized_bins - 1 \
                else (j + 1) * num_merged
            norm = is_nonzero[start:stop].sum()
            if norm:
                q[start:stop] = sliced[start:stop].sum() / norm
        q[~is_nonzero] = 0
        try:
            p = _smooth_distribution(p)
            q = _smooth_distribution(q)
        except ValueError:
            continue
        kl = _kl(p, q)
        if kl < best_kl:
            best_kl, best_i = kl, i
    return float(hist_edges[best_i])


def _percentile_threshold(hist, hist_edges, percentile=99.99):
    c = onp.cumsum(hist)
    if c[-1] == 0:
        return float(hist_edges[-1])
    idx = onp.searchsorted(c, c[-1] * percentile / 100.0)
    return float(hist_edges[min(idx + 1, len(hist_edges) - 1)])


# --------------------------------------------------------------------------
# quantized layer blocks
# --------------------------------------------------------------------------

def _quantize_weight(w: onp.ndarray):
    """Symmetric per-output-channel int8 (axis 0 = output channels)."""
    flat = onp.abs(w.reshape(w.shape[0], -1)).max(axis=1)
    scale = onp.maximum(flat, 1e-12) / _INT8_MAX
    q = onp.clip(onp.round(w / scale.reshape((-1,) + (1,) * (w.ndim - 1))),
                 -_INT8_MAX, _INT8_MAX).astype(onp.int8)
    return q, scale.astype(onp.float32)


def _fusable_act(act):
    """The layer's activation type when the fused epilogue can absorb it
    (see ops.quantization.FUSED_ACTS), else None — the Activation block
    then runs as a separate op after the fused matmul/conv."""
    from ..ops.quantization import FUSED_ACTS
    t = getattr(act, "_act_type", None)
    return t if t in FUSED_ACTS else None


class QuantizedDense(HybridBlock):
    """int8 replacement for nn.Dense (reference:
    quantized_fully_connected.cc as rewritten by quantize_net).

    Forward is ONE fused op (npx.quantized_dense_fused): activation
    quantize, int8 MXU dot, dequant + bias + activation epilogue — the
    separate quantize_v2/quantized_fully_connected pair this replaced
    paid an HBM round-trip per layer (BENCH_r05)."""

    def __init__(self, dense: nn.Dense, threshold: float):
        super().__init__()
        w = dense.weight.data().asnumpy()
        q, scale = _quantize_weight(w)
        self.qweight = Constant(q, name="qweight")
        self.w_scale = Constant(scale, name="w_scale")
        self.bias_c = (Constant(dense.bias.data().asnumpy(), name="bias")
                       if dense.bias is not None else None)
        self.threshold = float(threshold)
        self._units = dense._units
        self._flatten = dense._flatten
        self.act = dense.act
        self._fused_act = _fusable_act(dense.act)

    def forward(self, x):
        out = npx.quantized_dense_fused(
            x, self.qweight.data(), self.threshold / _INT8_MAX,
            self.w_scale.data(),
            bias=self.bias_c.data() if self.bias_c is not None else None,
            act=self._fused_act, flatten=self._flatten)
        if self.act is not None and self._fused_act is None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"QuantizedDense({self._units}, T={self.threshold:.4g})"


class QuantizedConv(HybridBlock):
    """int8 replacement for nn.Conv (reference: quantized_conv.cc)."""

    def __init__(self, conv, threshold: float):
        super().__init__()
        if conv._op_name != "convolution":
            raise MXNetError("only forward convolutions quantize")
        w = conv.weight.data().asnumpy()
        q, scale = _quantize_weight(w)
        self.qweight = Constant(q, name="qweight")
        self.w_scale = Constant(scale, name="w_scale")
        self.bias_c = (Constant(conv.bias.data().asnumpy(), name="bias")
                       if conv.bias is not None else None)
        self.threshold = float(threshold)
        self._conv_cfg = dict(kernel=conv._kernel, stride=conv._strides,
                              dilate=conv._dilation, pad=conv._padding,
                              num_filter=conv._channels,
                              num_group=conv._groups, layout=conv._layout)
        self.act = conv.act
        self._fused_act = _fusable_act(conv.act)

    def forward(self, x):
        out = npx.quantized_conv_fused(
            x, self.qweight.data(), self.threshold / _INT8_MAX,
            self.w_scale.data(),
            bias=self.bias_c.data() if self.bias_c is not None else None,
            act=self._fused_act, **self._conv_cfg)
        if self.act is not None and self._fused_act is None:
            out = self.act(out)
        return out

    def __repr__(self):
        cfg = self._conv_cfg
        return (f"QuantizedConv({cfg['num_filter']}, "
                f"kernel={cfg['kernel']}, T={self.threshold:.4g})")


# --------------------------------------------------------------------------
# quantize_net
# --------------------------------------------------------------------------

def _walk_layers(block, prefix=""):
    """Yield (parent, child_key, structural_path, layer)."""
    for key, child in list(block._children.items()):
        path = f"{prefix}{key}"
        yield block, key, path, child
        yield from _walk_layers(child, path + ".")


def _is_target(layer):
    return isinstance(layer, nn.Dense) or (
        isinstance(layer, nn.conv_layers._Conv)
        and layer._op_name == "convolution")


def _first_array(batch):
    if isinstance(batch, (list, tuple)):
        return batch[0]
    return batch


def quantize_net(network, quantized_dtype="int8", exclude_layers=None,
                 exclude_layers_match=None, calib_data=None,
                 calib_mode="naive", num_calib_batches=None, logger=None):
    """Quantize a Gluon network's Dense/Conv layers to int8.

    Mirrors the reference `mx.contrib.quantization.quantize_net`
    (python/mxnet/contrib/quantization.py): calibrates activation ranges
    over `calib_data` (an iterable of input batches or (data, ...) tuples)
    with `calib_mode` in {'naive', 'entropy', 'percentile'}, then returns
    a **new** network (deep copy) whose targeted layers are replaced by
    QuantizedDense/QuantizedConv.  The original network comes back
    unchanged, but DURING the call its hybridization is temporarily
    switched off so calibration hooks see concrete values — do not run
    concurrent forwards on `network` while quantize_net is calibrating.
    """
    if quantized_dtype != "int8":
        raise NotImplementedError("TPU path supports int8 only")
    if calib_mode not in ("naive", "entropy", "percentile"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    if calib_data is None:
        raise MXNetError("calib_data is required (post-training "
                         "quantization calibrates activation ranges)")
    log = logger or logging.getLogger(__name__)
    exclude_layers = set(exclude_layers or [])

    targets = {}
    for parent, key, path, layer in _walk_layers(network):
        if not _is_target(layer):
            continue
        if path in exclude_layers:
            continue
        if exclude_layers_match and any(m in path
                                        for m in exclude_layers_match):
            continue
        targets[path] = layer

    # -- calibration pass (eager, hooks collect layer-input stats) --------
    want_hist = calib_mode in ("entropy", "percentile")
    stats = {path: _Stats() for path in targets}
    hooks = []
    for path, layer in targets.items():
        def mk(path):
            def hook(block, args):
                import jax
                x = args[0]
                raw = x._data if isinstance(x, ndarray) else x
                if isinstance(raw, jax.core.Tracer):
                    return  # hybridized trace pass: no concrete values
                stats[path].update(onp.asarray(raw), want_hist)
            return hook
        h = mk(path)
        layer.register_forward_pre_hook(h)
        hooks.append((layer, h))
    # hooks need CONCRETE layer inputs: temporarily drop to eager for the
    # calibration forwards (compiled replays skip python hooks; flipping
    # _active directly preserves the user's hybridize flags and compiled
    # caches, unlike re-calling hybridize())
    hybrid_state = []
    stack = [network]
    while stack:
        blk = stack.pop()
        if isinstance(blk, HybridBlock) and getattr(blk, "_active", False):
            hybrid_state.append(blk)
            blk._active = False
        stack.extend(getattr(blk, "_children", {}).values())
    try:
        for i, batch in enumerate(calib_data):
            if num_calib_batches is not None and i >= num_calib_batches:
                break
            network(_first_array(batch))
    finally:
        for blk in hybrid_state:
            blk._active = True
        for layer, h in hooks:
            layer._forward_pre_hooks.remove(h)

    thresholds = {}
    for path, st in stats.items():
        if st.abs_max == 0.0:
            log.warning("layer %s saw no calibration data; skipping", path)
            continue
        if calib_mode == "naive":
            thresholds[path] = st.abs_max
        elif calib_mode == "entropy":
            thresholds[path] = optimal_threshold(st.hist, st.hist_edges)
        else:
            thresholds[path] = _percentile_threshold(st.hist, st.hist_edges)
        log.debug("calibrated %s: T=%.5g (absmax %.5g)", path,
                  thresholds[path], st.abs_max)

    # -- rewrite on a deep copy -------------------------------------------
    qnet = copy.deepcopy(network)
    replaced = 0
    for parent, key, path, layer in list(_walk_layers(qnet)):
        if path not in thresholds or not _is_target(layer):
            continue
        wrapper_cls = QuantizedDense if isinstance(layer, nn.Dense) \
            else QuantizedConv
        q = wrapper_cls(layer, thresholds[path])
        q.initialize()
        parent._children[key] = q
        for attr, val in list(parent.__dict__.items()):
            if val is layer:
                object.__setattr__(parent, attr, q)
        replaced += 1
    log.info("quantized %d/%d target layers", replaced, len(targets))
    if targets and replaced == 0:
        # returning an unquantized copy as "success" would be a silent
        # no-op (calibration runs eagerly even on hybridized nets, so
        # this means the iterable was empty or produced zero data)
        raise MXNetError(
            "quantize_net calibrated 0 of "
            f"{len(targets)} target layers: calib_data was empty or "
            "yielded all-zero batches.")
    return qnet

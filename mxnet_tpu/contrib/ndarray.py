"""mx.contrib.ndarray — 1.x import-path alias of the nd.contrib namespace.

Reference parity: python/mxnet/contrib/ndarray.py (an empty module the op
generator populated with `_contrib_*` wrappers at import). Here the real
namespace lives in ndarray/contrib.py; this module forwards to it so both
``mx.nd.contrib.foo`` and ``mx.contrib.ndarray.foo`` resolve.
"""
from ..ndarray import contrib as _impl


def __getattr__(name):
    return getattr(_impl, name)


def __dir__():
    return dir(_impl)

"""mx.contrib.io — adapters between Gluon data loaders and legacy DataIter.

Reference parity: python/mxnet/contrib/io.py (DataLoaderIter wrapping a
``gluon.data.DataLoader`` so 1.x module-style training loops can consume
it). The reference peeks one batch to learn shapes and zero-pads the last
partial batch up to ``batch_size``; same contract here, built on this
package's DataIter/DataBatch (io/__init__.py).
"""
from __future__ import annotations

import numpy as onp

from .. import numpy as _np
from ..io import DataDesc, DataIter


def _pad_to(arr, batch_size, dtype):
    """Zero-pad axis 0 of `arr` (host or device) up to `batch_size`."""
    a = onp.asarray(arr, dtype=dtype)
    if a.shape[0] == batch_size:
        return _np.array(a)
    out = onp.zeros((batch_size,) + a.shape[1:], dtype=dtype)
    out[: a.shape[0]] = a
    return _np.array(out)


class DataLoaderIter(DataIter):
    """Iterate a ``gluon.data.DataLoader`` through the DataIter interface.

    The loader must yield ``(data, label)`` pairs. Shapes are taken from
    the first batch; a trailing partial batch is zero-padded and its pad
    count reported via ``getpad()`` (reference contrib/io.py:50-93).
    """

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        data, label = next(iter(loader))
        super().__init__(batch_size=int(data.shape[0]))
        self._loader = loader
        self._iter = iter(loader)
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, tuple(data.shape))]
        self.provide_label = [DataDesc(label_name, tuple(label.shape))]
        self._batch = None

    def reset(self):
        self._iter = iter(self._loader)

    def iter_next(self):
        self._batch = next(self._iter, None)
        return self._batch is not None

    def getpad(self):
        return self.batch_size - int(self._batch[0].shape[0])

    def getdata(self):
        return [_pad_to(self._batch[0], self.batch_size, self.dtype)]

    def getlabel(self):
        return [_pad_to(self._batch[1], self.batch_size, self.dtype)]

    def getindex(self):
        return None

"""mx.insight — live performance attribution, fleet-wide metric
aggregation, and step-time drift detection.

Three planes (docs/OBSERVABILITY.md "Performance attribution, fleet
view & drift"):

- **Attribution** — every compiled surface (``ShardedTrainStep``, gluon
  ``_CachedGraph``, serve decode/prefill buckets, autotune trials)
  registers its XLA ``cost_analysis()`` (flops / bytes accessed /
  output bytes) plus argument signatures at compile time, so measured
  step time turns into a live ``insight.mfu`` gauge and a
  compute-vs-memory roofline verdict per executable — the bench.py
  accounting, on every run instead of only in the bench grid.
- **Fleet view** — each host periodically snapshots its telemetry +
  insight state as an atomic JSON file next to the mx.fleet heartbeat
  leases; the ops endpoint merges them so ``/metrics`` carries
  host-labelled fleet series and ``/insight`` returns the merged
  attribution report.
- **Drift** — a rolling robust baseline (median/MAD anchor + winsorised
  EWMA; ``insight.drift_window`` / ``insight.drift_sigma`` knobs) over
  the raw ``trainer.step_seconds`` / ``serve.step_seconds`` samples and
  the sharded train-step loop.  Sustained slowdown emits
  ``insight.drift`` events (telemetry counter + trace span + fault-plane
  record), turns the ``/healthz`` ``insight`` provider red, and feeds
  mx.fleet a per-host relative-slowness straggler signal.

Cost discipline matches telemetry/trace/fault: disabled (the default),
every hook is one module-attribute read — re-gated by
benchmark/telemetry_overhead.py in the ``insight`` CI stage.
"""
from __future__ import annotations

import json
import os
import re
import statistics
import threading
import time

from . import config as _config
from . import fault as _fault
from . import telemetry as _telemetry
from . import trace as _trace

__all__ = [
    "enable", "disable", "configure", "active", "reset",
    "capture_cost", "capture_jit", "register_executable", "note_step",
    "roofline_verdict", "input_stall_p50", "attribution", "last_summary",
    "healthz",
    "drift_events", "DriftDetector", "on_drift", "remove_drift_hook",
    "write_snapshot", "maybe_snapshot", "read_snapshots",
    "merge_snapshots", "fleet_exposition", "relative_slowness",
    "endpoint_report",
]

_telemetry.declare_metric(
    "insight.mfu", "gauge",
    "Measured model-flops utilisation per registered executable: "
    "analytic XLA flops over the last measured step time, divided by "
    "the chip's peak FLOP/s.")
_telemetry.declare_metric(
    "insight.executables", "gauge",
    "Compiled executables currently held in the attribution registry.")
_telemetry.declare_metric(
    "insight.drift_events_total", "counter",
    "Step-time drift events raised by the EWMA+MAD detector, by "
    "source.")
_telemetry.declare_metric(
    "insight.degraded_sources", "gauge",
    "Drift sources currently past threshold (sustained slowdown); "
    "nonzero flips the /healthz insight provider red.")
_telemetry.declare_metric(
    "insight.snapshots_written_total", "counter",
    "Fleet insight snapshots atomically published next to the "
    "heartbeat leases.")
_telemetry.declare_metric(
    "insight.fleet_snapshot_age_seconds", "gauge",
    "Age of each host's merged fleet snapshot at scrape time, by "
    "host — the staleness signal for the fleet view.")

#: peak FLOP/s by device_kind substring (public TPU bf16 specs; the
#: bench.py PEAK_BF16 table) plus a nominal host-CPU entry so the CI
#: virtual mesh still reports a defined — if approximate — MFU.
PEAK_FLOPS = {
    "v5 lite": 197e12, "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12, "v5": 459e12,
    "v6 lite": 918e12, "v6e": 918e12,
    "cpu": 1e11,
}

#: memory bandwidth (bytes/s) by device_kind substring (public HBM
#: specs) — the roofline's machine-balance denominator.
PEAK_BYTES_PER_S = {
    "v5 lite": 819e9, "v5e": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9, "v5": 2765e9,
    "v6 lite": 1640e9, "v6e": 1640e9,
    "cpu": 5e10,
}

_lock = threading.Lock()
_active = False

#: attribution registry: executable name -> entry dict
_exes: dict[str, dict] = {}
#: drift detectors: source name -> DriftDetector
_detectors: dict[str, "DriftDetector"] = {}
#: recent drift events, oldest first (bounded)
_drift_ring: list[dict] = []
_DRIFT_RING_CAP = 256
#: per-executable previous note_step() wall clock (inter-arrival timing)
_last_call: dict[str, float] = {}
_snap_last = 0.0
_peak_cache = None


# -- switches ----------------------------------------------------------------

def active():
    return _active


def _trainer_samples(value):
    _feed("trainer.step", value)


def _serve_samples(value):
    _feed("serve.step", value, exe="serve.decode")


def enable(on=True):
    """Flip the insight plane.  Enabling registers the ``insight``
    /healthz provider and the raw-sample listeners on the step-time
    histograms the drift detector rides (``trainer.step_seconds`` /
    ``serve.step_seconds``)."""
    global _active
    _active = bool(on)
    if _active:
        _telemetry.register_health("insight", healthz)
        _telemetry.add_sample_listener("trainer.step_seconds",
                                       _trainer_samples, tag="insight")
        _telemetry.add_sample_listener("serve.step_seconds",
                                       _serve_samples, tag="insight")
    else:
        _telemetry.unregister_health("insight")
        _telemetry.remove_sample_listener("trainer.step_seconds",
                                          tag="insight")
        _telemetry.remove_sample_listener("serve.step_seconds",
                                          tag="insight")
    return _active


def disable():
    return enable(False)


def configure():
    """Re-arm from the knob/environment state (MXNET_INSIGHT)."""
    return enable(bool(_config.get("insight.enable")))


def reset():
    """Drop every registered executable, detector, drift event and
    snapshot timer (the enabled state stays)."""
    global _snap_last, _peak_cache
    with _lock:
        _exes.clear()
        _detectors.clear()
        _drift_ring.clear()
        _last_call.clear()
        _drift_hooks.clear()
        _snap_last = 0.0
        _peak_cache = None


# -- device peaks & roofline -------------------------------------------------

def _device_kind():
    try:
        import jax
        return str(getattr(jax.devices()[0], "device_kind", "cpu")).lower()
    except Exception:   # noqa: BLE001 - attribution must not need a backend
        return "cpu"


def _lookup_peaks(kind):
    for sub, peak in PEAK_FLOPS.items():
        if sub != "cpu" and sub in kind:
            return peak, PEAK_BYTES_PER_S[sub]
    return PEAK_FLOPS["cpu"], PEAK_BYTES_PER_S["cpu"]


def _peaks(kind=None):
    """(peak FLOP/s, peak bytes/s) for ``kind`` (default: this process's
    first device, cached)."""
    global _peak_cache
    if kind is not None:
        return _lookup_peaks(str(kind).lower())
    if _peak_cache is None:
        _peak_cache = _lookup_peaks(_device_kind())
    return _peak_cache


def input_stall_p50():
    """Median recorded ``pipeline.input_stall_seconds`` (the device-
    prefetch consumer's wait for the host producer), or None without
    samples — the signal that separates a slow step from a starved
    one."""
    q = _telemetry.quantiles("pipeline.input_stall_seconds")
    return q.get("p50") if q else None


def roofline_verdict(flops, bytes_accessed, peak_flops=None,
                     peak_bytes_per_s=None, step_seconds=None):
    """``'input'`` | ``'compute'`` | ``'memory'`` | None.

    With ``step_seconds`` (a measured wall-clock step time), input
    starvation is tested first: when the recorded
    ``pipeline.input_stall_seconds`` p50 exceeds
    ``insight.input_bound_ratio`` × the step time the verdict is
    ``'input'`` regardless of arithmetic intensity — starvation
    masquerades as compute cost (arxiv 2008.01040), so the data plane
    must be ruled out before the roofline is read.  Otherwise:
    arithmetic intensity (flops/byte) against the machine balance (peak
    FLOP/s over peak bytes/s) — the classic roofline ridge-point
    test."""
    if step_seconds:
        stall = input_stall_p50()
        if stall is not None and stall > (
                float(_config.get("insight.input_bound_ratio"))
                * float(step_seconds)):
            return "input"
    if not flops or not bytes_accessed:
        return None
    if peak_flops is None or peak_bytes_per_s is None:
        pf, pb = _peaks()
        peak_flops = peak_flops or pf
        peak_bytes_per_s = peak_bytes_per_s or pb
    balance = peak_flops / peak_bytes_per_s
    return "compute" if flops / bytes_accessed >= balance else "memory"


# -- cost capture ------------------------------------------------------------

def capture_cost(compiled_or_lowered):
    """Normalise XLA ``cost_analysis()`` into ``{"flops",
    "bytes_accessed", "output_bytes"}`` (floats; keys present only when
    the backend reports them).  Accepts both ``Lowered`` (HLO-level
    analysis, no backend compile) and ``Compiled`` objects, unwraps the
    per-device list some backends return, and never raises —
    attribution is strictly best-effort."""
    try:
        ca = compiled_or_lowered.cost_analysis()
    except Exception:   # noqa: BLE001 - backends without analysis
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return {}
    out = {}
    flops = ca.get("flops")
    if flops is not None and float(flops) > 0:
        out["flops"] = float(flops)
    nbytes = ca.get("bytes accessed")
    if nbytes is not None and float(nbytes) > 0:
        out["bytes_accessed"] = float(nbytes)
    # the Lowered-level analysis names output traffic 'bytes accessedout{}'
    obytes = ca.get("bytes accessedout{}")
    if obytes is not None:
        out["output_bytes"] = float(obytes)
    return out


def _signature(args, kwargs=None, limit=16):
    """Compact ``'float32[8,16]'``-style signatures for the argument
    pytree leaves (non-array leaves skipped), capped at ``limit``."""
    if args is None:
        return []
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    out = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        if len(out) >= limit:
            out.append(f"...({len(leaves)} leaves total)")
            break
        dims = ",".join(str(d) for d in shape)
        out.append(f"{getattr(dtype, 'name', dtype)}[{dims}]")
    return out


def register_executable(name, compiled=None, args=None, kwargs=None,
                        cost=None, kind=None):
    """Register one compiled surface in the attribution registry.

    An explicit ``cost`` (a :func:`capture_cost` dict) wins; otherwise
    it is captured from ``compiled``.  Returns the registry entry, or
    None while the plane is disabled."""
    if not _active:
        return None
    if cost is None:
        cost = capture_cost(compiled) if compiled is not None else {}
    entry = {
        "name": name,
        "kind": kind or name.split(".", 1)[0],
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes_accessed"),
        "output_bytes": cost.get("output_bytes"),
        "args": _signature(args, kwargs),
        "bound": roofline_verdict(cost.get("flops"),
                                  cost.get("bytes_accessed")),
        "steps": 0,
        "seconds_total": 0.0,
        "last_seconds": None,
        "achieved_flops_per_s": None,
        "mfu": None,
        "registered_at": time.time(),
    }
    with _lock:
        _exes[name] = entry
        n = len(_exes)
    if _telemetry._active:
        _telemetry.set_gauge("insight.executables", n)
    return entry


def capture_jit(name, jitted, args, kind=None, **kwargs):
    """Register a ``jax.jit`` surface by re-tracing through ``.lower()``:
    HLO-level cost analysis only — no backend compile and no
    ``telemetry.note_compile``, so the recompile detector and compile
    counters are untouched."""
    if not _active:
        return None
    cost = {}
    try:
        cost = capture_cost(jitted.lower(*args, **kwargs))
    except Exception:   # noqa: BLE001 - attribution must never break a step
        pass
    return register_executable(name, args=args, kwargs=kwargs, cost=cost,
                               kind=kind)


# -- drift detection ---------------------------------------------------------

class DriftDetector:
    """Rolling robust step-time drift detector.

    The first full ``window`` samples anchor a robust baseline (their
    median) and scale (MAD, floored at 1% of the baseline so noise-free
    series keep a usable band).  Every later sample folds into an EWMA
    (``alpha = 2/(window+1)``) after being winsorised at
    ``ewma + 8*scale`` — a single spike cannot drag the average — and
    drift fires on the rising edge once the EWMA sits more than
    ``sigma * scale`` above baseline for two consecutive samples.
    One-sided by design: speedups never alarm, and ``degraded`` clears
    itself when the EWMA decays back under threshold."""

    def __init__(self, source, window=None, sigma=None):
        self.source = source
        self.window = max(4, int(
            window if window is not None
            else _config.get("insight.drift_window")))
        self.sigma = float(sigma if sigma is not None
                           else _config.get("insight.drift_sigma"))
        self.alpha = 2.0 / (self.window + 1.0)
        self.baseline = None
        self.scale = None
        self.ewma = None
        self.degraded = False
        self.events = 0
        self.count = 0
        self._anchor: list[float] = []
        self._over = 0

    def update(self, value):
        """Fold one sample in; True exactly when a drift event fires."""
        value = float(value)
        self.count += 1
        if self.baseline is None:
            self._anchor.append(value)
            if len(self._anchor) >= self.window:
                med = statistics.median(self._anchor)
                mad = statistics.median(
                    abs(x - med) for x in self._anchor)
                self.baseline = med
                self.scale = max(1.4826 * mad, 0.01 * abs(med), 1e-12)
                self.ewma = med
                self._anchor = []
            return False
        clipped = min(value, self.ewma + 8.0 * self.scale)
        self.ewma += self.alpha * (clipped - self.ewma)
        if self.ewma - self.baseline > self.sigma * self.scale:
            self._over += 1
            if not self.degraded and self._over >= 2:
                self.degraded = True
                self.events += 1
                return True
        else:
            self._over = 0
            self.degraded = False
        return False

    def state(self):
        return {"source": self.source, "window": self.window,
                "sigma": self.sigma, "count": self.count,
                "baseline": self.baseline, "scale": self.scale,
                "ewma": self.ewma, "degraded": self.degraded,
                "events": self.events}


def note_step(name, seconds=None, step=None):
    """Record one measured execution of registered executable ``name``.

    ``seconds=None`` derives the sample from the interval since the
    previous ``note_step(name)`` — steady-state loop time measured on
    wall clocks the caller already pays, adding no device syncs."""
    if not _active:
        return
    now = time.perf_counter()
    with _lock:
        prev = _last_call.get(name)
        _last_call[name] = now
    if seconds is None:
        if prev is None:
            return
        seconds = now - prev
    _feed(name, seconds, exe=name, step=step)


def _feed(source, seconds, exe=None, step=None):
    """One raw step-time sample: apply the ``insight.drift`` chaos point
    (an injected 3x stretch), update the executable's measured stats and
    ``insight.mfu``, then run the source's drift detector."""
    seconds = float(seconds)
    if _fault._active and _fault.fire("insight.drift", step=step):
        seconds *= 3.0
    peak_flops = _peaks()[0]
    fired = False
    event = None
    mfu = None
    exe_name = None
    with _lock:
        entry = _exes.get(exe) if exe is not None else None
        if entry is not None and seconds > 0:
            entry["steps"] += 1
            entry["seconds_total"] += seconds
            entry["last_seconds"] = seconds
            flops = entry.get("flops")
            if flops:
                achieved = flops / seconds
                entry["achieved_flops_per_s"] = achieved
                mfu = entry["mfu"] = achieved / peak_flops
                exe_name = entry["name"]
        det = _detectors.get(source)
        if det is None:
            det = _detectors[source] = DriftDetector(source)
        fired = det.update(seconds)
        degraded = sum(1 for d in _detectors.values() if d.degraded)
        if fired:
            event = {"source": source, "seconds": seconds,
                     "baseline": det.baseline, "ewma": det.ewma,
                     "scale": det.scale, "sigma": det.sigma,
                     "count": det.count, "time": time.time()}
            if step is not None:
                event["step"] = int(step)
            _drift_ring.append(event)
            del _drift_ring[:-_DRIFT_RING_CAP]
    if _telemetry._active:
        if mfu is not None:
            _telemetry.set_gauge("insight.mfu", round(mfu, 6),
                                 executable=exe_name)
        _telemetry.set_gauge("insight.degraded_sources", degraded)
    if fired:
        _record_drift(source, event)


#: external drift subscribers (e.g. the autotune Retuner arming an
#: online kernel re-search) — called as fn(source, event), exceptions
#: swallowed: a broken subscriber must not take the drift plane down
_drift_hooks: list = []


def on_drift(fn):
    """Subscribe ``fn(source, event)`` to every drift event; returns
    ``fn`` (decorator-friendly).  Idempotent per function object."""
    if fn not in _drift_hooks:
        _drift_hooks.append(fn)
    return fn


def remove_drift_hook(fn):
    """Unsubscribe; unknown functions are a no-op."""
    try:
        _drift_hooks.remove(fn)
    except ValueError:
        pass


def _record_drift(source, event):
    """Mirror one drift event into the telemetry, fault and trace
    planes, then fan out to the registered drift hooks."""
    if _telemetry._active:
        _telemetry.inc("insight.drift_events_total", source=source)
    _fault.record("insight.drift")
    if _trace._active:
        from . import profiler as _profiler
        _trace.emit("insight.drift", _profiler.now_us(), 0,
                    category="insight", source=source,
                    seconds=round(event["seconds"], 6),
                    baseline=round(event["baseline"], 6),
                    ewma=round(event["ewma"], 6))
    from . import blackbox as _blackbox
    if _blackbox._active:
        # a sustained slowdown is a terminal-class anomaly: freeze the
        # evidence window now, while the degraded state is still live
        _blackbox.dump(trigger="drift",
                       reason=f"insight.drift: {source}",
                       step=event.get("step"))
    for fn in list(_drift_hooks):
        try:
            fn(source, event)
        except Exception:
            pass


def drift_events():
    """Recent drift events, oldest first (bounded ring)."""
    with _lock:
        return list(_drift_ring)


def healthz():
    """The /healthz ``insight`` provider: red while any drift source is
    degraded (sustained slowdown past the EWMA+MAD threshold)."""
    with _lock:
        degraded = sorted(s for s, d in _detectors.items() if d.degraded)
        sources = len(_detectors)
        exes = len(_exes)
        events = sum(d.events for d in _detectors.values())
    return {"ok": not degraded, "degraded": degraded, "sources": sources,
            "executables": exes, "drift_events": events}


# -- reports -----------------------------------------------------------------

def attribution():
    """The live attribution report: per-executable cost + measured MFU +
    roofline verdict, drift-detector states, recent drift events."""
    pf, pb = _peaks()
    with _lock:
        exes = {n: dict(e) for n, e in _exes.items()}
        drift = {s: d.state() for s, d in _detectors.items()}
        events = list(_drift_ring)
    # re-read each verdict against the MEASURED step time: a registry
    # entry's static compute/memory call flips to 'input' when the
    # recorded input-stall p50 dominates the step it feeds
    stall = input_stall_p50()
    if stall is not None:
        for e in exes.values():
            if e.get("last_seconds"):
                v = roofline_verdict(e.get("flops"),
                                     e.get("bytes_accessed"),
                                     step_seconds=e["last_seconds"])
                if v == "input":
                    e["bound"] = "input"
    return {"device_kind": _device_kind(),
            "peak_flops_per_s": pf, "peak_bytes_per_s": pb,
            "machine_balance_flops_per_byte": pf / pb,
            "input_stall_p50_s": stall,
            "executables": exes, "drift": drift, "drift_events": events}


def last_summary():
    """The ``insight`` plane for TrainingTelemetry run reports (same
    contract as autotune/analyze planes); None when nothing was
    recorded."""
    with _lock:
        empty = not _exes and not _detectors and not _drift_ring
    if empty:
        return None
    return attribution()


def endpoint_report(lease_dir=None):
    """The ``/insight`` ops-endpoint body: local attribution plus the
    merged fleet view when lease-dir snapshots exist."""
    out = {"enabled": _active, "local": attribution()}
    try:
        out["fleet"] = merge_snapshots(lease_dir)
    except Exception:   # noqa: BLE001 - a torn snapshot can't 500 the scrape
        out["fleet"] = None
    return out


# -- fleet snapshots & merge -------------------------------------------------

SNAPSHOT_PREFIX = "insight-"


def _snapshot_path(lease_dir, rank):
    return os.path.join(lease_dir, f"{SNAPSHOT_PREFIX}{int(rank)}.json")


def write_snapshot(lease_dir=None, rank=0):
    """Atomically publish this host's telemetry + insight state as
    ``insight-<rank>.json`` next to the heartbeat leases (the
    HealthPlane tmp + ``os.replace`` idiom, so readers never see a torn
    file).  Returns the path, or None without a lease dir."""
    lease_dir = lease_dir or _config.get("fleet.lease_dir")
    if not lease_dir:
        return None
    snap = _telemetry.snapshot()
    payload = {"rank": int(rank), "pid": os.getpid(), "time": time.time(),
               "counters": snap["counters"], "gauges": snap["gauges"],
               "insight": attribution()}
    os.makedirs(lease_dir, exist_ok=True)
    path = _snapshot_path(lease_dir, rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload))
    os.replace(tmp, path)
    if _telemetry._active:
        _telemetry.inc("insight.snapshots_written_total")
    return path


def maybe_snapshot(lease_dir=None, rank=0, interval=None):
    """Rate-limited :func:`write_snapshot` — the fleet heartbeat hook
    (rides ``HealthPlane.beat``, so snapshot cadence needs no thread of
    its own)."""
    global _snap_last
    if not _active:
        return None
    if interval is None:
        interval = float(_config.get("insight.snapshot_interval"))
    now = time.monotonic()
    with _lock:
        if _snap_last and now - _snap_last < interval:
            return None
        _snap_last = now
    try:
        return write_snapshot(lease_dir, rank)
    except OSError:
        return None


def read_snapshots(lease_dir=None):
    """{rank: payload} for every well-formed ``insight-*.json`` snapshot
    in the lease dir (torn/foreign files skipped)."""
    lease_dir = lease_dir or _config.get("fleet.lease_dir")
    out = {}
    if not lease_dir or not os.path.isdir(lease_dir):
        return out
    for fname in sorted(os.listdir(lease_dir)):
        if not (fname.startswith(SNAPSHOT_PREFIX)
                and fname.endswith(".json")):
            continue
        try:
            with open(os.path.join(lease_dir, fname)) as f:
                payload = json.loads(f.read())
            out[int(payload["rank"])] = payload
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def merge_snapshots(lease_dir=None):
    """Merge every host snapshot into the fleet view: counters summed,
    gauges maxed (both also kept per host), executables unioned (the
    slowest host's measurement wins the headline — that host bounds the
    fleet's step time), drift sources degraded when ANY host is.
    Refreshes the per-host ``insight.fleet_snapshot_age_seconds``
    staleness gauge.  None when no snapshots exist."""
    snaps = read_snapshots(lease_dir)
    if not snaps:
        return None
    now = time.time()
    merged = {"hosts": sorted(snaps), "time": now,
              "snapshot_age_seconds": {}, "counters": {}, "gauges": {},
              "per_host": {}, "executables": {}, "drift": {},
              "drift_events": []}
    for rank in sorted(snaps):
        p = snaps[rank]
        age = max(0.0, now - float(p.get("time", 0.0)))
        merged["snapshot_age_seconds"][str(rank)] = round(age, 3)
        if _telemetry._active:
            _telemetry.set_gauge("insight.fleet_snapshot_age_seconds",
                                 round(age, 3), host=str(rank))
        counters = dict(p.get("counters") or {})
        gauges = dict(p.get("gauges") or {})
        merged["per_host"][str(rank)] = {"counters": counters,
                                         "gauges": gauges}
        for k, v in counters.items():
            merged["counters"][k] = merged["counters"].get(k, 0) + v
        for k, v in gauges.items():
            prev = merged["gauges"].get(k)
            try:
                merged["gauges"][k] = v if prev is None else max(prev, v)
            except TypeError:
                merged["gauges"][k] = v
        ins = p.get("insight") or {}
        for name, e in (ins.get("executables") or {}).items():
            cur = merged["executables"].get(name)
            pick = dict(e)
            if cur is not None and (cur.get("last_seconds") or 0) >= \
                    (e.get("last_seconds") or 0):
                pick = dict(cur)
            pick["hosts"] = ((cur or {}).get("hosts") or []) + [rank]
            merged["executables"][name] = pick
        for src, d in (ins.get("drift") or {}).items():
            cur = merged["drift"].setdefault(
                src, {"degraded": False, "events": 0, "per_host": {}})
            cur["degraded"] = cur["degraded"] or bool(d.get("degraded"))
            cur["events"] += int(d.get("events") or 0)
            cur["per_host"][str(rank)] = d
        for ev in (ins.get("drift_events") or []):
            merged["drift_events"].append({**ev, "host": rank})
    merged["drift_events"].sort(key=lambda e: e.get("time", 0.0))
    # per-axis collective traffic rollup: parse the labeled
    # mesh.collective_bytes_total{axis="dp"} / zero.collective_bytes_total
    # {op=...} samples out of the summed counters so the fleet view
    # answers "how many bytes moved per mesh axis" (and makes the
    # compression cut directly observable: the dp sample counts wire
    # bytes at the compressed width vs mesh.dp_gradient_bytes_total's
    # uncompressed payload)
    coll = {"by_axis": {}, "zero_by_op": {}}
    for k, v in merged["counters"].items():
        m = re.match(r'mesh\.collective_bytes_total\{axis="([^"]+)"\}$', k)
        if m:
            ax = m.group(1)
            coll["by_axis"][ax] = coll["by_axis"].get(ax, 0) + v
            continue
        m = re.match(r'zero\.collective_bytes_total\{op="([^"]+)"\}$', k)
        if m:
            op = m.group(1)
            coll["zero_by_op"][op] = coll["zero_by_op"].get(op, 0) + v
    comp = merged["counters"].get("comm.compressed_bytes_total", 0)
    uncomp = merged["counters"].get("comm.uncompressed_bytes_total", 0)
    if uncomp and comp:
        coll["compression_ratio"] = round(uncomp / comp, 3)
    if coll["by_axis"] or coll["zero_by_op"]:
        merged["collectives"] = coll
    return merged


def _prom_sample(rendered, value, host):
    """One Prometheus sample line from a snapshot's rendered
    ``name{labels}`` key, with a ``host`` label spliced in."""
    try:
        vv = f"{float(value):g}"
    except (TypeError, ValueError):
        return None
    name, _, rest = rendered.partition("{")
    labels = [f'host="{host}"']
    if rest:
        labels.append(rest[:-1])
    return f"{_telemetry._sanitize(name)}{{{','.join(labels)}}} {vv}"


def fleet_exposition(lease_dir=None):
    """Prometheus text for the fleet view, appended to ``/metrics`` by
    the scraped host: every snapshot counter/gauge re-rendered with a
    ``host="<rank>"`` label, fleet-wide sums (counters) and maxes
    (gauges) under ``host="fleet"``, and the per-host snapshot-age
    staleness gauge.  '' when no snapshots exist."""
    merged = merge_snapshots(lease_dir)
    if merged is None:
        return ""
    lines = ["# fleet view (mx.insight): host-labelled series merged "
             "from lease-dir snapshots"]

    def _extend(kv, host):
        for k, v in sorted(kv.items()):
            line = _prom_sample(k, v, host)
            if line is not None:
                lines.append(line)

    for rank in merged["hosts"]:
        ph = merged["per_host"][str(rank)]
        _extend(ph["counters"], str(rank))
        _extend(ph["gauges"], str(rank))
    _extend(merged["counters"], "fleet")
    _extend(merged["gauges"], "fleet")
    for rank, age in sorted(merged["snapshot_age_seconds"].items()):
        lines.append(_prom_sample(
            "insight.fleet_snapshot_age_seconds", age, rank))
    return "\n".join(ln for ln in lines if ln) + "\n"


#: source names scanned, in priority order, for a host's representative
#: step-time EWMA in its snapshot
_STEP_SOURCES = ("parallel.train_step", "trainer.step", "serve.step",
                 "serve.decode")


def relative_slowness(lease_dir=None):
    """{rank: ratio} of each host's step-time EWMA to the fleet median,
    read from the lease-dir snapshots — mx.fleet's per-host straggler
    signal (cut at ``insight.straggler_ratio``), replacing the
    one-size-fits-all ``fleet.slow_fraction`` deadline for hosts that
    publish insight state.  {} without at least two reporting hosts."""
    snaps = read_snapshots(lease_dir)
    ewmas = {}
    for rank, p in snaps.items():
        drift = (p.get("insight") or {}).get("drift") or {}
        val = None
        for src in _STEP_SOURCES:
            d = drift.get(src)
            if d and d.get("ewma"):
                val = float(d["ewma"])
                break
        if val is None:
            for d in drift.values():
                if d and d.get("ewma"):
                    val = float(d["ewma"])
                    break
        if val:
            ewmas[rank] = val
    if len(ewmas) < 2:
        return {}
    med = statistics.median(ewmas.values())
    if med <= 0:
        return {}
    return {rank: v / med for rank, v in ewmas.items()}


# arm from the environment at import (MXNET_INSIGHT=1), mirroring
# telemetry/fault, so spawned workers and plain scripts inherit it
if _config.get("insight.enable"):
    enable()

"""Tensor-dict serialization formats.

Reference parity: src/serialization/cnpy.{h,cc} (npy/npz save/load of
NDArray dicts — the format behind Block.save_parameters) plus the legacy
NDArray binary format (src/ndarray/ndarray.cc Save/Load).  TPU-native
additions: the **safetensors** format (zero-copy, mmap-friendly,
framework-portable — the modern replacement for the legacy binary
format), implemented directly against the public spec: an 8-byte
little-endian header length, a JSON header mapping tensor name ->
{dtype, shape, data_offsets}, then raw little-endian buffers.

    mx.serialization.save_safetensors(path, {"w": arr, ...})
    tensors = mx.serialization.load_safetensors(path)

Block.save_parameters/load_parameters route here when the filename ends
in ``.safetensors``.
"""
from __future__ import annotations

import json
import struct

import numpy as onp

from .base import MXNetError

__all__ = ["save_safetensors", "load_safetensors"]

# safetensors dtype tags <-> numpy
_DTYPES = {
    "F64": "float64", "F32": "float32", "F16": "float16", "BF16": "bfloat16",
    "I64": "int64", "I32": "int32", "I16": "int16", "I8": "int8",
    "U64": "uint64", "U32": "uint32", "U16": "uint16", "U8": "uint8",
    "BOOL": "bool",
}
_NP2TAG = {v: k for k, v in _DTYPES.items()}


def _np_dtype(tag):
    if tag not in _DTYPES:
        raise MXNetError(f"safetensors dtype {tag!r} unsupported")
    name = _DTYPES[tag]
    if name == "bfloat16":
        import ml_dtypes
        return onp.dtype(ml_dtypes.bfloat16)
    return onp.dtype(name)


def _as_numpy(v):
    if hasattr(v, "asnumpy"):
        return v.asnumpy()
    return onp.asarray(v)


def save_safetensors(path, tensors, metadata=None):
    """Write a dict name -> array (mx ndarray / numpy / jax) to `path`."""
    arrays = {}
    header = {}
    offset = 0
    for name in sorted(tensors):
        arr = onp.ascontiguousarray(_as_numpy(tensors[name]))
        if arr.dtype.byteorder == ">":
            arr = arr.byteswap().view(arr.dtype.newbyteorder("<"))
        tag = _NP2TAG.get(str(arr.dtype))
        if tag is None:
            raise MXNetError(f"{name}: dtype {arr.dtype} has no "
                             "safetensors mapping")
        n = arr.nbytes
        header[name] = {"dtype": tag, "shape": list(arr.shape),
                        "data_offsets": [offset, offset + n]}
        arrays[name] = arr
        offset += n
    if metadata:
        header["__metadata__"] = {str(k): str(v)
                                  for k, v in metadata.items()}
    blob = json.dumps(header, separators=(",", ":")).encode()
    pad = (8 - len(blob) % 8) % 8          # spec: align data to 8 bytes
    blob += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        for name in sorted(arrays):
            f.write(arrays[name].tobytes())
    return path


def load_safetensors(path, return_metadata=False):
    """Read a safetensors file -> dict name -> numpy array."""
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        data = f.read()
    metadata = header.pop("__metadata__", {})
    out = {}
    for name, info in header.items():
        lo, hi = info["data_offsets"]
        arr = onp.frombuffer(data[lo:hi], dtype=_np_dtype(info["dtype"]))
        out[name] = arr.reshape(info["shape"]).copy()
    if return_metadata:
        return out, metadata
    return out

"""Tensor-dict serialization formats.

Reference parity: src/serialization/cnpy.{h,cc} (npy/npz save/load of
NDArray dicts — the format behind Block.save_parameters) plus the legacy
NDArray binary format (src/ndarray/ndarray.cc Save/Load).  TPU-native
additions: the **safetensors** format (zero-copy, mmap-friendly,
framework-portable — the modern replacement for the legacy binary
format), implemented directly against the public spec: an 8-byte
little-endian header length, a JSON header mapping tensor name ->
{dtype, shape, data_offsets}, then raw little-endian buffers.

    mx.serialization.save_safetensors(path, {"w": arr, ...})
    tensors = mx.serialization.load_safetensors(path)

Block.save_parameters/load_parameters route here when the filename ends
in ``.safetensors``.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import struct

import numpy as onp

from .base import MXNetError

__all__ = ["save_safetensors", "load_safetensors",
           "save_legacy_params", "load_legacy_params", "is_legacy_params",
           "atomic_write_bytes", "write_checksum", "verify_checksum",
           "CHECKSUM_SUFFIX"]

CHECKSUM_SUFFIX = ".sha256"


def _clean_stale_tmp(path):
    """Drop temp files a crashed earlier save left next to ``path``
    (``<name>.tmp-*``) so interrupted-then-retried saves don't accumulate
    garbage in the checkpoint directory."""
    d = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path) + ".tmp-"
    try:
        names = os.listdir(d)
    except OSError:
        return
    for n in names:
        if n.startswith(base):
            with contextlib.suppress(OSError):
                os.remove(os.path.join(d, n))


def atomic_write_bytes(path, data):
    """Crash-atomic file write: same-directory temp file + fsync +
    ``os.replace``.  A reader (or a crash at any point) observes either
    the old ``path`` or the complete new one, never a torn file — the
    failure mode the reference's plain ``open(path, 'wb')`` checkpointing
    is exposed to.

    Injection: ``serialization.torn_write`` silently truncates the
    persisted bytes — emulating disk/filesystem-level corruption that
    atomic replace cannot prevent; checksum validation (``write_checksum``
    / ``verify_checksum``) is the recovery that catches it on load.
    """
    from . import fault as _fault
    data = data if isinstance(data, (bytes, bytearray, memoryview)) \
        else bytes(data)
    persisted = data
    if _fault._active and _fault.fire("serialization.torn_write"):
        persisted = data[:max(1, len(data) // 2)]
    _clean_stale_tmp(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(persisted)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    return path


def write_checksum(path):
    """Write a ``path + '.sha256'`` sidecar holding the hex digest of the
    file's current bytes.  Ordering guarantee: the sidecar is written
    *after* the data file, so a crash between the two leaves a checkpoint
    that fails validation (rejected, older one used) — never a corrupt
    checkpoint that passes."""
    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    atomic_write_bytes(path + CHECKSUM_SUFFIX, digest.encode())
    return digest


def verify_checksum(path, required=False):
    """Validate ``path`` against its ``.sha256`` sidecar.

    Returns True when the digest matches, None when no sidecar exists and
    ``required`` is False.  Raises :class:`MXNetError` on mismatch (torn/
    corrupt file) or on a missing sidecar with ``required=True``.
    """
    side = path + CHECKSUM_SUFFIX
    if not os.path.exists(side):
        if required:
            raise MXNetError(f"{path}: checksum sidecar {side} missing")
        return None
    with open(side, "rb") as f:
        want = f.read().decode().strip()
    with open(path, "rb") as f:
        have = hashlib.sha256(f.read()).hexdigest()
    if have != want:
        raise MXNetError(
            f"{path}: checksum mismatch (file {have[:12]}.. vs recorded "
            f"{want[:12]}..) — torn or corrupt checkpoint; falling back "
            "to an older checkpoint is the intended recovery")
    return True

# safetensors dtype tags <-> numpy
_DTYPES = {
    "F64": "float64", "F32": "float32", "F16": "float16", "BF16": "bfloat16",
    "I64": "int64", "I32": "int32", "I16": "int16", "I8": "int8",
    "U64": "uint64", "U32": "uint32", "U16": "uint16", "U8": "uint8",
    "BOOL": "bool",
}
_NP2TAG = {v: k for k, v in _DTYPES.items()}


def _np_dtype(tag):
    if tag not in _DTYPES:
        raise MXNetError(f"safetensors dtype {tag!r} unsupported")
    name = _DTYPES[tag]
    if name == "bfloat16":
        import ml_dtypes
        return onp.dtype(ml_dtypes.bfloat16)
    return onp.dtype(name)


def _as_numpy(v):
    if hasattr(v, "asnumpy"):
        return v.asnumpy()
    return onp.asarray(v)


def save_safetensors(path, tensors, metadata=None):
    """Write a dict name -> array (mx ndarray / numpy / jax) to `path`."""
    arrays = {}
    header = {}
    offset = 0
    for name in sorted(tensors):
        arr = onp.ascontiguousarray(_as_numpy(tensors[name]))
        if arr.dtype.byteorder == ">":
            arr = arr.byteswap().view(arr.dtype.newbyteorder("<"))
        tag = _NP2TAG.get(str(arr.dtype))
        if tag is None:
            raise MXNetError(f"{name}: dtype {arr.dtype} has no "
                             "safetensors mapping")
        n = arr.nbytes
        header[name] = {"dtype": tag, "shape": list(arr.shape),
                        "data_offsets": [offset, offset + n]}
        arrays[name] = arr
        offset += n
    if metadata:
        header["__metadata__"] = {str(k): str(v)
                                  for k, v in metadata.items()}
    blob = json.dumps(header, separators=(",", ":")).encode()
    pad = (8 - len(blob) % 8) % 8          # spec: align data to 8 bytes
    blob += b" " * pad
    payload = b"".join([struct.pack("<Q", len(blob)), blob]
                       + [arrays[name].tobytes() for name in sorted(arrays)])
    return atomic_write_bytes(path, payload)


def load_safetensors(path, return_metadata=False):
    """Read a safetensors file -> dict name -> numpy array."""
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        data = f.read()
    metadata = header.pop("__metadata__", {})
    out = {}
    for name, info in header.items():
        lo, hi = info["data_offsets"]
        arr = onp.frombuffer(data[lo:hi], dtype=_np_dtype(info["dtype"]))
        out[name] = arr.reshape(info["shape"]).copy()
    if return_metadata:
        return out, metadata
    return out


# ---------------------------------------------------------------------------
# legacy MXNet NDArray binary format (.params files)
# ---------------------------------------------------------------------------
#
# Reference: src/ndarray/ndarray.cc NDArray::Save/Load (list container at
# :2123 kMXAPINDArrayListMagic=0x112; per-array V1/V2/V3 records at
# :1851-1864) over dmlc::Stream. Byte-level layout (little-endian):
#
#   u64 0x112, u64 reserved,
#   u64 n_arrays, then per array:
#     u32 magic (V2=0xF993FAC9 | V3=0xF993FACA | V1=0xF993FAC8 | ndim),
#     [V2/V3] i32 stype (0=dense; sparse adds a storage TShape),
#     TShape: i32 ndim + ndim*i64 dims,
#     i32 dev_type, i32 dev_id,
#     i32 mshadow type_flag, raw data bytes
#   u64 n_names, then per name: u64 len + bytes
#
# Implementing this independently gives real interop: `.params` files
# written by Apache MXNet load here, and vice versa.

_LIST_MAGIC = 0x112
_V1_MAGIC = 0xF993FAC8
_V2_MAGIC = 0xF993FAC9
_V3_MAGIC = 0xF993FACA

# mshadow type flags (3rdparty/mshadow/mshadow/base.h:352-364)
_TYPE_FLAGS = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
               4: "int32", 5: "int8", 6: "int64", 7: "bool"}
_FLAG_OF = {v: k for k, v in _TYPE_FLAGS.items()}
_BF16_FLAG = 12


def _np_from_flag(flag):
    if flag == _BF16_FLAG:
        import ml_dtypes
        return onp.dtype(ml_dtypes.bfloat16)
    if flag not in _TYPE_FLAGS:
        raise MXNetError(f"legacy type_flag {flag} unsupported")
    return onp.dtype(_TYPE_FLAGS[flag])


def _flag_of(dtype):
    name = str(onp.dtype(dtype)) if str(dtype) != "bfloat16" else "bfloat16"
    if name == "bfloat16":
        return _BF16_FLAG
    if name not in _FLAG_OF:
        raise MXNetError(f"dtype {name} has no legacy type_flag")
    return _FLAG_OF[name]


def save_legacy_params(path, tensors):
    """Write arrays in the Apache MXNet .params binary format (loadable
    by `mxnet.nd.load`).  `tensors` is a name->array dict (names stored)
    or a list (no names, loads back as a list — reference behavior)."""
    if isinstance(tensors, dict):
        names = list(tensors)
        values = [tensors[n] for n in names]
    else:
        names = []
        values = list(tensors)
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(values)))
        for v in values:
            arr = onp.ascontiguousarray(_as_numpy(v))
            # V3 for 0-d (np-shape semantics); V2 otherwise (1.x compat)
            magic = _V3_MAGIC if arr.ndim == 0 else _V2_MAGIC
            f.write(struct.pack("<I", magic))
            f.write(struct.pack("<i", 0))                    # dense stype
            f.write(struct.pack("<i", arr.ndim))
            for d in arr.shape:
                f.write(struct.pack("<q", d))
            f.write(struct.pack("<ii", 1, 0))                # cpu(0)
            f.write(struct.pack("<i", _flag_of(arr.dtype)))
            f.write(arr.tobytes())
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)
    return path


def load_legacy_params(path):
    """Read an Apache MXNet .params binary file -> dict name->numpy.

    Handles V1/V2/V3 records plus the pre-V1 layout where the magic
    field is the ndim of a uint32 shape (ndarray.cc LegacyTShapeLoad).
    """
    with open(path, "rb") as f:
        data = f.read()
    off = 0

    def take(fmt):
        nonlocal off
        try:
            vals = struct.unpack_from("<" + fmt, data, off)
        except struct.error as e:
            raise MXNetError(
                f"{path}: truncated/corrupt legacy NDArray file "
                f"(at byte {off}): {e}") from e
        off += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    header, _reserved = take("QQ")
    if header != _LIST_MAGIC:
        raise MXNetError(f"{path} is not a legacy NDArray file "
                         f"(magic {header:#x})")
    n = take("Q")
    arrays = []
    for _ in range(n):
        magic = take("I")
        if magic in (_V2_MAGIC, _V3_MAGIC):
            stype = take("i")
            if stype != 0:
                raise MXNetError("sparse records in legacy files are not "
                                 "supported; re-save densely")
            ndim = take("i")
            shape = [take("q") for _ in range(ndim)]
            if magic == _V2_MAGIC and ndim == 0:
                arrays.append(onp.zeros(0, "float32"))
                continue
        elif magic == _V1_MAGIC:
            ndim = take("i")
            shape = [take("q") for _ in range(ndim)]
            if ndim == 0:
                arrays.append(onp.zeros(0, "float32"))
                continue
        else:  # pre-V1: magic is ndim, dims are uint32
            ndim = magic
            shape = [take("I") for _ in range(ndim)]
            if ndim == 0:
                arrays.append(onp.zeros(0, "float32"))
                continue
        take("ii")                                   # context
        flag = take("i")
        dt = _np_from_flag(flag)
        count = 1
        for d in shape:
            if d < 0:
                raise MXNetError(f"{path}: corrupt legacy NDArray file "
                                 f"(negative dim {d} in shape {shape})")
            count *= d
        nbytes = count * dt.itemsize
        if len(data) - off < nbytes:
            raise MXNetError(f"{path}: truncated legacy NDArray file "
                             f"(record needs {nbytes} bytes at {off})")
        arr = onp.frombuffer(data, dt, count=count,
                             offset=off).reshape(shape).copy()
        off += nbytes
        arrays.append(arr)
    n_names = take("Q")
    names = []
    for _ in range(n_names):
        ln = take("Q")
        if len(data) - off < ln:
            raise MXNetError(f"{path}: truncated name section")
        names.append(data[off:off + ln].decode())
        off += ln
    if names and len(names) != len(arrays):
        raise MXNetError("corrupt legacy file: name/array count mismatch")
    if not names:
        return arrays   # unnamed save -> list (reference load behavior)
    return dict(zip(names, arrays))


def is_legacy_params(path):
    try:
        with open(path, "rb") as f:
            return struct.unpack("<Q", f.read(8))[0] == _LIST_MAGIC
    except (OSError, struct.error):
        return False

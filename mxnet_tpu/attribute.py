"""mx.attribute — symbol attribute scopes.

Reference parity: python/mxnet/attribute.py (AttrScope: with-scoped
attribute dicts attached to symbols created inside the scope).
"""
from __future__ import annotations

import threading

_local = threading.local()


class AttrScope:
    """`with AttrScope(ctx_group='dev1'):` attaches attrs to symbols
    created in scope (reference: attribute.py AttrScope)."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings")
        self._attr = kwargs
        self._old = None

    def get(self, attr=None):
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        self._old = current()
        merged = dict(self._old._attr)
        merged.update(self._attr)
        self._attr = merged
        _local.scope = self
        return self

    def __exit__(self, *exc):
        _local.scope = self._old


def current():
    scope = getattr(_local, "scope", None)
    if scope is None:
        scope = AttrScope()
        _local.scope = scope
    return scope

"""Weight-only low-bit storage for the decode path.

Decode is memory-bandwidth-bound: every step streams the full weight set
from HBM to produce one token per slot, so halving (fp32 -> int8) or
cutting to ~an eighth (fp32 -> int4) the weight bytes is a straight
bandwidth win with no activation quantization risk.

Schemes:

- **int8**: symmetric per-output-channel (zero-point 0, the
  ops/quantization.py scheme) over eligible float parameters.
- **int4**: symmetric group-wise along the input axis
  (``serve.quantize_group_size`` columns per scale; rows whose width is
  not divisible fall back to one scale per row), packed two nibbles per
  byte. Bytes per fp32 element: 1/8 for the nibbles + 4/group for the
  scales — ~0.133x at the default group of 128.

Eligibility is governed by the ``serve.quantize_min_elems`` /
``serve.quantize_ndim`` config knobs; everything else (biases, LayerNorm
vectors, tiny heads) stays in float.

The dequant is emitted at the top of the jitted serve step (unpack +
``astype(dtype) * scale``) so XLA fuses the widen-and-scale into the
consuming matmul — weights cross HBM as int8/packed-int4, the MXU/VPU
sees the usual float operand, and ``lax.dot_general`` keeps its
``preferred_element_type`` accumulation. No calibration pass is needed:
scales come from the weights themselves.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import config as _config

_INT8_MAX = 127.0
_INT4_MAX = 7.0

#: historical default for the eligibility floor; the live value is the
#: ``serve.quantize_min_elems`` config knob.
MIN_ELEMENTS = 4096


def _min_elements(v=None):
    return int(_config.get("serve.quantize_min_elems") if v is None else v)


def _ndim(v=None):
    return int(_config.get("serve.quantize_ndim") if v is None else v)


def _group_size(v=None):
    return int(_config.get("serve.quantize_group_size") if v is None else v)


def eligible(name, arr, min_elements=None, ndim=None):
    """Quantize only float matmul operands of meaningful size (rank and
    floor from the serve.quantize_* knobs unless overridden)."""
    return (getattr(arr, "ndim", 0) == _ndim(ndim)
            and jnp.issubdtype(arr.dtype, jnp.floating)
            and arr.size >= _min_elements(min_elements))


def quantize_params_int8(params, min_elements=None, ndim=None):
    """Split a name->array dict into (passthrough, quantized, meta).

    quantized maps name -> (int8 weights, per-row float32 scales);
    meta maps the same names to the original dtype string (kept out of
    the array pytree so jit/AOT lowering sees arrays only). Rows are
    output channels for every 2-D weight this framework stores: Dense
    keeps (units, in_units), Embedding (vocab, units) — the tied LM head
    consumes it transposed, which turns row scales into
    per-output-channel scales there too.
    """
    passthrough, quantized, meta = {}, {}, {}
    for name, arr in params.items():
        if not eligible(name, arr, min_elements, ndim):
            passthrough[name] = arr
            continue
        a = jnp.asarray(arr)
        # per-row for the 2-D default; last-axis generalizes to whatever
        # rank serve.quantize_ndim admits (1-D -> one scale)
        scale = jnp.max(jnp.abs(a), axis=-1, keepdims=True) / _INT8_MAX
        scale = jnp.where(scale == 0, 1.0, scale).astype(jnp.float32)
        q = jnp.clip(jnp.round(a / scale), -_INT8_MAX, _INT8_MAX)
        quantized[name] = (q.astype(jnp.int8), scale)
        meta[name] = str(a.dtype)
    return passthrough, quantized, meta


def quantize_params_int4(params, min_elements=None, ndim=None,
                         group_size=None):
    """int4 variant: group-wise symmetric scales along the input axis,
    nibbles packed two per byte (even column = low nibble).

    quantized maps name -> (packed uint8 (rows, cols//2),
    float32 scales (rows, cols//group)); meta entries are dicts
    ``{"mode": "int4", "dtype", "cols", "group"}`` so
    :func:`dequantize_params` can tell them from legacy int8 strings.
    Odd-width weights pass through (no half byte to park the last
    nibble in).
    """
    g0 = _group_size(group_size)
    passthrough, quantized, meta = {}, {}, {}
    for name, arr in params.items():
        if not eligible(name, arr, min_elements, ndim) \
                or getattr(arr, "ndim", 0) != 2 or arr.shape[-1] % 2:
            passthrough[name] = arr
            continue
        a = jnp.asarray(arr)
        rows, cols = a.shape
        g = g0 if g0 > 0 and cols % g0 == 0 else cols
        grouped = a.reshape(rows, cols // g, g)
        scale = jnp.max(jnp.abs(grouped), axis=2) / _INT4_MAX
        scale = jnp.where(scale == 0, 1.0, scale).astype(jnp.float32)
        q = jnp.clip(jnp.round(grouped / scale[:, :, None]),
                     -_INT4_MAX, _INT4_MAX)
        q = q.astype(jnp.int8).reshape(rows, cols)
        lo = q[:, 0::2].astype(jnp.uint8) & 0xF
        hi = q[:, 1::2].astype(jnp.uint8) & 0xF
        quantized[name] = (lo | (hi << 4), scale)
        meta[name] = {"mode": "int4", "dtype": str(a.dtype),
                      "cols": int(cols), "group": int(g)}
    return passthrough, quantized, meta


def _unpack_int4(packed, cols):
    """(rows, cols//2) uint8 -> (rows, cols) int8 in [-7, 7]."""
    lo = (packed & 0xF).astype(jnp.int8)
    hi = (packed >> 4).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    return jnp.stack([lo, hi], axis=-1).reshape(packed.shape[0], cols)


def dequantize_params(passthrough, quantized, meta):
    """Rebuild the full float param dict inside a trace. The unpack +
    astype + multiply stays adjacent to each consumer, so XLA fuses it
    and the HBM reads stay low-bit."""
    out = dict(passthrough)
    for name, (q, scale) in quantized.items():
        m = meta[name]
        if isinstance(m, dict):  # int4: unpack nibbles, group scales
            dtype, cols, g = m["dtype"], m["cols"], m["group"]
            w = _unpack_int4(q, cols).astype(dtype)
            w = (w.reshape(q.shape[0], cols // g, g)
                 * scale[:, :, None].astype(dtype))
            out[name] = w.reshape(q.shape[0], cols)
        else:
            out[name] = q.astype(m) * scale.astype(m)
    return out


def quantized_bytes(passthrough, quantized, meta):
    """(quantized footprint, original footprint) in bytes — the
    bandwidth story a serve benchmark reports."""
    now = sum(int(a.size) * a.dtype.itemsize for a in passthrough.values())
    was = now
    for name, (q, scale) in quantized.items():
        m = meta[name]
        now += int(q.size) * q.dtype.itemsize + int(scale.size) * 4
        if isinstance(m, dict):
            was += int(q.shape[0]) * m["cols"] * jnp.dtype(m["dtype"]).itemsize
        else:
            was += int(q.size) * jnp.dtype(m).itemsize
    return now, was

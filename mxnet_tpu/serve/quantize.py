"""Weight-only int8 for the decode path.

Decode is memory-bandwidth-bound: every step streams the full weight set
from HBM to produce one token per slot, so halving (fp32) or quartering
the weight bytes is a straight bandwidth win with no activation
quantization risk. Scheme: symmetric per-output-channel int8 (zero-point
0, the ops/quantization.py scheme) over 2-D float parameters; everything
else (biases, LayerNorm vectors) stays in float.

The dequant is emitted at the top of the jitted serve step
(``w_q.astype(dtype) * scale``) so XLA fuses the widen-and-scale into
the consuming matmul — weights cross HBM as int8, the MXU/VPU sees the
usual float operand, and ``lax.dot_general`` keeps its
``preferred_element_type`` accumulation. No calibration pass is needed:
scales come from the weights themselves.
"""
from __future__ import annotations

import jax.numpy as jnp

_INT8_MAX = 127.0

#: 2-D float params smaller than this (elements) stay unquantized — the
#: bandwidth win is negligible and tiny layers are accuracy-sensitive.
MIN_ELEMENTS = 4096


def eligible(name, arr, min_elements=MIN_ELEMENTS):
    """Quantize only 2-D float matmul operands of meaningful size."""
    return (getattr(arr, "ndim", 0) == 2
            and jnp.issubdtype(arr.dtype, jnp.floating)
            and arr.size >= min_elements)


def quantize_params_int8(params, min_elements=MIN_ELEMENTS):
    """Split a name->array dict into (passthrough, quantized, dtypes).

    quantized maps name -> (int8 weights, per-row float32 scales);
    dtypes maps the same names to the original dtype string (kept out of
    the array pytree so jit/AOT lowering sees arrays only). Rows are
    output channels for every 2-D weight this framework stores: Dense
    keeps (units, in_units), Embedding (vocab, units) — the tied LM head
    consumes it transposed, which turns row scales into
    per-output-channel scales there too.
    """
    passthrough, quantized, dtypes = {}, {}, {}
    for name, arr in params.items():
        if not eligible(name, arr, min_elements):
            passthrough[name] = arr
            continue
        a = jnp.asarray(arr)
        scale = jnp.max(jnp.abs(a), axis=1, keepdims=True) / _INT8_MAX
        scale = jnp.where(scale == 0, 1.0, scale).astype(jnp.float32)
        q = jnp.clip(jnp.round(a / scale), -_INT8_MAX, _INT8_MAX)
        quantized[name] = (q.astype(jnp.int8), scale)
        dtypes[name] = str(a.dtype)
    return passthrough, quantized, dtypes


def dequantize_params(passthrough, quantized, dtypes):
    """Rebuild the full float param dict inside a trace. The astype +
    multiply stays adjacent to each consumer, so XLA fuses it and the
    HBM reads stay int8."""
    out = dict(passthrough)
    for name, (q, scale) in quantized.items():
        dtype = dtypes[name]
        out[name] = q.astype(dtype) * scale.astype(dtype)
    return out


def quantized_bytes(passthrough, quantized, dtypes):
    """(quantized footprint, original footprint) in bytes — the
    bandwidth story a serve benchmark reports."""
    now = sum(int(a.size) * a.dtype.itemsize for a in passthrough.values())
    was = now
    for name, (q, scale) in quantized.items():
        now += int(q.size) + int(scale.size) * 4
        was += int(q.size) * jnp.dtype(dtypes[name]).itemsize
    return now, was

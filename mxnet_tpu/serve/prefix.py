"""Host-side radix index over token-block-granular KV cache rows.

The prefix cache's bookkeeping half (docs/SERVING.md "Prefix caching").
The device half never changes shape: KV rows live inside the engine's
fixed-footprint donated ``(max_slots, max_seq, heads, head_dim)``
allocation, and this index merely remembers *which* slot rows currently
hold the KV of *which* token blocks.  Tokens are grouped into fixed-size
blocks of ``serve.prefix_block`` tokens — the block is the radix unit,
so path compression is the block itself and a diverging insert splits a
shared path into a common prefix plus branches (the classic radix-tree
split, block-granular).

Disciplines the engine relies on:

- **Locations are (slot, row) pairs.**  A node's KV lives at rows
  ``[row, row + block)`` of ``slot`` in every layer's cache.  Blocks of
  one matched path may live in *different* slots — the whole matched
  path is copied by the copy loop fused into the engine's compiled
  suffix-prefill executable (one dispatch per admission).
- **Ref-counting pins live prompts.**  A request's own prompt blocks
  are acquired at admission and released at finish; refcount > 0 blocks
  are never evicted by the LRU, and a release below zero is a bug the
  index raises on (the test oracle).
- **Slot reuse invalidates.**  Admitting a new request into slot ``s``
  first drops every node whose KV lived in ``s`` (the rows are about to
  be overwritten) together with the node's whole subtree — a child's
  meaning depends on its ancestors being intact.
- **LRU eviction is leaf-only.**  Capacity pressure evicts the
  least-recently-used refcount-0 *leaf* (evicting an interior node
  would orphan descendants whose prefix just vanished).

Pure host Python: no jax imports, unit-testable without a device.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["RadixIndex"]


class _Node:
    """One cached block: the trie edge label is the block's token tuple."""

    __slots__ = ("tokens", "slot", "row", "refs", "last_use", "parent",
                 "children", "alive")

    def __init__(self, tokens, slot, row, parent):
        self.tokens = tokens      # tuple of block-size token ids
        self.slot = slot          # cache slot holding the rows
        self.row = row            # first row of the block in that slot
        self.refs = 0
        self.last_use = 0
        self.parent = parent
        self.children = {}
        self.alive = True

    def __repr__(self):
        return (f"_Node(slot={self.slot}, row={self.row}, "
                f"refs={self.refs}, kids={len(self.children)})")


class RadixIndex:
    """Block-granular radix trie mapping token prefixes to KV rows.

    ``block`` is the tokens-per-block granularity; ``capacity`` bounds
    the number of indexed blocks (0 = unbounded — the engine's natural
    bound is ``max_slots * (max_seq // block)``).  All counters
    (``hits``/``misses``/``evictions``/``tokens_reused``) are plain
    ints the engine mirrors into telemetry.
    """

    def __init__(self, block, capacity=0):
        self.block = int(block)
        if self.block <= 0:
            raise MXNetError(f"prefix block size must be positive, "
                             f"got {block}")
        self.capacity = int(capacity)
        self._root = _Node((), None, None, None)
        self._size = 0
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.tokens_reused = 0

    def __len__(self):
        return self._size

    def _blocks(self, tokens):
        b = self.block
        n = len(tokens) // b
        return [tuple(tokens[i * b:(i + 1) * b]) for i in range(n)]

    # -- lookup ----------------------------------------------------------

    def match(self, tokens):
        """Longest cached block path covering a *strict* prefix of
        ``tokens`` -> list of nodes (possibly empty).  Strict: at least
        one token is always left for the suffix prefill, which must
        produce the next-token logits — a fully-cached prompt would
        have nothing to forward."""
        self._clock += 1
        path = []
        node = self._root
        covered = 0
        for blk in self._blocks(tokens):
            child = node.children.get(blk)
            if child is None or covered + self.block >= len(tokens):
                break
            child.last_use = self._clock
            path.append(child)
            covered += self.block
            node = child
        return path

    # -- mutation --------------------------------------------------------

    def insert(self, tokens, slot):
        """Index every full block of ``tokens`` as resident in ``slot``
        (block i at rows [i*block, (i+1)*block)).  Existing nodes are
        kept (their rows are just as valid; dedup keeps one canonical
        location per prefix) — a diverging suffix branches off the
        shared path.  Returns the full node path for the prompt, for
        :meth:`acquire`.  Stops early when capacity pressure cannot be
        relieved (every leaf pinned)."""
        self._clock += 1
        node = self._root
        path = []
        for i, blk in enumerate(self._blocks(tokens)):
            child = node.children.get(blk)
            if child is None:
                if self.capacity and self._size >= self.capacity:
                    if not self._evict_lru(protect=set(id(p) for p in path)):
                        break
                child = _Node(blk, int(slot), i * self.block, node)
                node.children[blk] = child
                self._size += 1
            child.last_use = self._clock
            path.append(child)
            node = child
        return path

    def acquire(self, path):
        """Pin every node of ``path`` (+1 ref) — held for the lifetime
        of the request whose slot the blocks live in."""
        for node in path:
            if node.alive:
                node.refs += 1

    def release(self, path):
        """Unpin (−1 ref).  Dead (already-evicted) nodes are skipped —
        ``evict_slot`` may race a request's finish in program order —
        but a live node driven below zero is a bookkeeping bug."""
        for node in path:
            if not node.alive:
                continue
            node.refs -= 1
            if node.refs < 0:
                raise MXNetError(
                    "prefix cache refcount went negative (double "
                    f"release) on {node!r}")

    def _drop(self, node):
        """Remove ``node`` and its whole subtree from the index."""
        if not node.alive:
            return
        if node.parent is not None and \
                node.parent.children.get(node.tokens) is node:
            del node.parent.children[node.tokens]
        stack = [node]
        while stack:
            n = stack.pop()
            if not n.alive:
                continue
            stack.extend(n.children.values())
            n.children.clear()
            n.alive = False
            self._size -= 1
            self.evictions += 1

    def evict_slot(self, slot):
        """Drop every node whose KV rows live in ``slot`` (the slot is
        being reused and its rows overwritten), subtrees included.
        Returns the number of blocks dropped."""
        before = self.evictions
        stack = [self._root]
        doomed = []
        while stack:
            n = stack.pop()
            for child in n.children.values():
                if child.slot == slot:
                    doomed.append(child)
                else:
                    stack.append(child)
        for n in doomed:
            self._drop(n)
        return self.evictions - before

    def evict_path(self, path):
        """Force-evict a matched path (the ``serve.prefix_evict`` chaos
        injection: the hot prefix vanishes between admission and
        prefill).  Dropping the shallowest node takes the rest of the
        path down with it.  Returns the number of blocks dropped."""
        if not path:
            return 0
        before = self.evictions
        self._drop(path[0])
        return self.evictions - before

    def _evict_lru(self, protect=()):
        """Evict the least-recently-used refcount-0 leaf not in
        ``protect``.  Returns True when a block was freed."""
        victim = None
        stack = [self._root]
        while stack:
            n = stack.pop()
            for child in n.children.values():
                stack.append(child)
                if (not child.children and child.refs == 0
                        and id(child) not in protect
                        and (victim is None
                             or child.last_use < victim.last_use)):
                    victim = child
        if victim is None:
            return False
        self._drop(victim)
        return True

    # -- engine helpers --------------------------------------------------

    def slot_heat(self, slot):
        """Newest ``last_use`` over the blocks indexed in ``slot`` (-1
        when none) — the engine prefers reusing the *coldest* free slot
        so hot cached prefixes survive longest."""
        heat = -1
        stack = [self._root]
        while stack:
            n = stack.pop()
            for child in n.children.values():
                stack.append(child)
                if child.slot == slot and child.last_use > heat:
                    heat = child.last_use
        return heat

    def stats(self):
        total = self.hits + self.misses
        return {
            "blocks": self._size,
            "block_tokens": self.block,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else None,
            "tokens_reused": self.tokens_reused,
            "evictions": self.evictions,
        }

"""mx.serve — continuous-batching online inference (docs/SERVING.md).

One resident compiled decode step over a fixed-footprint slot-based KV
cache; requests are admitted/evicted per step, prompts bucket-pad so the
recompile detector stays quiet after warmup, and sampled tokens drain to
the host asynchronously through a bounded deferred window.

    import mxnet_tpu as mx
    eng = mx.serve.load(model, max_slots=8, eos_id=50256,
                        quantize="int8_weights").warmup()
    req = eng.submit(prompt_ids, max_new_tokens=64)
    eng.run()
    req.output_ids, req.ttft, eng.stats()
"""
from .engine import QUANTIZE_MODES, Request, ServeEngine, load
from .quantize import (dequantize_params, quantize_params_int4,
                       quantize_params_int8)

__all__ = ["Request", "ServeEngine", "load", "QUANTIZE_MODES",
           "quantize_params_int8", "quantize_params_int4",
           "dequantize_params"]

"""Continuous-batching serve engine: ONE resident compiled decode step.

Design (PAPERS.md "Portable O(1) Autoregressive Caching for Inference"
is the blueprint; "A Learned Performance Model for TPUs" motivates the
static-shape discipline):

- **Fixed footprint.** The KV cache — per layer one
  (max_slots, max_seq, heads, head_dim) K and V array — is allocated
  once at construction and *donated* through every compiled call, so the
  decode working set never grows, shrinks, or reallocates no matter how
  requests arrive. Every device shape in the engine is static.
- **One decode executable.** All live requests advance together through
  a single AOT-compiled step (batch dim = max_slots); idle slots ride
  along masked. Prefill gets one executable per prompt-length *bucket*
  (``serve.buckets``), prompts pad up to the smallest fitting bucket,
  and ``warmup()`` compiles the whole grid up front — after that the
  PR 2 recompile detector (``telemetry.note_compile``) must stay silent,
  and the engine counts any post-warmup compile as a bug signal.
- **Continuous batching.** A slot is freed the moment its request
  finishes (EOS or token budget) and the next queued request is admitted
  into it mid-flight — no waiting for the batch to drain, the property
  that buys the ≥2x over sequential decode in
  benchmark/serve_throughput.py.
- **Sync-free step loop.** The mx.pipeline deferred-window pattern:
  each step's sampled (token, done) vectors stay on device and are
  pushed into a bounded :class:`_EmitWindow`; the host fetches them at
  most ``serve.drain_window`` steps later (or when it needs a slot).
  Dispatching a step never blocks on device results, so the device
  pipeline stays full. The price: completions are observed up to
  ``drain_window`` steps late — bounded staleness, never lost tokens.
"""
from __future__ import annotations

import collections
import time

import jax
import jax.numpy as jnp
import numpy as onp

from .. import config as _config
from .. import fault as _fault
from .. import functional as _functional
from .. import goodput as _goodput
from .. import insight as _insight
from .. import pipeline as _pipeline
from .. import profiler as _profiler
from .. import servefleet as _servefleet
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..base import MXNetError
from . import quantize as _quantize
from .prefix import RadixIndex

__all__ = ["Request", "ServeEngine", "EngineBusy", "load"]

_telemetry.declare_metric(
    "serve.requests_total", "counter",
    "requests submitted to serve engines")
_telemetry.declare_metric(
    "serve.admitted_total", "counter",
    "requests admitted into a decode slot (prefill dispatched)")
_telemetry.declare_metric(
    "serve.completed_total", "counter",
    "requests finished (EOS or token budget)")
_telemetry.declare_metric(
    "serve.tokens_total", "counter",
    "generated tokens delivered to requests")
_telemetry.declare_metric(
    "serve.prefill_tokens_total", "counter",
    "prompt tokens processed by prefill (bucket-padded length)")
_telemetry.declare_metric(
    "serve.steps_total", "counter",
    "continuous-batching decode steps dispatched")
_telemetry.declare_metric(
    "serve.step_seconds", "histogram",
    "host wall time to dispatch one decode step (sync-free: excludes "
    "device completion)", buckets=_telemetry.TIME_BUCKETS)
_telemetry.declare_metric(
    "serve.ttft_seconds", "histogram",
    "time to first token: submit -> first token drained to the host",
    buckets=_telemetry.TIME_BUCKETS)
_telemetry.declare_metric(
    "serve.tpot_seconds", "histogram",
    "time per output token after the first (decode cadence per request)",
    buckets=_telemetry.TIME_BUCKETS)
_telemetry.declare_metric(
    "serve.queue_depth", "gauge",
    "requests waiting for a free slot")
_telemetry.declare_metric(
    "serve.rejected_total", "counter",
    "requests rejected by submit() (engine stopping, or the bounded "
    "serve.max_queue backpressure) or discarded queued by "
    "stop(drain=False)")
_telemetry.declare_metric(
    "serve.slot_occupancy", "gauge",
    "slots holding a live request")
_telemetry.declare_metric(
    "serve.post_warmup_compiles_total", "counter",
    "XLA compiles after warmup() — should stay 0; any hit means a "
    "request shape escaped the bucket grid")
_telemetry.declare_metric(
    "serve.quantized_params", "gauge",
    "parameters stored low-bit by the engine's weight quantization "
    "(serve.quantize_min_elems / serve.quantize_ndim govern eligibility)")
_telemetry.declare_metric(
    "serve.passthrough_params", "gauge",
    "parameters kept in float by the engine's weight quantization "
    "(ineligible rank/size, or quantization off)")
_telemetry.declare_metric(
    "serve.slo_violations_total", "counter",
    "requests finishing past a declared serving SLO objective, by kind "
    "(ttft: serve.slo_ttft_ms at first token; tpot: serve.slo_tpot_ms "
    "per output token at finish)")
_telemetry.declare_metric(
    "serve.slo_burn_rate", "gauge",
    "per-engine error-budget burn rate against serve.slo_target over "
    "the trailing window, by kind — 1.0 spends the budget exactly; "
    "past goodput.burn_threshold the engine's /healthz goes red (the "
    "autoscaler admission signal)")

_telemetry.declare_metric(
    "serve.prefix_hits_total", "counter",
    "admissions that reused a cached KV prefix (radix prefix cache): "
    "matched blocks were row-copied and only the suffix prefilled")
_telemetry.declare_metric(
    "serve.prefix_misses_total", "counter",
    "admissions that prefilled the whole prompt (no cached prefix, a "
    "suffix that would overrun max_seq, or a serve.prefix_evict "
    "injection between match and copy)")
_telemetry.declare_metric(
    "serve.prefix_tokens_reused_total", "counter",
    "prompt tokens whose KV was row-copied from the prefix cache "
    "instead of recomputed by prefill")
_telemetry.declare_metric(
    "serve.prefix_evictions_total", "counter",
    "KV blocks dropped from the radix index (slot reuse, LRU capacity "
    "pressure, or the serve.prefix_evict chaos injection)")
_telemetry.declare_metric(
    "serve.prefix_blocks", "gauge",
    "KV blocks currently indexed by the engine's radix prefix cache")
_telemetry.declare_metric(
    "serve.spec_rounds_total", "counter",
    "speculative-decoding rounds dispatched (one draft propose + one "
    "batched big-model verify per round)")
_telemetry.declare_metric(
    "serve.spec_proposed_total", "counter",
    "draft tokens proposed by speculative decoding (k per live slot "
    "per round)")
_telemetry.declare_metric(
    "serve.spec_accepted_total", "counter",
    "draft proposals the big-model verify accepted (the emitted "
    "correction token is not counted)")
_telemetry.declare_metric(
    "serve.spec_acceptance_rate", "gauge",
    "trailing draft-acceptance ratio (accepted / proposed) — the "
    "knob that decides whether speculation pays for its draft")
_telemetry.declare_metric(
    "serve.class_ttft_seconds", "histogram",
    "per-SLO-class time to first token (labelled slo_class; the "
    "unlabelled serve.ttft_seconds carries the aggregate)",
    buckets=_telemetry.TIME_BUCKETS)
_telemetry.declare_metric(
    "serve.class_tpot_seconds", "histogram",
    "per-SLO-class time per output token (labelled slo_class)",
    buckets=_telemetry.TIME_BUCKETS)
_telemetry.declare_metric(
    "serve.class_queue_depth", "gauge",
    "queued requests per SLO class (labelled slo_class)")
_telemetry.declare_metric(
    "serve.aged_admissions_total", "counter",
    "admissions where starvation aging (serve.class_aging_ms) "
    "promoted a request ahead of strict class priority")

#: weight-storage modes ServeEngine(quantize=...) understands; combine
#: with "," (e.g. "int4_weights,int8_kv")
QUANTIZE_MODES = ("int8_weights", "int4_weights", "int8_kv")


def _parse_quantize(quantize):
    """-> (normalized spec or None, weight mode or None, kv_int8 flag)."""
    if not quantize:
        return None, None, False
    modes = [m.strip() for m in str(quantize).split(",") if m.strip()]
    unknown = [m for m in modes if m not in QUANTIZE_MODES]
    if unknown or not modes:
        raise MXNetError(
            f"unknown quantize mode {quantize!r}; modes: "
            f"{', '.join(QUANTIZE_MODES)} (comma-combinable)")
    weight = [m for m in modes if m.endswith("_weights")]
    if len(weight) > 1:
        raise MXNetError(f"conflicting weight modes in {quantize!r}")
    return ",".join(dict.fromkeys(modes)), \
        (weight[0] if weight else None), "int8_kv" in modes


class EngineBusy(MXNetError):
    """:meth:`ServeEngine.submit` rejected the request — the engine is
    stopping, or the bounded queue (``serve.max_queue``) is full.
    Structured so callers can backpressure instead of string-matching:
    ``reason`` ("stopping" / "queue_full"), ``queued`` (depth at
    rejection), ``max_queue`` (the bound; 0 = unbounded), and
    ``retry_after_hint`` — the machine-readable backoff in seconds
    (queue depth x the engine's observed TPOT p50), so a router retries
    when a slot is plausibly free instead of hammering a saturated
    replica."""

    def __init__(self, reason, queued, max_queue, retry_after_hint=0.0):
        self.reason = reason
        self.queued = queued
        self.max_queue = max_queue
        self.retry_after_hint = float(retry_after_hint)
        bound = f", bound {max_queue} (serve.max_queue)" if max_queue else ""
        hint = (f", retry after ~{self.retry_after_hint:.3f}s"
                if self.retry_after_hint else "")
        super().__init__(
            f"serve engine busy ({reason}): {queued} queued{bound}{hint}")


class Request:
    """One generation request and its latency record.

    ``generated`` holds every sampled token id (EOS included when hit);
    ``output_ids`` strips a trailing EOS. TTFT/TPOT are measured at
    *drain* time — when the token was actually available to the caller,
    not when the device produced it — so the deferred window's bounded
    staleness is charged to the engine, keeping the SLO numbers honest.
    """

    __slots__ = ("id", "prompt", "max_new_tokens", "eos_id", "generated",
                 "slot", "finished", "rejected", "reject_reason",
                 "t_submit", "t_admitted", "t_first",
                 "t_done", "phases", "_span", "_enq",
                 "slo_class", "prefix_tokens", "_nodes")

    def __init__(self, rid, prompt, max_new_tokens, eos_id=None,
                 slo_class="default"):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new_tokens = max(1, int(max_new_tokens))
        self.eos_id = eos_id
        #: admission-priority class (serve.slo_classes; "default" when
        #: the engine runs classless)
        self.slo_class = slo_class
        #: prompt tokens served from the radix prefix cache (KV rows
        #: copied instead of recomputed); 0 = full prefill
        self.prefix_tokens = 0
        self._nodes = ()   # pinned radix path, released at _finish
        self.generated = []
        self.slot = None
        self.finished = False
        #: structured rejection marker: a queued request discarded by
        #: stop(drain=False) flips this True (with reject_reason set)
        #: so a waiting caller observes the outcome instead of hanging
        self.rejected = False
        self.reject_reason = None
        self.t_submit = time.perf_counter()
        self.t_admitted = None
        self.t_first = None
        self.t_done = None
        #: per-phase wall-time samples (seconds) — the source of
        #: stats()["phases"]: unbounded while mx.trace records this
        #: request, else capped by serve.phase_sampling
        self.phases = {}
        self._span = None   # serve.request root (trace.SpanHandle)
        self._enq = None    # serve.enqueue child, open until admission

    @property
    def output_ids(self):
        out = list(self.generated)
        if out and self.eos_id is not None and out[-1] == self.eos_id:
            out.pop()
        return out

    @property
    def ttft(self):
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot(self):
        if self.t_done is None or self.t_first is None:
            return None
        return (self.t_done - self.t_first) / max(1, len(self.generated) - 1)

    def __repr__(self):
        state = "done" if self.finished else (
            "slot%d" % self.slot if self.slot is not None else "queued")
        return (f"Request(id={self.id}, prompt={len(self.prompt)} tok, "
                f"out={len(self.generated)} tok, {state})")


class _EmitWindow(_pipeline.DeferredWindow):
    """DeferredWindow whose entries are device *vectors* (per-slot token
    ids + done flags), not scalars: the drain fetches with device_get and
    hands host numpy arrays to the sink. Overflow keeps the base-class
    behavior — oldest entry drained in place, counted as a host sync and
    a ``pipeline.deferred_evictions_total`` tick."""

    def _drain_one(self):
        value, sink = self._pending.pop(0)
        sink(jax.device_get(value))

    def drain_oldest(self, n=1):
        for _ in range(min(n, len(self._pending))):
            if _pipeline._guard_depth:
                _pipeline.note_host_sync("serve.drain")
            self._drain_one()


def _parse_buckets(spec):
    try:
        vals = sorted({int(v) for v in str(spec).split(",") if v.strip()})
    except ValueError as e:
        raise MXNetError(f"bad serve.buckets spec {spec!r}") from e
    if not vals or any(v <= 0 for v in vals):
        raise MXNetError(f"bad serve.buckets spec {spec!r}")
    return vals


def _sds(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


class ServeEngine:
    """Online inference over a block exposing the KV-cache surface
    (``init_cache`` / ``prefill`` / ``decode_step`` — gluon's GPT family
    and any HybridBlock following the same contract).

    Usage::

        eng = mx.serve.load(model, max_slots=8, eos_id=50256)
        eng.warmup()                      # compile the whole grid
        reqs = [eng.submit(ids, max_new_tokens=64) for ids in prompts]
        eng.run()                         # continuous batching
        reqs[0].output_ids, reqs[0].ttft, eng.stats()

    ``temperature=0`` is greedy; >0 samples from softmax(logits/T).
    ``quantize`` picks low-bit storage (serve/quantize.py, comma-
    combinable): ``"int8_weights"`` = per-channel int8 weights,
    ``"int4_weights"`` = group-wise int4 packed two nibbles per byte,
    ``"int8_kv"`` = int8 KV cache with per-(slot, row, head) scales.
    Dequant always fuses into the consuming matmuls, so HBM reads stay
    low-bit.
    """

    def __init__(self, model, max_slots=None, max_seq=None, buckets=None,
                 eos_id=None, temperature=0.0, seed=0, quantize=None,
                 drain_window=None, cache_dtype="float32", draft=None,
                 prefix_cache=None):
        for attr in ("init_cache", "prefill", "decode_step"):
            if not callable(getattr(model, attr, None)):
                raise MXNetError(
                    f"model {type(model).__name__} has no {attr}(); the "
                    "serve engine needs the KV-cache block surface "
                    "(gluon.model_zoo.gpt, docs/SERVING.md)")
        self.model = model
        self.max_slots = int(max_slots if max_slots is not None
                             else _config.get("serve.max_slots"))
        if self.max_slots <= 0:
            raise MXNetError("max_slots must be positive")
        if max_seq is None:
            max_seq = getattr(model, "max_length", None)
            if max_seq is None:
                raise MXNetError("max_seq not given and model has no "
                                 "max_length")
        self.max_seq = int(max_seq)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self._ensure_initialized()
        params = _functional.param_arrays(model)
        self.quantize, weight_mode, kv_int8 = _parse_quantize(quantize)
        self._weight_mode = weight_mode
        if (weight_mode == "int4_weights"
                and getattr(model, "_fp8_trained", False)
                and not _config.get("serve.allow_fp8_requant")):
            # fp8-trained weights already carry ~2 mantissa bits of
            # quantization noise at every matmul site; stacking group-wise
            # int4 on top compounds it past the accuracy contract int4
            # was validated under.  int8_weights / int8_kv compose fine
            # (int8's grid is strictly finer than e4m3's).
            raise MXNetError(
                "quantize='int4_weights' on an fp8-trained checkpoint "
                "(model._fp8_trained is set): compounding int4 weight "
                "quantization on fp8 training noise is refused by "
                "default. Serve with 'int8_weights'/'int8_kv' (which "
                "compose with fp8 training), or set "
                "mx.config.set('serve.allow_fp8_requant', True) to "
                "override after validating accuracy.")
        if kv_int8:
            cache_dtype = "int8"
        pt, qt, qdt = self._quantize_weights(params)
        self._params = (pt, qt)
        self._qdtypes = qdt
        if _telemetry._active and weight_mode:
            _telemetry.set_gauge("serve.quantized_params", len(qt))
            _telemetry.set_gauge("serve.passthrough_params", len(pt))
        buckets = _parse_buckets(buckets if buckets is not None
                                 else _config.get("serve.buckets"))
        self.buckets = [b for b in buckets if b <= self.max_seq] \
            or [self.max_seq]
        self.cache_dtype = cache_dtype
        cache = model.init_cache(self.max_slots, self.max_seq,
                                 dtype=cache_dtype)
        self._cache = jax.tree_util.tree_map(
            _functional._raw, cache,
            is_leaf=lambda x: hasattr(x, "_data"))
        n = self.max_slots
        self._state = {
            "tokens": jnp.zeros((n,), jnp.int32),
            "positions": jnp.zeros((n,), jnp.int32),
            "done": jnp.ones((n,), bool),
            "limits": jnp.zeros((n,), jnp.int32),
            "key": jax.random.PRNGKey(seed),
        }
        self._queue = collections.deque()
        self._slots = [None] * n
        self._free = list(range(n - 1, -1, -1))  # pop() -> lowest first
        self._window = _EmitWindow(
            drain_window if drain_window is not None
            else _config.get("serve.drain_window"))
        self._exe = {}
        import types
        self._aux_exe_owner = types.SimpleNamespace()
        self._warmed = False
        self.compiles = 0
        self.post_warmup_compiles = 0
        self._next_id = 0
        self._steps = 0
        self._completed = []
        self._stopping = False
        self._max_queue = int(_config.get("serve.max_queue"))
        self._last_step_time = None
        self._created = time.monotonic()
        # serving SLO objectives (0 = disarmed) + the always-on bounded
        # phase reservoir (stats()["phases"] without the tracer)
        self._slo_ttft = float(_config.get("serve.slo_ttft_ms")) / 1e3
        self._slo_tpot = float(_config.get("serve.slo_tpot_ms")) / 1e3
        self._slo_events = collections.deque(maxlen=2048)
        self._phase_cap = int(_config.get("serve.phase_sampling"))
        # -- SLO classes: strict-priority admission over one queue ------
        spec = str(_config.get("serve.slo_classes") or "")
        self._classes = [c.strip() for c in spec.split(",") if c.strip()] \
            or ["default"]
        if len(set(self._classes)) != len(self._classes):
            raise MXNetError(
                f"duplicate class in serve.slo_classes {spec!r}")
        self._class_rank = {c: i for i, c in enumerate(self._classes)}
        self._class_bounds = {}
        bspec = str(_config.get("serve.class_max_queue") or "")
        for part in (p.strip() for p in bspec.split(",") if p.strip()):
            cls, _, bound = part.partition("=")
            cls = cls.strip()
            if cls not in self._class_rank or not bound.strip().isdigit():
                raise MXNetError(
                    f"bad serve.class_max_queue entry {part!r} (classes: "
                    f"{', '.join(self._classes)})")
            self._class_bounds[cls] = int(bound)
        self._aging = float(_config.get("serve.class_aging_ms")) / 1e3
        self._aged_admissions = 0
        # -- radix prefix cache -----------------------------------------
        if prefix_cache is None:
            prefix_cache = bool(_config.get("serve.prefix_cache"))
        self._prefix = None
        self._prefix_block = int(_config.get("serve.prefix_block"))
        if prefix_cache:
            if self._prefix_block <= 0:
                raise MXNetError("serve.prefix_block must be positive")
            for attr in ("prefill_suffix", "copy_cache_rows"):
                if not callable(getattr(model, attr, None)):
                    raise MXNetError(
                        f"model {type(model).__name__} has no {attr}(); "
                        "the prefix cache needs the suffix-prefill block "
                        "surface (docs/SERVING.md 'Prefix caching')")
            self._prefix = RadixIndex(
                self._prefix_block,
                int(_config.get("serve.prefix_capacity")))
        # -- speculative decoding (draft model) -------------------------
        self.draft = draft
        self._spec_k = 0
        self._draft_params = None
        self._draft_cache = None
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        if draft is not None:
            if self.temperature != 0.0:
                raise MXNetError(
                    "speculative decoding needs temperature=0: the "
                    "verify keeps greedy output token-for-token "
                    "identical, which has no sampled analogue here")
            if not callable(getattr(model, "decode_multi", None)):
                raise MXNetError(
                    f"model {type(model).__name__} has no decode_multi();"
                    " the speculative verify needs the multi-token "
                    "decode surface (docs/SERVING.md)")
            for attr in ("init_cache", "prefill", "decode_step"):
                if not callable(getattr(draft, attr, None)):
                    raise MXNetError(
                        f"draft {type(draft).__name__} has no {attr}(); "
                        "the draft must expose the same KV-cache "
                        "surface as the served model")
            if self._prefix is not None:
                for attr in ("prefill_suffix", "copy_cache_rows"):
                    if not callable(getattr(draft, attr, None)):
                        raise MXNetError(
                            f"draft {type(draft).__name__} has no "
                            f"{attr}(); combining the prefix cache with "
                            "speculative decoding needs it on the draft "
                            "too (its KV rows are copied alongside)")
            self._spec_k = max(2, int(_config.get("serve.spec_tokens")))
            self._ensure_initialized(draft)
            # draft weights stay float: the draft is small by design and
            # the verify keeps output quality pinned to the big model
            self._draft_params = _functional.param_arrays(draft)
            dcache = draft.init_cache(self.max_slots, self.max_seq,
                                      dtype=cache_dtype)
            self._draft_cache = jax.tree_util.tree_map(
                _functional._raw, dcache,
                is_leaf=lambda x: hasattr(x, "_data"))
        self._register_health()

    def _register_health(self):
        """Register this engine's /healthz provider. The ops endpoint's
        /healthz reflects THIS engine's step-loop liveness (a process
        hosts one serving engine; the newest wins).  Bound weakly: a
        collected engine must not pin a stale check.  Re-invoked by
        :meth:`resume` after a rolling weight update's drain/stop cycle
        unregistered the provider."""
        import weakref
        ref = weakref.ref(self)

        def _check():
            eng = ref()
            if eng is None:
                _telemetry.unregister_health("serve")
                return True
            return eng._health()

        self._health_name = _telemetry.register_health("serve", _check)

    # -- model/param plumbing -------------------------------------------

    def _quantize_weights(self, params):
        """Run the engine's configured weight-storage mode over a flat
        ``{name: array}`` tree -> ``(passthrough, quantized, qdtypes)``.
        Shared by __init__ and :meth:`update_weights` so a weight swap
        reproduces the storage layout the AOT executables were compiled
        against."""
        if self._weight_mode == "int8_weights":
            return _quantize.quantize_params_int8(params)
        if self._weight_mode == "int4_weights":
            return _quantize.quantize_params_int4(params)
        return params, {}, {}

    def _ensure_initialized(self, model=None):
        """Materialize deferred params with one tiny eager forward —
        shape inference must not happen inside an AOT trace."""
        model = self.model if model is None else model
        needs = any(p._data is None
                    for p in model.collect_params().values())
        if needs:
            from .. import numpy as np
            model(np.zeros((1, min(2, self.max_seq)), dtype="int32"))

    def _full_params(self):
        pt, qt = self._params
        if not qt:
            return pt
        return _quantize.dequantize_params(pt, qt, self._qdtypes)

    def _sample(self, logits, key):
        if self.temperature > 0:
            return jax.random.categorical(
                key, logits / self.temperature, axis=-1).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # -- compiled step functions ----------------------------------------

    def _compile(self, kind, build_args):
        """AOT lower+compile one step executable, accounted through the
        PR 2 recompile detector (telemetry.note_compile) so a post-warmup
        compile trips RecompileWarning exactly like a re-tracing block.
        The base grid (decode + prefill buckets) counts against the
        engine; the prefix/spec surface (copy + suffix buckets + spec)
        is a second planned grid and counts against its own owner, so a
        fully-featured warmup does not trip the per-block signature
        heuristic while real post-warmup escapes still do."""
        t0 = time.perf_counter()
        jitted, args = build_args()
        exe = jitted.lower(*args).compile()
        dt = time.perf_counter() - t0
        self.compiles += 1
        if self._warmed:
            self.post_warmup_compiles += 1
            if _telemetry._active:
                _telemetry.inc("serve.post_warmup_compiles_total")
        owner = self if (kind == "decode" or kind.startswith("prefill")) \
            else self._aux_exe_owner
        _telemetry.note_compile(owner, f"serve.{kind}", dt,
                                signatures=len(self._exe) + 1)
        if _insight._active:
            # attribution capture from the AOT executable we already
            # paid for (args are the abstract ShapeDtypeStructs)
            _insight.register_executable(f"serve.{kind}", compiled=exe,
                                         args=args, kind="serve")
        return exe

    def _decode_fn(self, params, cache, state):
        pt, qt = params
        full = (_quantize.dequantize_params(pt, qt, self._qdtypes)
                if qt else pt)
        key, kf, ks = jax.random.split(state["key"], 3)
        (logits, cache), _ = _functional.functional_call(
            self.model, full, state["tokens"][:, None], cache,
            state["positions"], rng_key=kf, method="decode_step")
        tok = self._sample(logits, ks)
        done0 = state["done"]
        positions = jnp.where(done0, state["positions"],
                              state["positions"] + 1)
        hit_eos = (tok == self.eos_id) if self.eos_id is not None \
            else jnp.zeros_like(done0)
        done = done0 | hit_eos | (positions >= state["limits"])
        new_state = {
            "tokens": jnp.where(done0, state["tokens"], tok),
            "positions": positions,
            "done": done,
            "limits": state["limits"],
            "key": key,
        }
        emit = (jnp.where(done0, -1, tok), done)
        return cache, new_state, emit

    def _prefill_fn(self, params, cache, state, prompt, slot, length,
                    limit):
        pt, qt = params
        full = (_quantize.dequantize_params(pt, qt, self._qdtypes)
                if qt else pt)
        key, kf, ks = jax.random.split(state["key"], 3)
        (logits, cache), _ = _functional.functional_call(
            self.model, full, prompt[None, :], cache, slot,
            rng_key=kf, method="prefill")
        tok = self._sample(logits[0, length - 1][None, :], ks)[0]
        hit_eos = (tok == self.eos_id) if self.eos_id is not None \
            else jnp.array(False)
        done = hit_eos | (length >= limit)
        new_state = {
            "tokens": state["tokens"].at[slot].set(tok),
            "positions": state["positions"].at[slot].set(length),
            "done": state["done"].at[slot].set(done),
            "limits": state["limits"].at[slot].set(limit),
            "key": key,
        }
        return cache, new_state, (tok, done)

    # cache trees ride the copy / suffix / spec executables as ONE
    # donated pytree so a spec engine's draft cache moves with the big
    # model's — one dispatch, one donation story
    def _cache_tree(self):
        if self.draft is not None:
            return (self._cache, self._draft_cache)
        return self._cache

    def _set_cache_tree(self, tree):
        if self.draft is not None:
            self._cache, self._draft_cache = tree
        else:
            self._cache = tree

    def _copy_blocks(self, caches, src_slots, src_rows, dst_slot):
        """Traced matched-path copy: row r of ``dst_slot`` becomes row
        src_rows[r] of slot src_slots[r] (shape (max_seq,), so the
        executable never depends on the match length).  Rows past the
        matched prefix are encoded by the caller as identity
        coordinates.  ONE gather per leaf, inlined into the
        suffix-prefill executables — a prefix-hit admission is ONE
        dispatch, same as a miss, or the copy overhead eats the reuse
        win."""
        from ..ops import attention as _att
        return _att.gather_cache_rows(caches, src_slots, src_rows,
                                      dst_slot)

    def _suffix_fn(self, params, cache, state, suffix, src_slots,
                   src_rows, slot, start, length, limit):
        """Prefix-cache admission, fused: copy the matched KV block
        path into rows [0, start) of ``slot``, then run only the
        ``length``-token suffix (padded to its bucket) and sample from
        its last real row."""
        pt, qt = params
        full = (_quantize.dequantize_params(pt, qt, self._qdtypes)
                if qt else pt)
        cache = self._copy_blocks(cache, src_slots, src_rows, slot)
        key, kf, ks = jax.random.split(state["key"], 3)
        (logits, cache), _ = _functional.functional_call(
            self.model, full, suffix[None, :], cache, slot, start,
            rng_key=kf, method="prefill_suffix")
        tok = self._sample(logits[0, length - 1][None, :], ks)[0]
        end = start + length
        hit_eos = (tok == self.eos_id) if self.eos_id is not None \
            else jnp.array(False)
        done = hit_eos | (end >= limit)
        new_state = {
            "tokens": state["tokens"].at[slot].set(tok),
            "positions": state["positions"].at[slot].set(end),
            "done": state["done"].at[slot].set(done),
            "limits": state["limits"].at[slot].set(limit),
            "key": key,
        }
        return cache, new_state, (tok, done)

    def _prefill_spec_fn(self, params, dparams, caches, state, prompt,
                         slot, length, limit):
        """Spec-mode prefill: the prompt also runs through the draft so
        its cache holds the same context the big model's does."""
        cache, dcache = caches
        pt, qt = params
        full = (_quantize.dequantize_params(pt, qt, self._qdtypes)
                if qt else pt)
        key, kf, ks = jax.random.split(state["key"], 3)
        (logits, cache), _ = _functional.functional_call(
            self.model, full, prompt[None, :], cache, slot,
            rng_key=kf, method="prefill")
        (_, dcache), _ = _functional.functional_call(
            self.draft, dparams, prompt[None, :], dcache, slot,
            rng_key=kf, method="prefill")
        tok = self._sample(logits[0, length - 1][None, :], ks)[0]
        hit_eos = (tok == self.eos_id) if self.eos_id is not None \
            else jnp.array(False)
        done = hit_eos | (length >= limit)
        new_state = {
            "tokens": state["tokens"].at[slot].set(tok),
            "positions": state["positions"].at[slot].set(length),
            "done": state["done"].at[slot].set(done),
            "limits": state["limits"].at[slot].set(limit),
            "key": key,
        }
        return (cache, dcache), new_state, (tok, done)

    def _suffix_spec_fn(self, params, dparams, caches, state, suffix,
                        src_slots, src_rows, slot, start, length,
                        limit):
        caches = self._copy_blocks(caches, src_slots, src_rows, slot)
        cache, dcache = caches
        pt, qt = params
        full = (_quantize.dequantize_params(pt, qt, self._qdtypes)
                if qt else pt)
        key, kf, ks = jax.random.split(state["key"], 3)
        (logits, cache), _ = _functional.functional_call(
            self.model, full, suffix[None, :], cache, slot, start,
            rng_key=kf, method="prefill_suffix")
        (_, dcache), _ = _functional.functional_call(
            self.draft, dparams, suffix[None, :], dcache, slot, start,
            rng_key=kf, method="prefill_suffix")
        tok = self._sample(logits[0, length - 1][None, :], ks)[0]
        end = start + length
        hit_eos = (tok == self.eos_id) if self.eos_id is not None \
            else jnp.array(False)
        done = hit_eos | (end >= limit)
        new_state = {
            "tokens": state["tokens"].at[slot].set(tok),
            "positions": state["positions"].at[slot].set(end),
            "done": state["done"].at[slot].set(done),
            "limits": state["limits"].at[slot].set(limit),
            "key": key,
        }
        return (cache, dcache), new_state, (tok, done)

    def _spec_fn(self, params, dparams, caches, state):
        """One speculative round, ONE dispatch: the draft proposes k
        tokens greedily against its own cache, then the big model
        verifies all k in a single batched ``decode_multi`` call.

        Acceptance is the standard greedy rule — proposal i stands iff
        every earlier proposal matched the big model's argmax — and the
        first disagreement is replaced by the big model's own token, so
        the emitted stream is token-for-token the non-speculative greedy
        output.  A slot emits between 1 and k tokens per round (0 when
        already done); rows written past the accepted point are garbage
        the next round overwrites before anything attends to them."""
        cache, dcache = caches
        pt, qt = params
        full = (_quantize.dequantize_params(pt, qt, self._qdtypes)
                if qt else pt)
        n, k = self.max_slots, self._spec_k
        key, kf = jax.random.split(state["key"], 2)
        pos0 = state["positions"]
        cur = state["tokens"]
        drafts = []
        for i in range(k):
            (dlogits, dcache), _ = _functional.functional_call(
                self.draft, dparams, cur[:, None], dcache, pos0 + i,
                rng_key=kf, method="decode_step")
            cur = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            drafts.append(cur)
        d = jnp.stack(drafts, axis=1)                      # (n, k)
        seq = jnp.concatenate([state["tokens"][:, None], d[:, :k - 1]],
                              axis=1)
        (logits, cache), _ = _functional.functional_call(
            self.model, full, seq, cache, pos0,
            rng_key=kf, method="decode_multi")
        b = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (n, k)
        ones = jnp.ones((n, 1), bool)
        ok = jnp.concatenate(
            [ones, jnp.cumprod((d[:, :k - 1] == b[:, :k - 1])
                               .astype(jnp.int32), axis=1).astype(bool)],
            axis=1)
        pos_i = pos0[:, None] + 1 + jnp.arange(k)[None, :]
        hit_eos = (b == self.eos_id) if self.eos_id is not None \
            else jnp.zeros(b.shape, bool)
        stop = hit_eos | (pos_i >= state["limits"][:, None])
        before_stop = jnp.concatenate(
            [ones, jnp.cumprod((~stop[:, :k - 1]).astype(jnp.int32),
                               axis=1).astype(bool)], axis=1)
        live = ~state["done"]
        valid = ok & before_stop & live[:, None]
        toks = jnp.where(valid, b, -1)
        nvalid = valid.sum(axis=1)          # >= 1 for every live slot
        last = jnp.maximum(nvalid - 1, 0)[:, None]
        last_tok = jnp.take_along_axis(b, last, axis=1)[:, 0]
        last_stop = jnp.take_along_axis(stop, last, axis=1)[:, 0]
        new_done = state["done"] | (live & last_stop)
        new_state = {
            "tokens": jnp.where(live, last_tok, state["tokens"]),
            "positions": jnp.where(live, pos0 + nvalid, pos0),
            "done": new_done,
            "limits": state["limits"],
            "key": key,
        }
        return (cache, dcache), new_state, (toks, new_done)

    def _decode_exe(self):
        exe = self._exe.get("decode")
        if exe is None:
            def build():
                jitted = jax.jit(self._decode_fn, donate_argnums=(1, 2))
                return jitted, (_sds(self._params), _sds(self._cache),
                                _sds(self._state))
            exe = self._exe["decode"] = self._compile("decode", build)
        return exe

    def _prefill_exe(self, bucket):
        key = ("prefill", bucket)
        exe = self._exe.get(key)
        if exe is None:
            def build():
                scalar = jax.ShapeDtypeStruct((), jnp.int32)
                prompt = jax.ShapeDtypeStruct((bucket,), jnp.int32)
                if self.draft is not None:
                    jitted = jax.jit(self._prefill_spec_fn,
                                     donate_argnums=(2, 3))
                    return jitted, (_sds(self._params),
                                    _sds(self._draft_params),
                                    _sds(self._cache_tree()),
                                    _sds(self._state), prompt,
                                    scalar, scalar, scalar)
                jitted = jax.jit(self._prefill_fn, donate_argnums=(1, 2))
                return jitted, (_sds(self._params), _sds(self._cache),
                                _sds(self._state), prompt,
                                scalar, scalar, scalar)
            exe = self._exe[key] = self._compile(f"prefill_{bucket}", build)
        return exe

    def _suffix_exe(self, bucket):
        key = ("suffix", bucket)
        exe = self._exe.get(key)
        if exe is None:
            def build():
                scalar = jax.ShapeDtypeStruct((), jnp.int32)
                suffix = jax.ShapeDtypeStruct((bucket,), jnp.int32)
                vec = jax.ShapeDtypeStruct((self.max_seq,), jnp.int32)
                if self.draft is not None:
                    jitted = jax.jit(self._suffix_spec_fn,
                                     donate_argnums=(2, 3))
                    return jitted, (_sds(self._params),
                                    _sds(self._draft_params),
                                    _sds(self._cache_tree()),
                                    _sds(self._state), suffix,
                                    vec, vec,
                                    scalar, scalar, scalar, scalar)
                jitted = jax.jit(self._suffix_fn, donate_argnums=(1, 2))
                return jitted, (_sds(self._params), _sds(self._cache),
                                _sds(self._state), suffix,
                                vec, vec,
                                scalar, scalar, scalar, scalar)
            exe = self._exe[key] = self._compile(f"suffix_{bucket}", build)
        return exe

    def _spec_exe(self):
        exe = self._exe.get("spec")
        if exe is None:
            def build():
                jitted = jax.jit(self._spec_fn, donate_argnums=(2, 3))
                return jitted, (_sds(self._params),
                                _sds(self._draft_params),
                                _sds(self._cache_tree()),
                                _sds(self._state))
            exe = self._exe["spec"] = self._compile("spec", build)
        return exe

    def warmup(self):
        """Compile the full executable grid: decode (or the speculative
        propose+verify round when a draft is attached) + one prefill per
        bucket, plus one fused block-copy + suffix-prefill per bucket
        when the prefix cache is on. After this the engine never
        compiles again for any request mix whose prompts fit the
        buckets — the recompile-guard regression test pins that down."""
        if self.draft is not None:
            self._spec_exe()
        else:
            self._decode_exe()
        for b in self.buckets:
            self._prefill_exe(b)
        if self._prefix is not None:
            for b in self.buckets:
                self._suffix_exe(b)
        self._warmed = True
        return self

    # -- scheduling ------------------------------------------------------

    def bucket_for(self, length):
        for b in self.buckets:
            if length <= b:
                return b
        raise MXNetError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]} (serve.buckets, max_seq={self.max_seq})")

    def submit(self, prompt, max_new_tokens=32, eos_id="engine",
               slo_class=None):
        """Enqueue one request; returns its :class:`Request` handle.
        Admission happens inside :meth:`step` when a slot frees up.
        ``slo_class`` names one of ``serve.slo_classes`` (priority
        admission); ``None`` takes the lowest-priority (last) class."""
        prompt = [int(t) for t in onp.asarray(prompt).reshape(-1)]
        if not prompt:
            raise MXNetError("empty prompt")
        self.bucket_for(len(prompt))  # validate now, not at admission
        cls = self._classes[-1] if slo_class is None else str(slo_class)
        if cls not in self._class_rank:
            raise MXNetError(
                f"unknown slo_class {cls!r} (serve.slo_classes: "
                f"{', '.join(self._classes)})")
        if self._stopping:
            if _telemetry._active:
                _telemetry.inc("serve.rejected_total", reason="stopping")
            raise EngineBusy("stopping", len(self._queue), self._max_queue,
                             retry_after_hint=self._retry_after_hint())
        if self._max_queue and len(self._queue) >= self._max_queue:
            if _telemetry._active:
                _telemetry.inc("serve.rejected_total", reason="queue_full")
            raise EngineBusy("queue_full", len(self._queue), self._max_queue,
                             retry_after_hint=self._retry_after_hint())
        bound = self._class_bounds.get(cls, 0)
        if bound and sum(1 for r in self._queue
                         if r.slo_class == cls) >= bound:
            if _telemetry._active:
                _telemetry.inc("serve.rejected_total",
                               reason="class_queue_full")
            raise EngineBusy("class_queue_full", len(self._queue), bound,
                             retry_after_hint=self._retry_after_hint())
        req = Request(self._next_id, prompt, max_new_tokens,
                      self.eos_id if eos_id == "engine" else eos_id,
                      slo_class=cls)
        self._next_id += 1
        self._queue.append(req)
        if _trace._active:
            req._span = _trace.begin("serve.request", category="serve",
                                     request=req.id,
                                     prompt_tokens=len(prompt))
            req._enq = _trace.begin("serve.enqueue", category="serve",
                                    parent=req._span.context,
                                    request=req.id)
        if _telemetry._active:
            _telemetry.inc("serve.requests_total")
            _telemetry.set_gauge("serve.queue_depth", len(self._queue))
        return req

    def _finish(self, req):
        req.finished = True
        req.t_done = time.perf_counter()
        if req.slot is not None:
            self._slots[req.slot] = None
            self._free.append(req.slot)
            self._free.sort(reverse=True)
            req.slot = None
        if req._nodes:
            # unpin the request's radix path — its blocks become
            # LRU-evictable again
            self._prefix.release(list(req._nodes))
            req._nodes = ()
        self._completed.append(req)
        if req._enq is not None:  # finished without ever being admitted
            req._enq.end()
            req._enq = None
        if req._span is not None:
            req._span.end(tokens=len(req.generated))
            req._span = None
        if _telemetry._active:
            _telemetry.inc("serve.completed_total")
            _telemetry.inc("serve.tokens_total", len(req.generated))
            if req.tpot is not None:
                _telemetry.observe("serve.tpot_seconds", req.tpot)
                _telemetry.observe("serve.class_tpot_seconds", req.tpot,
                                   slo_class=req.slo_class)
        if self._slo_tpot and req.tpot is not None:
            self._slo_observe("tpot", req.tpot > self._slo_tpot,
                              req.slo_class)

    def _prefill_sink(self, req):
        def sink(fetched):
            t0u = _profiler.now_us() if _trace._active else 0
            span_ctx = req._span.context if req._span is not None else None
            tok, done = int(fetched[0]), bool(fetched[1])
            req.t_first = time.perf_counter()
            req.generated.append(tok)
            if _telemetry._active and req.ttft is not None:
                _telemetry.observe("serve.ttft_seconds", req.ttft)
                _telemetry.observe("serve.class_ttft_seconds", req.ttft,
                                   slo_class=req.slo_class)
            if self._slo_ttft and req.ttft is not None:
                self._slo_observe("ttft", req.ttft > self._slo_ttft,
                                  req.slo_class)
            if done:
                self._finish(req)
            if _trace._active and span_ctx is not None:
                _trace.emit("serve.drain", t0u, _profiler.now_us() - t0u,
                            parent=span_ctx, category="serve",
                            request=req.id, first_token=True)
        return sink

    def _decode_sink(self, slot_map):
        def sink(fetched):
            t0u = _profiler.now_us() if _trace._active else 0
            toks, done = fetched
            for slot, req in slot_map.items():
                if req.finished:
                    continue  # finished in an older entry of this window
                span_ctx = (req._span.context
                            if req._span is not None else None)
                tok = int(toks[slot])
                if tok >= 0:
                    req.generated.append(tok)
                if bool(done[slot]):
                    self._finish(req)
                if _trace._active and span_ctx is not None and tok >= 0:
                    _trace.emit("serve.drain", t0u,
                                _profiler.now_us() - t0u,
                                parent=span_ctx, category="serve",
                                request=req.id)
        return sink

    def _next_request(self):
        """Dequeue under strict class priority (``serve.slo_classes``
        order, FIFO within a class), with the starvation-aging escape
        hatch: once a request waits past ``serve.class_aging_ms`` it
        competes on age alone, so a saturated high class cannot starve
        the low classes forever."""
        q = self._queue
        if len(self._classes) == 1 or len(q) == 1:
            return q.popleft()
        best, best_rank = None, len(self._classes)
        for r in q:
            rank = self._class_rank[r.slo_class]
            if rank < best_rank:
                best, best_rank = r, rank
                if rank == 0:
                    break
        req = best
        if self._aging:
            now = time.perf_counter()
            aged = [r for r in q if (now - r.t_submit) >= self._aging]
            if aged:
                oldest = min(aged, key=lambda r: r.t_submit)
                if oldest is not best:
                    req = oldest
                    self._aged_admissions += 1
                    if _telemetry._active:
                        _telemetry.inc("serve.aged_admissions_total")
        q.remove(req)
        return req

    def _pick_slot(self):
        """Free-slot choice.  Without the prefix cache: lowest slot
        (the original behaviour).  With it: the *coldest* free slot —
        the one whose newest indexed block is oldest, never-indexed
        first — so admissions overwrite the least-reusable KV rows."""
        if self._prefix is None or len(self._free) == 1:
            return self._free.pop()
        slot = min(self._free,
                   key=lambda s: (self._prefix.slot_heat(s), s))
        self._free.remove(slot)
        return slot

    def _spec_sink(self, slot_map):
        """Drain sink for a speculative round: each live slot carries up
        to k token ids (-1 padded past the accepted point).  Acceptance
        accounting happens here, host-side — a live slot always emits at
        least one token (the big model's own), so ``emitted - 1`` is the
        number of draft proposals that survived the verify."""
        def sink(fetched):
            t0u = _profiler.now_us() if _trace._active else 0
            toks, done = fetched
            k, proposed, accepted = self._spec_k, 0, 0
            for slot, req in slot_map.items():
                if req.finished:
                    continue  # finished in an older entry of this window
                span_ctx = (req._span.context
                            if req._span is not None else None)
                emitted = [int(t) for t in toks[slot] if int(t) >= 0]
                req.generated.extend(emitted)
                if emitted:
                    # rows with no emit were already done on device —
                    # the draft proposed nothing real for them
                    proposed += k
                    accepted += len(emitted) - 1
                if bool(done[slot]):
                    self._finish(req)
                if _trace._active and span_ctx is not None and emitted:
                    _trace.emit("serve.drain", t0u,
                                _profiler.now_us() - t0u,
                                parent=span_ctx, category="serve",
                                request=req.id, tokens=len(emitted))
            self._spec_proposed += proposed
            self._spec_accepted += accepted
            if _telemetry._active and proposed:
                _telemetry.inc("serve.spec_proposed_total", proposed)
                _telemetry.inc("serve.spec_accepted_total", accepted)
                _telemetry.set_gauge(
                    "serve.spec_acceptance_rate",
                    round(self._spec_accepted
                          / max(1, self._spec_proposed), 4))
        return sink

    def _admit(self):
        admitted = 0
        while self._queue and self._free:
            self._dispatch_prefill(self._next_request(), self._pick_slot())
            admitted += 1
        return admitted

    def _dispatch_prefill(self, req, slot):
        """Admit ``req`` into ``slot``.  With the prefix cache on, the
        longest indexed prompt prefix is row-copied from its donor slot
        (block granular) and only the suffix runs through prefill; the
        whole prompt is then (re)indexed under this slot and pinned
        until the request finishes."""
        length = len(req.prompt)
        limit = min(length + req.max_new_tokens - 1, self.max_seq - 1)
        t0u = _profiler.now_us() if _trace._active else 0
        t0p = time.perf_counter()
        nodes, start, sbucket = (), 0, None
        if self._prefix is not None:
            nodes = tuple(self._prefix.match(req.prompt))
            if nodes and _fault._active \
                    and _fault.fire("serve.prefix_evict"):
                # chaos: the matched prefix vanishes between match and
                # copy — the engine must fall back to a full prefill
                dropped = self._prefix.evict_path(list(nodes))
                if dropped and _telemetry._active:
                    _telemetry.inc("serve.prefix_evictions_total", dropped)
                nodes = ()
            if nodes:
                start = len(nodes) * self._prefix_block
                sbucket = self.bucket_for(length - start)
                if start + sbucket > self.max_seq:
                    # the padded suffix would overrun the cache rows
                    nodes, start, sbucket = (), 0, None
        if nodes:
            # the destination slot's stale rows leave the index first
            evicted = self._prefix.evict_slot(slot)
            if evicted and _telemetry._active:
                _telemetry.inc("serve.prefix_evictions_total", evicted)
            # per-row source coordinates for the matched prefix; rows
            # past it are identity (dest slot, own row) — untouched
            blk = self._prefix_block
            src_slots = onp.full((self.max_seq,), slot, dtype=onp.int32)
            src_rows = onp.arange(self.max_seq, dtype=onp.int32)
            for i, node in enumerate(nodes):
                src_slots[i * blk:(i + 1) * blk] = node.slot
                src_rows[i * blk:(i + 1) * blk] = onp.arange(
                    node.row, node.row + blk, dtype=onp.int32)
            suffix = req.prompt[start:]
            padded = onp.zeros((sbucket,), dtype=onp.int32)
            padded[:len(suffix)] = suffix
            exe = self._suffix_exe(sbucket)
            if self.draft is not None:
                tree, self._state, emit = exe(
                    self._params, self._draft_params, self._cache_tree(),
                    self._state, jnp.asarray(padded),
                    jnp.asarray(src_slots), jnp.asarray(src_rows),
                    jnp.int32(slot), jnp.int32(start),
                    jnp.int32(len(suffix)), jnp.int32(limit))
                self._set_cache_tree(tree)
            else:
                self._cache, self._state, emit = exe(
                    self._params, self._cache, self._state,
                    jnp.asarray(padded),
                    jnp.asarray(src_slots), jnp.asarray(src_rows),
                    jnp.int32(slot), jnp.int32(start),
                    jnp.int32(len(suffix)), jnp.int32(limit))
            req.prefix_tokens = start
            self._prefix.hits += 1
            self._prefix.tokens_reused += start
            bucket = sbucket
            if _telemetry._active:
                _telemetry.inc("serve.prefix_hits_total")
                _telemetry.inc("serve.prefix_tokens_reused_total", start)
        else:
            if self._prefix is not None:
                evicted = self._prefix.evict_slot(slot)
                if evicted and _telemetry._active:
                    _telemetry.inc("serve.prefix_evictions_total",
                                   evicted)
                self._prefix.misses += 1
                if _telemetry._active:
                    _telemetry.inc("serve.prefix_misses_total")
            bucket = self.bucket_for(length)
            padded = onp.zeros((bucket,), dtype=onp.int32)
            padded[:length] = req.prompt
            exe = self._prefill_exe(bucket)
            if self.draft is not None:
                tree, self._state, emit = exe(
                    self._params, self._draft_params, self._cache_tree(),
                    self._state, jnp.asarray(padded), jnp.int32(slot),
                    jnp.int32(length), jnp.int32(limit))
                self._set_cache_tree(tree)
            else:
                self._cache, self._state, emit = exe(
                    self._params, self._cache, self._state,
                    jnp.asarray(padded), jnp.int32(slot),
                    jnp.int32(length), jnp.int32(limit))
        if self._prefix is not None:
            path = self._prefix.insert(req.prompt, slot)
            self._prefix.acquire(path)
            req._nodes = tuple(path)
            if _telemetry._active:
                _telemetry.set_gauge("serve.prefix_blocks",
                                     len(self._prefix))
        req.slot = slot
        req.t_admitted = time.perf_counter()
        if req._enq is not None:
            req._enq.end()
            req._enq = None
        if _trace._active and req._span is not None:
            duru = _profiler.now_us() - t0u
            _trace.emit("serve.prefill", t0u, duru,
                        parent=req._span.context, category="serve",
                        request=req.id, slot=slot, bucket=bucket,
                        prefix_tokens=req.prefix_tokens)
        if _trace._active or self._phase_cap:
            self._phase_note(req, "queue_wait",
                             req.t_admitted - req.t_submit)
            self._phase_note(req, "prefill",
                             req.t_admitted - t0p)
        self._slots[slot] = req
        self._window.push(emit, self._prefill_sink(req))
        if _telemetry._active:
            _telemetry.inc("serve.admitted_total")
            _telemetry.inc("serve.prefill_tokens_total", bucket)

    # -- the serve loop --------------------------------------------------

    def step(self):
        """One continuous-batching iteration: free slots via bounded
        drain when the queue is starved, admit, dispatch ONE decode step
        for every live slot, defer the result. Returns False when fully
        idle (nothing queued, running, or pending drain)."""
        self._last_step_time = time.monotonic()
        if self._queue and not self._free and len(self._window):
            # starved for slots: reclaim just enough, oldest first —
            # one per queued request, so a deep queue refills the whole
            # slot grid in one step instead of trickling one admission
            # per decode dispatch
            self._window.drain_oldest(min(len(self._queue),
                                          len(self._window)))
        admitted = self._admit()
        live = {i: r for i, r in enumerate(self._slots) if r is not None}
        if _telemetry._active:
            _telemetry.set_gauge("serve.queue_depth", len(self._queue))
            _telemetry.set_gauge("serve.slot_occupancy", len(live))
            if len(self._classes) > 1:
                depth = {c: 0 for c in self._classes}
                for r in self._queue:
                    depth[r.slo_class] += 1
                for c, v in depth.items():
                    _telemetry.set_gauge("serve.class_queue_depth", v,
                                         slo_class=c)
        if not live:
            if len(self._window):
                self._window.drain()
                return True
            return admitted > 0
        if self.draft is not None:
            exe = self._spec_exe()
            t0 = time.perf_counter()
            tree, self._state, emit = exe(
                self._params, self._draft_params, self._cache_tree(),
                self._state)
            self._set_cache_tree(tree)
            self._spec_rounds += 1
        else:
            exe = self._decode_exe()
            t0 = time.perf_counter()
            self._cache, self._state, emit = exe(
                self._params, self._cache, self._state)
        dt = time.perf_counter() - t0
        self._steps += 1
        if _servefleet._active:
            _servefleet.note_step(self)
        if _telemetry._active:
            _telemetry.inc("serve.steps_total")
            _telemetry.observe("serve.step_seconds", dt)
            if self.draft is not None:
                _telemetry.inc("serve.spec_rounds_total")
        if _trace._active:
            # one span per live request per step: the dispatch wall time
            # was measured anyway, so re-stamp it on the shared clock
            duru = int(dt * 1e6)
            t0u = _profiler.now_us() - duru
            for slot, req in live.items():
                if req._span is not None:
                    _trace.emit("serve.decode_step", t0u, duru,
                                parent=req._span.context,
                                category="serve", request=req.id,
                                slot=slot, step=self._steps)
                self._phase_note(req, "decode_step", dt)
        elif self._phase_cap:
            for req in live.values():
                self._phase_note(req, "decode_step", dt)
        sink = self._spec_sink(live) if self.draft is not None \
            else self._decode_sink(live)
        self._window.push(emit, sink)
        return True

    def _phase_note(self, req, key, val):
        """Per-request phase sample: unbounded while the tracer runs
        (the PR 9 behaviour), else capped at ``serve.phase_sampling``
        samples per phase so stats()["phases"] stays populated in
        production at a bounded cost."""
        lst = req.phases.setdefault(key, [])
        if _trace._active or len(lst) < self._phase_cap:
            lst.append(val)

    def drain(self):
        """Fetch every deferred emit (host sync); completions land."""
        self._window.drain()

    @property
    def pending(self):
        return bool(self._queue or len(self._window)
                    or any(s is not None for s in self._slots))

    def run(self, max_steps=None):
        """Drive :meth:`step` until every submitted request finished (or
        ``max_steps`` decode steps elapsed), then drain. The continuous-
        batching main loop for offline/batch use; online callers own the
        loop and call ``step()`` themselves."""
        steps = 0
        try:
            while self.pending:
                self.step()
                steps += 1
                if max_steps is not None and steps >= max_steps:
                    break
            self.drain()
        except Exception as e:
            # the serving loop is the long-running production surface:
            # freeze the evidence window with engine state attached
            # before the exception unwinds (the excepthook dedupes on
            # the same exception object, so this is the one bundle)
            from .. import blackbox as _blackbox
            if _blackbox._active:
                _blackbox.set_context(serve={
                    "decode_steps": steps,
                    "queued": len(self._queue),
                    "live_slots": sum(1 for s in self._slots
                                      if s is not None),
                    "completed": len(self._completed)})
                _blackbox.dump(trigger="manual",
                               reason=f"serve.run fatal: "
                                      f"{type(e).__name__}: {e}", exc=e)
            raise
        return self

    # -- shutdown / liveness ---------------------------------------------

    def stop(self, drain=True):
        """Graceful shutdown.  From the moment this is called,
        :meth:`submit` raises :class:`EngineBusy` ("stopping").

        ``drain=True`` finishes every in-flight AND queued request (runs
        the step loop to completion) before returning; ``drain=False``
        discards still-queued requests (each counted in
        ``serve.rejected_total``) and only fetches the already-dispatched
        deferred emits, leaving in-flight slots unfinished.  Either way
        the engine's /healthz provider is unregistered.  Idempotent."""
        if self._stopping:
            return self
        self._stopping = True
        tok = _goodput.begin("drain") if _goodput._active else None
        try:
            if drain:
                self.run()
            else:
                while self._queue:
                    self._reject(self._queue.popleft(), "stopping")
                self.drain()
        finally:
            _goodput.end(tok)
            _telemetry.unregister_health(self._health_name)
        return self

    # -- rolling weight updates (mx.servefleet) --------------------------

    def update_weights(self, params):
        """Swap the engine's weights in place with a new flat
        ``{name: jax.Array}`` tree (the :func:`mxnet_tpu.functional.
        param_arrays` layout) and return the previous ``(passthrough,
        quantized)`` tuple for :meth:`restore_weights` rollback.

        The new tree is pushed through the SAME quantization mode the
        engine was built with and validated structurally — names, shapes
        and dtypes must match what the AOT executables were compiled
        against, so the swap never invalidates the compiled grid and a
        subsequent :meth:`warmup` is a cache hit (zero compiles).  The
        KV cache is untouched: callers drain in-flight requests first
        (``stop(drain=True)``) because tokens decoded under the old
        weights must not continue under the new ones."""
        pt, qt, qdt = self._quantize_weights(dict(params))
        old_pt, old_qt = self._params

        def _sig(tree):
            return {k: (tuple(v.shape), str(v.dtype))
                    for k, v in tree.items()}
        for label, new, old in (("passthrough", pt, old_pt),
                                ("quantized", qt, old_qt)):
            if _sig(new) != _sig(old):
                missing = sorted(set(old) - set(new))
                extra = sorted(set(new) - set(old))
                changed = sorted(
                    k for k in set(new) & set(old)
                    if (tuple(new[k].shape), str(new[k].dtype))
                    != (tuple(old[k].shape), str(old[k].dtype)))
                raise MXNetError(
                    f"update_weights: incoming {label} params do not "
                    f"match the tree the engine compiled against "
                    f"(missing={missing[:4]}, extra={extra[:4]}, "
                    f"changed={changed[:4]}) — the compiled grid would "
                    "be invalid; build a fresh engine for a different "
                    "architecture")
        self._params = (pt, qt)
        self._qdtypes = qdt
        return (old_pt, old_qt)

    def restore_weights(self, old):
        """Roll back to a ``(passthrough, quantized)`` tuple previously
        returned by :meth:`update_weights` — the canary auto-rollback
        path.  No validation: the tuple came from this engine."""
        self._params = old
        return self

    def resume(self):
        """Re-open a drained engine after a rolling weight update:
        clears the stopping latch (submit() admits again) and
        re-registers the /healthz provider that :meth:`stop`
        unregistered.  The compiled grid, KV cache and slot machinery
        are untouched."""
        self._stopping = False
        self._register_health()
        return self

    def _slo_observe(self, kind, violated, slo_class="default"):
        """Account one request against the declared SLO objective of
        ``kind`` — the drain-time observation point the burn gauge and
        autoscaler admission signal ride."""
        self._slo_events.append(
            (time.monotonic(), kind, bool(violated), slo_class))
        if violated and _telemetry._active:
            _telemetry.inc("serve.slo_violations_total", kind=kind,
                           slo_class=slo_class)

    def slo_burn(self, window=300.0):
        """Per-kind error-budget burn rate over the trailing ``window``
        seconds: violation rate over the budget ``1 - serve.slo_target``
        (1.0 spends the budget exactly).  {} until an objective is
        armed and a request has been observed."""
        budget = 1.0 - float(_config.get("serve.slo_target"))
        if budget <= 0:
            return {}
        cut = time.monotonic() - window
        out = {}
        for kind, armed in (("ttft", self._slo_ttft),
                            ("tpot", self._slo_tpot)):
            if not armed:
                continue
            hits = [v for (t, k, v, _c) in self._slo_events
                    if k == kind and t >= cut]
            if not hits:
                continue
            burn = (sum(hits) / len(hits)) / budget
            out[kind] = round(burn, 4)
            if _telemetry._active:
                _telemetry.set_gauge("serve.slo_burn_rate",
                                     round(burn, 4), kind=kind)
        return out

    def _tpot_p50(self):
        """Observed TPOT p50 over the most recent completions — the unit
        of the EngineBusy ``retry_after_hint``. Falls back to the armed
        SLO objective (the declared cadence) before any request has
        finished, then to a conservative 20ms guess."""
        tpots = sorted(r.tpot for r in self._completed[-256:]
                       if r.tpot is not None)
        if tpots:
            return tpots[len(tpots) // 2]
        return self._slo_tpot if self._slo_tpot else 0.02

    def _retry_after_hint(self):
        return self._tpot_p50() * max(1, len(self._queue))

    def _reject(self, req, reason):
        """Account a queued request discarded by stop(drain=False): its
        spans close (rejected=True), ``req.rejected``/``req.reject_reason``
        flip so a waiting caller observes a structured outcome, and it
        never reaches a slot."""
        req.rejected = True
        req.reject_reason = reason
        if req._enq is not None:
            req._enq.end()
            req._enq = None
        if req._span is not None:
            req._span.end(rejected=True)
            req._span = None
        if _telemetry._active:
            _telemetry.inc("serve.rejected_total", reason=reason)

    def _health(self):
        """/healthz provider: red while stopping, and red when the engine
        has pending work but the step loop has not dispatched within
        ``serve.health_window`` seconds (a wedged or abandoned loop — the
        condition a static-OK healthz could never see); red as well when
        a declared serving SLO's error budget burns past
        ``goodput.burn_threshold`` — the 503 the autoscaler consumes."""
        if self._stopping:
            return {"ok": False, "state": "stopping"}
        if self._slo_ttft or self._slo_tpot:
            burn = self.slo_burn()
            thresh = float(_config.get("goodput.burn_threshold"))
            if burn and max(burn.values()) > thresh:
                return {"ok": False, "state": "slo_burn", "burn": burn,
                        "threshold": thresh}
        if not self.pending:
            return {"ok": True, "state": "idle", "steps": self._steps}
        last = (self._last_step_time if self._last_step_time is not None
                else self._created)
        age = time.monotonic() - last
        window = _config.get("serve.health_window")
        return {"ok": age < window, "state": "serving",
                "steps": self._steps, "last_step_age_s": round(age, 3)}

    # -- reporting -------------------------------------------------------

    def stats(self):
        """Host-side aggregate: counts, tokens, latency percentiles (from
        per-request records — telemetry histograms carry the bucketed
        view when enabled)."""
        done = self._completed
        ttfts = sorted(r.ttft for r in done if r.ttft is not None)
        tpots = sorted(r.tpot for r in done if r.tpot is not None)

        def pct(vals, q):
            if not vals:
                return None
            return float(onp.percentile(vals, q))

        out = {
            "completed": len(done),
            "queued": len(self._queue),
            "live": sum(1 for s in self._slots if s is not None),
            "steps": self._steps,
            "tokens_out": sum(len(r.generated) for r in done),
            "compiles": self.compiles,
            "post_warmup_compiles": self.post_warmup_compiles,
            "max_slots": self.max_slots,
            "max_seq": self.max_seq,
            "buckets": list(self.buckets),
            "quantize": self.quantize,
            "cache_dtype": self.cache_dtype,
        }
        for name, vals in (("ttft", ttfts), ("tpot", tpots)):
            out[name] = {"p50": pct(vals, 50), "p95": pct(vals, 95),
                         "p99": pct(vals, 99)}
        # per-request phase breakdown: unbounded trace instrumentation
        # while mx.trace records, else the bounded always-on reservoir
        # (serve.phase_sampling; None per phase only when both are off)
        phases = {}
        for key, label in (("queue_wait", "queue_wait"),
                           ("prefill", "prefill"),
                           ("decode_step", "decode_per_token")):
            vals = sorted(v for r in done for v in r.phases.get(key, ()))
            phases[label] = None if not vals else {
                "p50": pct(vals, 50), "p95": pct(vals, 95),
                "p99": pct(vals, 99)}
        out["phases"] = phases
        if self._slo_ttft or self._slo_tpot:
            viol = {}
            for (_t, kind, v, _c) in self._slo_events:
                if v:
                    viol[kind] = viol.get(kind, 0) + 1
            out["slo"] = {
                "ttft_ms": self._slo_ttft * 1e3 if self._slo_ttft else None,
                "tpot_ms": self._slo_tpot * 1e3 if self._slo_tpot else None,
                "target": float(_config.get("serve.slo_target")),
                "burn": self.slo_burn(),
                "violations": viol,
            }
        if self.quantize:
            pt, qt = self._params
            now, was = _quantize.quantized_bytes(pt, qt, self._qdtypes)
            out["weight_bytes"] = now
            out["weight_bytes_fp"] = was
            out["quantized_params"] = len(qt)
            out["passthrough_params"] = len(pt)
        if self._prefix is not None:
            out["prefix"] = self._prefix.stats()
        if self.draft is not None:
            rate = (self._spec_accepted / self._spec_proposed
                    if self._spec_proposed else None)
            out["spec"] = {
                "k": self._spec_k,
                "rounds": self._spec_rounds,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "acceptance_rate": None if rate is None
                else round(rate, 4),
            }
        if len(self._classes) > 1 or self._aging:
            per = {}
            for cls in self._classes:
                rs = [r for r in done if r.slo_class == cls]
                ct = sorted(r.ttft for r in rs if r.ttft is not None)
                cp = sorted(r.tpot for r in rs if r.tpot is not None)
                per[cls] = {
                    "completed": len(rs),
                    "queued": sum(1 for r in self._queue
                                  if r.slo_class == cls),
                    "ttft": {"p50": pct(ct, 50), "p99": pct(ct, 99)},
                    "tpot": {"p50": pct(cp, 50), "p99": pct(cp, 99)},
                }
            out["classes"] = per
            out["aged_admissions"] = self._aged_admissions
        return out

    @property
    def prefix_hits(self):
        """Host counter of prefix-cache admission hits — the per-replica
        number mx.servefleet snapshots into /servefleet and report()."""
        return self._prefix.hits if self._prefix is not None else 0

    @property
    def spec_acceptance(self):
        """Trailing draft-acceptance ratio, None without a draft or
        before the first speculative round drained."""
        if self.draft is None or not self._spec_proposed:
            return None
        return self._spec_accepted / self._spec_proposed


def load(model, max_slots=None, quantize=None, warmup=False, **kwargs):
    """Build a :class:`ServeEngine` over ``model``.

    ``quantize`` enables low-bit decode storage — "int8_weights",
    "int4_weights", "int8_kv", comma-combinable (docs/SERVING.md);
    ``warmup=True`` compiles the full bucket grid before returning so
    the first request never pays a compile.  ``prefix_cache=True`` (or
    ``serve.prefix_cache=1``) turns on radix prefix-cache KV reuse;
    ``draft=small_model`` turns on speculative decoding (greedy-exact,
    ``serve.spec_tokens`` proposals per round).
    """
    eng = ServeEngine(model, max_slots=max_slots, quantize=quantize,
                      **kwargs)
    if warmup:
        eng.warmup()
    return eng

"""Object-detection image pipeline: Det* augmenters + ImageDetIter.

Reference parity: python/mxnet/image/detection.py (DetAugmenter :40,
DetBorrowAug :66, DetRandomSelectAug :91, DetHorizontalFlipAug :127,
DetRandomCropAug :153, DetRandomPadAug :324, CreateMultiRandCropAugmenter
:418, CreateDetAugmenter :483, ImageDetIter :625).

TPU-native design: labels are plain numpy (host metadata — proposal
rejection sampling is inherently host control flow, same as the
reference), while every pixel operation is a device op. A crop is ONE
fused crop-and-resize gather (image.py ``_affine_crop_resize``), padding
is one masked-canvas op, and ``ImageDetIter`` splits the chain at the
force-resize: the geometric prefix runs per sample (labels are coupled to
each sample's random window), then the photometric tail — color jitter,
lighting, normalize — runs as batched device passes over the stacked
(N,H,W,C) tensor exactly like the classification iterator.
"""
from __future__ import annotations

import json
import logging
import random as pyrandom

import jax.numpy as jnp
import numpy as onp

from .base import MXNetError
from .numpy.multiarray import ndarray, _wrap
from . import image as _img

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


def _npimg(src):
    """-> (H, W, C) jnp array."""
    return src._data if isinstance(src, ndarray) else jnp.asarray(src)


class DetAugmenter:
    """Detection augmenter base (reference: detection.py:40): takes
    (image, label) and returns both — label rows are
    [cls, xmin, ymin, xmax, ymax, ...] with normalized coordinates."""

    def __init__(self, **kwargs):
        self._kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, ndarray):
                v = v.asnumpy()
            if isinstance(v, onp.ndarray):
                v = v.tolist()
            self._kwargs[k] = v

    def dumps(self):
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Borrow a label-invariant classification augmenter
    (reference: detection.py:66)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, _img.Augmenter):
            raise TypeError("Borrowing from invalid Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def dumps(self):
        return [type(self).__name__.lower(), self.augmenter.dumps()]

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter, with a chance to skip all
    (reference: detection.py:91)."""

    def __init__(self, aug_list, skip_prob=0):
        super().__init__(skip_prob=skip_prob)
        if not isinstance(aug_list, (list, tuple)):
            aug_list = [aug_list]
        for aug in aug_list:
            if not isinstance(aug, DetAugmenter):
                raise ValueError("Allow DetAugmenter in list only")
        if not aug_list:
            skip_prob = 1
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def dumps(self):
        return [type(self).__name__.lower(),
                [x.dumps() for x in self.aug_list]]

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image + labels with probability p
    (reference: detection.py:127)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = _wrap(_npimg(src)[:, ::-1])
            label = label.copy()
            tmp = 1.0 - label[:, 1]
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


def _areas(label):
    """(K, 4+) corner boxes -> areas (reference: _calculate_areas)."""
    h = onp.maximum(0, label[:, 3] - label[:, 1])
    w = onp.maximum(0, label[:, 2] - label[:, 0])
    return h * w


class DetRandomCropAug(DetAugmenter):
    """IOU-constrained random crop (reference: detection.py:153).

    Proposal search is host-side numpy (cheap label math); the accepted
    crop applies as one fused device crop (``image.fixed_crop``)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.enabled = (area_range[1] > 0 and
                        area_range[0] <= area_range[1] and
                        aspect_ratio_range[0] <= aspect_ratio_range[1] and
                        aspect_ratio_range[0] > 0)
        if not self.enabled:
            logging.warning("DetRandomCropAug disabled: invalid ranges")

    def __call__(self, src, label):
        img = _npimg(src)
        crop = self._random_crop_proposal(label, img.shape[0], img.shape[1])
        if crop:
            x, y, w, h, label = crop
            src = _img.fixed_crop(_wrap(img), x, y, w, h, None)
        return src, label

    def _intersect(self, label, xmin, ymin, xmax, ymax):
        left = onp.maximum(label[:, 0], xmin)
        right = onp.minimum(label[:, 2], xmax)
        top = onp.maximum(label[:, 1], ymin)
        bot = onp.minimum(label[:, 3], ymax)
        invalid = (left >= right) | (top >= bot)
        out = label.copy()
        out[:, 0], out[:, 1], out[:, 2], out[:, 3] = left, top, right, bot
        out[invalid, :] = 0
        return out

    def _check_satisfy_constraints(self, label, xmin, ymin, xmax, ymax,
                                   width, height):
        if (xmax - xmin) * (ymax - ymin) < 2:
            return False
        x1, y1 = float(xmin) / width, float(ymin) / height
        x2, y2 = float(xmax) / width, float(ymax) / height
        object_areas = _areas(label[:, 1:])
        valid = onp.where(object_areas * width * height > 2)[0]
        if valid.size < 1:
            return False
        inter = self._intersect(label[valid, 1:], x1, y1, x2, y2)
        cov = _areas(inter) / object_areas[valid]
        cov = cov[cov > 0]
        return cov.size > 0 and onp.amin(cov) > self.min_object_covered

    def _update_labels(self, label, crop_box, height, width):
        xmin = float(crop_box[0]) / width
        ymin = float(crop_box[1]) / height
        w = float(crop_box[2]) / width
        h = float(crop_box[3]) / height
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] - xmin) / w
        out[:, (2, 4)] = (out[:, (2, 4)] - ymin) / h
        out[:, 1:5] = onp.clip(out[:, 1:5], 0, 1)
        coverage = _areas(out[:, 1:]) * w * h / _areas(label[:, 1:])
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2]) & \
            (coverage > self.min_eject_coverage)
        if not valid.any():
            return None
        return out[valid, :]

    def _random_crop_proposal(self, label, height, width):
        from math import sqrt
        if not self.enabled or height <= 0 or width <= 0:
            return ()
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(max_h * ratio) > width:
                max_h = int((width + 0.4999999) / ratio)
            max_h = min(max_h, height)
            h = min(h, max_h)
            if h < max_h:
                h = pyrandom.randint(h, max_h)
            w = int(round(h * ratio))
            area = w * h
            if area < min_area:
                h += 1
                w = int(round(h * ratio))
                area = w * h
            if area > max_area:
                h -= 1
                w = int(round(h * ratio))
                area = w * h
            if not (min_area <= area <= max_area and
                    0 <= w <= width and 0 <= h <= height):
                continue
            y = pyrandom.randint(0, max(0, height - h))
            x = pyrandom.randint(0, max(0, width - w))
            if self._check_satisfy_constraints(label, x, y, x + w, y + h,
                                               width, height):
                new_label = self._update_labels(label, (x, y, w, h),
                                                height, width)
                if new_label is not None:
                    return (x, y, w, h, new_label)
        return ()


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding (reference: detection.py:324): place the
    image in a larger pad_val canvas — one masked device op."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (list, tuple)):
            pad_val = (pad_val,)
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0 and
                        area_range[0] <= area_range[1] and
                        aspect_ratio_range[0] > 0 and
                        aspect_ratio_range[0] <= aspect_ratio_range[1])
        if not self.enabled:
            logging.warning("DetRandomPadAug disabled: invalid ranges")

    def __call__(self, src, label):
        img = _npimg(src)
        height, width = img.shape[0], img.shape[1]
        pad = self._random_pad_proposal(label, height, width)
        if pad:
            x, y, w, h, label = pad
            canvas = jnp.broadcast_to(
                jnp.asarray(self.pad_val, img.dtype),
                (h, w, img.shape[2])) if len(self.pad_val) > 1 else \
                jnp.full((h, w, img.shape[2]),
                         self.pad_val[0], img.dtype)
            src = _wrap(canvas.at[y:y + height, x:x + width].set(img))
        return src, label

    def _update_labels(self, label, pad_box, height, width):
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] * width + pad_box[0]) / pad_box[2]
        out[:, (2, 4)] = (out[:, (2, 4)] * height + pad_box[1]) / pad_box[3]
        return out

    def _random_pad_proposal(self, label, height, width):
        from math import sqrt
        if not self.enabled or height <= 0 or width <= 0:
            return ()
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(h * ratio) < width:
                h = int((width + 0.499999) / ratio)
            h = max(h, height)
            h = min(h, max_h)
            if h < max_h:
                h = pyrandom.randint(h, max_h)
            w = int(round(h * ratio))
            if (h - height) < 2 or (w - width) < 2:
                continue
            y = pyrandom.randint(0, max(0, h - height))
            x = pyrandom.randint(0, max(0, w - width))
            new_label = self._update_labels(label, (x, y, w, h),
                                            height, width)
            return (x, y, w, h, new_label)
        return ()


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """Multiple crop augmenters under one random selector
    (reference: detection.py:418)."""
    def align(params):
        out, num = [], 1
        for p in params:
            if not isinstance(p, list):
                p = [p]
            out.append(p)
            num = max(num, len(p))
        for k, p in enumerate(out):
            if len(p) != num:
                assert len(p) == 1
                out[k] = p * num
        return out

    aligned = align([min_object_covered, aspect_ratio_range, area_range,
                     min_eject_coverage, max_attempts])
    augs = [DetRandomCropAug(min_object_covered=moc, aspect_ratio_range=arr,
                             area_range=ar, min_eject_coverage=mec,
                             max_attempts=ma)
            for moc, arr, ar, mec, ma in zip(*aligned)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection augmentation chain (reference: detection.py:483);
    same stage order: resize, crop, mirror, pad, force-resize, cast, then
    the photometric tail (which ImageDetIter batches on device)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range, area_range,
            min_eject_coverage, max_attempts, skip_prob=(1 - rand_crop)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad_aug = DetRandomPadAug(aspect_ratio_range,
                                  (1.0, area_range[1]), max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad_aug], 1 - rand_pad))
    auglist.append(DetBorrowAug(
        _img.ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(_img.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            _img.ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(_img.HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(
            _img.LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(_img.RandomGrayAug(rand_gray)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(_img.ImageIter):
    """Detection data iterator (reference: detection.py:625).

    Labels use the reference's packed format
    ``[header_w, obj_w, ..., (cls, x1, y1, x2, y2, ...)*]``; batches carry
    (B, max_objects, obj_w) labels padded with -1. The geometric prefix
    of the augmenter chain (everything up to and including the
    force-resize) runs per sample (labels are coupled to each sample's
    random window); the photometric tail runs as batched device passes."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="label", **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         label_width=1, path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        self.auglist = (CreateDetAugmenter(data_shape, **kwargs)
                        if aug_list is None else aug_list)
        # split point for the batched photometric tail: the maximal
        # DetBorrowAug-only SUFFIX (label-coupled augmenters anywhere in
        # the chain stay per-sample), pushed past the force-resize stage —
        # stacking needs the shape-unifying resize in the per-sample
        # prefix
        start = len(self.auglist)
        for i in range(len(self.auglist) - 1, -1, -1):
            if not isinstance(self.auglist[i], DetBorrowAug):
                break
            start = i
        for i, aug in enumerate(self.auglist):
            if isinstance(aug, DetBorrowAug) and \
                    isinstance(aug.augmenter, _img.ForceResizeAug):
                start = max(start, i + 1)
        self._batch_tail_start = start
        label_shape = self._estimate_label_shape()
        self.label_shape = label_shape
        self.provide_label = [(label_name,
                               (batch_size,) + tuple(label_shape))]
        self.provide_data = [(data_name, (batch_size,) + tuple(data_shape))]

    # -- label parsing (reference: detection.py:718) ----------------------
    def _parse_label(self, label):
        raw = onp.asarray(label).ravel()
        if raw.size < 7:
            raise MXNetError(f"Label shape is invalid: {raw.shape}")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if (raw.size - header_width) % obj_width != 0:
            raise MXNetError(
                f"Label shape {raw.shape} inconsistent with annotation "
                f"width {obj_width}.")
        out = onp.reshape(raw[header_width:], (-1, obj_width))
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
        if not valid.any():
            raise MXNetError("Encounter sample with no valid label.")
        return out[valid, :].astype(onp.float32)

    def _check_valid_label(self, label):
        if label.ndim != 2 or label.shape[1] < 5:
            raise MXNetError(
                f"Label with shape (1+, 5+) required, {label} received.")
        valid = (label[:, 0] >= 0) & (label[:, 3] > label[:, 1]) & \
            (label[:, 4] > label[:, 2])
        if not valid.any():
            raise MXNetError("Invalid label occurs.")

    def _estimate_label_shape(self):
        max_count, obj_w = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self._next_sample()
                parsed = self._parse_label(label)
                max_count = max(max_count, parsed.shape[0])
                obj_w = parsed.shape[1]
        except StopIteration:
            pass
        self.reset()
        return (max_count, obj_w)

    def _next_sample(self):
        """Full label vector (not truncated to label_width)."""
        from . import recordio as rio
        if self.record is not None:
            if self.seq is not None:
                if self._cursor >= len(self.seq):
                    raise StopIteration
                s = self.record.read_idx(self.seq[self._cursor])
                self._cursor += 1
            else:
                s = self.record.read()
                if s is None:
                    raise StopIteration
            header, img = rio.unpack(s)
            return onp.array(header.label), img
        if self._cursor >= len(self.seq):
            raise StopIteration
        label, fname = self.imglist[self.seq[self._cursor]]
        self._cursor += 1
        import os
        with open(os.path.join(self.path_root, fname), "rb") as f:
            return onp.asarray(label), f.read()

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.provide_data = [(self.provide_data[0][0],
                                  (self.batch_size,) + tuple(data_shape))]
            self.data_shape = tuple(data_shape)
            # retarget the chain's force-resize stage so the augmented
            # pixels actually match the new provide_data contract
            for aug in self.auglist:
                if isinstance(aug, DetBorrowAug) and \
                        isinstance(aug.augmenter, _img.ForceResizeAug):
                    aug.augmenter.size = (data_shape[2], data_shape[1])
        if label_shape is not None:
            self.check_label_shape(label_shape)
            self.provide_label = [(self.provide_label[0][0],
                                   (self.batch_size,) + tuple(label_shape))]
            self.label_shape = tuple(label_shape)

    def check_label_shape(self, label_shape):
        if not len(label_shape) == 2:
            raise ValueError("label_shape should have length 2")
        if label_shape[0] < self.label_shape[0]:
            raise ValueError(
                f"Attempts to reduce label count from "
                f"{self.label_shape[0]} to {label_shape[0]}, not allowed.")
        if label_shape[1] != self.label_shape[1]:
            raise ValueError(
                f"label_shape object width inconsistent: "
                f"{self.label_shape[1]} vs {label_shape[1]}.")

    def augmentation_transform(self, data, label):
        """Per-sample geometric prefix (reference: detection.py:847)."""
        for aug in self.auglist[:self._batch_tail_start]:
            data, label = aug(data, label)
        return data, label

    def next(self):
        from .io import DataBatch
        bs = self.batch_size
        c, h, w = self.data_shape
        mlab, wlab = self.label_shape
        imgs, labs = [], []
        i = 0
        try:
            while i < bs:
                raw_label, buf = self._next_sample()
                try:
                    img = _wrap(jnp.asarray(
                        _img.imdecode_np(buf, flag=1 if c == 3 else 0)))
                    label = self._parse_label(raw_label)
                    img, label = self.augmentation_transform(img, label)
                    self._check_valid_label(label)
                except MXNetError as e:
                    logging.debug("Invalid sample, skipping: %s", e)
                    continue
                imgs.append(img._data.astype(jnp.float32))
                labs.append(label)
                i += 1
        except StopIteration:
            if not i:
                raise
        pad = bs - i
        batch = jnp.stack(imgs + [jnp.zeros_like(imgs[0])] * pad)
        # batched photometric tail: one device pass over the whole batch
        tail = [a.augmenter for a in self.auglist[self._batch_tail_start:]
                if isinstance(a, DetBorrowAug)]
        if tail:
            batch = _img.apply_batch(tail, _wrap(batch))._data
        batch = jnp.transpose(batch, (0, 3, 1, 2))  # NHWC -> NCHW
        out_lab = onp.full((bs, mlab, wlab), -1.0, onp.float32)
        for j, lab in enumerate(labs):
            k = min(lab.shape[0], mlab)
            out_lab[j, :k, :lab.shape[1]] = lab[:k]
        return DataBatch([_wrap(batch)], [_wrap(jnp.asarray(out_lab))],
                         pad=pad)

    def sync_label_shape(self, it, verbose=False):
        """Synchronize label shape with another iterator
        (reference: detection.py:913)."""
        assert isinstance(it, ImageDetIter)
        train_label_shape = self.label_shape
        val_label_shape = it.label_shape
        assert train_label_shape[1] == val_label_shape[1]
        max_count = max(train_label_shape[0], val_label_shape[0])
        if max_count > train_label_shape[0]:
            self.reshape(None, (max_count, train_label_shape[1]))
        if max_count > val_label_shape[0]:
            it.reshape(None, (max_count, val_label_shape[1]))
        if verbose and max_count > min(train_label_shape[0],
                                       val_label_shape[0]):
            logging.info("Resized label_shape to (%d, %d).",
                         max_count, train_label_shape[1])
        return it

    def draw_next(self, *args, **kwargs):
        raise NotImplementedError(
            "draw_next needs cv2 display; use label/bbox data directly")

"""mx.libinfo — library/feature discovery.

Reference parity: python/mxnet/libinfo.py (find_lib_path locating
libmxnet.so, __version__).  Here the "library" is the set of native
helper .so files built on demand plus the jax substrate; features come
from mx.runtime.
"""
from __future__ import annotations

import os

from . import __version__  # noqa: F401


def find_lib_path(prefix=None):
    """Paths of the native helper libraries that exist/build locally
    (reference: libinfo.py find_lib_path)."""
    from . import native
    out = []
    build = native._build_dir()
    if os.path.isdir(build):
        for f in sorted(os.listdir(build)):
            if f.endswith(".so"):
                out.append(os.path.join(build, f))
    return out


def find_include_path():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")

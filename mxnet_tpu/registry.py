"""mx.registry — generic factory registry for serializable objects.

Reference parity: python/mxnet/registry.py (get_registry /
get_register_func / get_alias_func / get_create_func). The reference keys
one flat dict per base class and hands back closures; 1.x users reach it
directly (``mx.registry.get_create_func(Initializer, 'initializer')``) and
`initializer.py:277-279` builds its register/alias/create triple from it.

This build keeps the same four-function surface but backs each base class
with the shared `base._Registry` (thread-safe, alias-aware) so objects
registered here and objects registered through the framework's own module
registries are one namespace per base class. ``create`` accepts the same
config forms as the reference: an instance (passthrough), a dict, a
``'["name", {kwargs}]'`` json list, a ``'{"nickname": ...}'`` json object,
or a plain registered name.
"""
from __future__ import annotations

import json
import warnings

from .base import MXNetError, _Registry

# one registry per base class; exposed (copied) via get_registry
_REGISTRIES: dict[type, _Registry] = {}


def _registry_for(base_class, nickname=None):
    reg = _REGISTRIES.get(base_class)
    if reg is None:
        reg = _REGISTRIES.setdefault(
            base_class, _Registry(nickname or base_class.__name__.lower()))
    return reg


def get_registry(base_class):
    """Return a copy of ``{name: class}`` registered under `base_class`."""
    return dict(_registry_for(base_class)._map)


def get_register_func(base_class, nickname):
    """Return ``register(klass, name=None)`` for `base_class`.

    Warns (like the reference) when a name is re-registered, then replaces.
    """
    reg = _registry_for(base_class, nickname)

    def register(klass, name=None):
        if not (isinstance(klass, type) and issubclass(klass, base_class)):
            raise MXNetError(
                f"can only register subclasses of {base_class.__name__}, "
                f"got {klass!r}")
        key = (name or klass.__name__).lower()
        prev = reg.find(key)
        if prev is not None and prev is not klass:
            warnings.warn(
                f"new {nickname} {klass.__module__}.{klass.__name__} "
                f"registered with name {key} is overriding existing "
                f"{nickname} {prev.__module__}.{prev.__name__}",
                UserWarning, stacklevel=2)
        reg.register(key)(klass)
        return klass

    return register


def get_alias_func(base_class, nickname):
    """Return a decorator registering a class under several names."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def _reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return _reg

    return alias


def get_create_func(base_class, nickname):
    """Return ``create(...)`` instantiating registered classes from config."""
    reg = _registry_for(base_class, nickname)

    def create(*args, **kwargs):
        if args:
            name, args = args[0], args[1:]
        elif nickname in kwargs:
            name = kwargs.pop(nickname)
        else:
            raise MXNetError(
                f"config must name the {nickname} (positionally or via "
                f"the '{nickname}' key); got keys {sorted(kwargs)}")
        if isinstance(name, base_class):
            if args or kwargs:
                raise MXNetError(
                    f"{nickname} is already an instance; additional "
                    "arguments are invalid")
            return name
        if isinstance(name, dict):
            return create(**name)
        if not isinstance(name, str):
            raise MXNetError(f"{nickname} must be a string, got {name!r}")
        if name.startswith("["):
            if args or kwargs:
                raise MXNetError("json-list config takes no extra arguments")
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.startswith("{"):
            if args or kwargs:
                raise MXNetError("json-dict config takes no extra arguments")
            return create(**json.loads(name))
        klass = reg.find(name)
        if klass is None:
            raise MXNetError(
                f"{name} is not registered. Please register with "
                f"{nickname}.register first. Registered: {reg.list()}")
        return klass(*args, **kwargs)

    return create

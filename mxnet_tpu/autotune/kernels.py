"""Kernel-level autotuning: searched Pallas block/grid shapes.

The step-level search (search.py) picks ``{batch_size, steps_per_call,
...}``; this module tunes the layer below — the tile shapes every
Pallas kernel hard-coded until now (``block_q``/``block_k`` for flash
attention forward and backward, ``block_m``/``block_n`` for the
int8/fp8 matmuls, the ln_residual row tile).  TVM-style
(arXiv 1802.04799): an analytic VMEM-footprint model prunes the block
grid, a cost model — learned (learned.py) when it beats the closed
form on recorded trials, analytic otherwise — ranks the survivors, and
only the predicted-top ``autotune.kernel_trial_fraction`` is measured
with short hermetic trials (same ``trial_compile_scope`` / OOM-survival
discipline as the step search).

Winners persist in the same ``winners.json`` (schema 2, persist.py)
keyed ``kernel|shape_bucket|device_kind`` and load into a
process-global tuned-shape table.  Kernel call sites route through
:func:`resolve_blocks` — a tuned run changes no call signatures, and an
untuned run falls back to a per-``device_kind`` static default table
(one module-dict read on the fast path; gated under the <2% budget by
benchmark/telemetry_overhead.py).

Closing the loop online: :class:`Retuner` arms on ``insight.drift``
events (``autotune.retune_on_drift`` knob), re-searches in a background
thread, and hot-swaps the winner at the next checkpoint boundary via
``ShardedTrainStep.rebuild`` — an ``autotune.retune`` trace span and
the ``autotune.retunes_total`` counter mark every swap.
"""
from __future__ import annotations

import itertools
import math
import threading
import time

from .. import config as _config
from .. import fault as _fault
from .. import goodput as _goodput
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..base import MXNetError
from .cost import (VMEM_BYTES, VMEM_FRACTION, kernel_cost,
                   kernel_tile_bytes)
from .learned import LearnedCostModel, rank_gate
from .persist import (append_trials, kernel_key, load_all, load_trials,
                      save_winner, winners_path)
from .search import TrialOOM, _is_oom, trial_compile_scope
from .space import as_axis

__all__ = ["KERNELS", "resolve_blocks", "shape_bucket", "static_blocks",
           "kernel_candidates", "search_kernels", "load_tuned",
           "kernel_config_summary", "KernelSearchResult", "Retuner",
           "last_kernel_summary", "reset"]

#: the tunable kernels and their block-shape axes (flash attention's
#: forward and backward passes tile independently — the bwd kernels
#: carry twice the accumulator footprint, so their optimum is smaller)
KERNELS = ("flash_attention", "flash_attention_bwd", "quantized_matmul",
           "fp8_matmul", "ln_residual")

_SPACE = {
    "flash_attention": {"block_q": (256, 512, 1024, 2048),
                        "block_k": (128, 256, 512, 1024)},
    "flash_attention_bwd": {"block_q": (256, 512, 1024),
                            "block_k": (128, 256, 512)},
    "quantized_matmul": {"block_m": (64, 128, 256, 512),
                         "block_n": (128, 256, 512)},
    "fp8_matmul": {"block_m": (64, 128, 256, 512),
                   "block_n": (128, 256, 512)},
    "ln_residual": {"block_rows": (64, 128, 256, 512, 1024)},
}

#: per-device_kind static defaults — the no-winner fallback.  The "cpu"
#: row is the interpret-mode path and keeps the historical one-size
#: constants bit-for-bit (CPU CI behavior is unchanged); the TPU rows
#: size tiles to each generation's VMEM/MXU balance: v4 favors smaller
#: q tiles (HBM BW per FLOP is tighter), v6 takes the largest tiles its
#: VMEM fits.
_STATIC_DEFAULTS = {
    "v4": {"flash_attention": {"block_q": 512, "block_k": 512},
           "flash_attention_bwd": {"block_q": 512, "block_k": 512},
           "quantized_matmul": {"block_m": 256, "block_n": 256},
           "fp8_matmul": {"block_m": 256, "block_n": 256},
           "ln_residual": {"block_rows": 256}},
    "v5e": {"flash_attention": {"block_q": 512, "block_k": 512},
            "flash_attention_bwd": {"block_q": 512, "block_k": 256},
            "quantized_matmul": {"block_m": 256, "block_n": 512},
            "fp8_matmul": {"block_m": 256, "block_n": 512},
            "ln_residual": {"block_rows": 512}},
    "v6": {"flash_attention": {"block_q": 2048, "block_k": 1024},
           "flash_attention_bwd": {"block_q": 1024, "block_k": 512},
           "quantized_matmul": {"block_m": 512, "block_n": 512},
           "fp8_matmul": {"block_m": 512, "block_n": 512},
           "ln_residual": {"block_rows": 512}},
    "cpu": {"flash_attention": {"block_q": 1024, "block_k": 512},
            "flash_attention_bwd": {"block_q": 1024, "block_k": 512},
            "quantized_matmul": {"block_m": 256, "block_n": 256},
            "fp8_matmul": {"block_m": 256, "block_n": 256},
            "ln_residual": {"block_rows": 256}},
}

#: process-global tuned-shape table: (kernel, bucket) -> blocks dict.
#: Mutated in place (never rebound) so resolve_blocks' fast path is one
#: truthiness test on a module global.
_TUNED = {}
#: resolved static defaults for THIS process' device family, filled
#: lazily on first resolve (jax backend init is too heavy for import)
_STATIC = {}

#: summary of the most recent kernel search in this process — merged
#: into the "autotune" plane of TrainingTelemetry run reports
_LAST_KERNELS = None


def _device_family(device_kind=None):
    """Map a device kind onto a static-default row (v4 / v5e / v6 /
    cpu).  v5p sizes like v6 (same-generation VMEM); v2/v3 take the v4
    row (closest conservative tiling); unknown TPUs take v5e."""
    if device_kind is None:
        import jax
        devs = jax.devices()
        if not devs or devs[0].platform not in ("tpu", "axon"):
            return "cpu"
        device_kind = getattr(devs[0], "device_kind", "")
    k = str(device_kind).lower()
    if "v6" in k or "v5p" in k:
        return "v6"
    if "v5" in k or "lite" in k:
        return "v5e"
    if "v4" in k or "v3" in k or "v2" in k:
        return "v4"
    return "v5e" if "tpu" in k else "cpu"


def static_blocks(kernel, device_kind=None):
    """The per-device_kind static default blocks for ``kernel`` (the
    untuned fallback)."""
    if kernel not in _SPACE:
        raise MXNetError(f"unknown kernel {kernel!r}; one of {KERNELS}")
    return dict(_STATIC_DEFAULTS[_device_family(device_kind)][kernel])


def _init_static():
    fam = _STATIC_DEFAULTS[_device_family()]
    for kern, blocks in fam.items():
        _STATIC[kern] = dict(blocks)
    return _STATIC


def _p2(n):
    return 1 << max(0, int(n) - 1).bit_length()


def shape_bucket(kernel, shape):
    """Bucket a problem shape: every searched dim rounds up to a power
    of two, so one measured winner covers the whole bucket (tile choice
    is insensitive to small shape deltas; a 2x shape change re-tunes)."""
    if kernel in ("flash_attention", "flash_attention_bwd"):
        sq, sk, d = shape
        return (_p2(sq), _p2(sk), int(d))
    if kernel in ("quantized_matmul", "fp8_matmul"):
        m, n, k = shape
        return (_p2(m), _p2(n), _p2(k))
    if kernel == "ln_residual":
        rows, dim = shape
        return (_p2(rows), int(dim))
    raise MXNetError(f"unknown kernel {kernel!r}; one of {KERNELS}")


def resolve_blocks(kernel, shape=None):
    """Blocks for one kernel call: the tuned winner for the shape's
    bucket when one is loaded, else the per-device static default.

    This is the routing every kernel call site takes at TRACE time (the
    resolved values are static python ints baked into the jitted
    executable) — the untuned fast path is one module-dict truthiness
    test plus one dict read, gated <2% by the CI overhead budget.
    """
    if _TUNED and shape is not None:
        rec = _TUNED.get((kernel, shape_bucket(kernel, shape)))
        if rec is not None:
            return rec
    blocks = _STATIC.get(kernel)
    if blocks is not None:
        return blocks
    return _init_static()[kernel]


def _clamped(kernel, bucket, blocks):
    """The effective blocks after the kernel's own shape clamps — used
    to dedup candidates that compile identically on a small bucket."""
    b = dict(blocks)
    if kernel in ("flash_attention", "flash_attention_bwd"):
        sq, sk, _d = bucket
        return (min(b["block_q"], sq), min(b["block_k"], sk))
    if kernel in ("quantized_matmul", "fp8_matmul"):
        m, n, _k = bucket
        return (min(b["block_m"], -(-m // 32) * 32),
                min(b["block_n"], -(-n // 128) * 128))
    rows, _dim = bucket
    br = min(b["block_rows"], max(8, rows))
    return ((br + 7) // 8 * 8,)


def kernel_candidates(kernel, bucket=None, axes=None):
    """Enumerate the block grid for one kernel, deterministic order.
    With a ``bucket``, candidates whose clamped effective tiles coincide
    are deduped (first wins) — on small problems most of the grid
    collapses.  ``axes`` overrides any axis, e.g. ``{"block_q": (128,
    256)}``."""
    if kernel not in _SPACE:
        raise MXNetError(f"unknown kernel {kernel!r}; one of {KERNELS}")
    space = dict(_SPACE[kernel])
    for name, vals in (axes or {}).items():
        if name not in space:
            raise MXNetError(f"{kernel} has no block axis {name!r}")
        space[name] = as_axis(vals)
    names = sorted(space)
    out, seen = [], set()
    for vals in itertools.product(*(space[n] for n in names)):
        blocks = dict(zip(names, (int(v) for v in vals)))
        if bucket is not None:
            eff = _clamped(kernel, bucket, blocks)
            if eff in seen:
                continue
            seen.add(eff)
        out.append(blocks)
    return out


def reset():
    """Drop every loaded/tuned winner and the last kernel summary (test
    isolation; the static defaults are device facts and survive)."""
    global _LAST_KERNELS
    _TUNED.clear()
    _LAST_KERNELS = None


def last_kernel_summary():
    """Summary of the most recent kernel search in this process (None
    when none ran) — merged into run reports via search.last_summary."""
    return _LAST_KERNELS


def load_tuned(path=None, device_kind=None):
    """Load persisted kernel winners for this device kind into the
    process-global table; returns the number of entries loaded."""
    if device_kind is None:
        import jax
        devs = jax.devices()
        device_kind = (getattr(devs[0], "device_kind", "cpu") if devs
                       else "cpu")
    n = 0
    for key, rec in load_all(path).items():
        if not isinstance(rec, dict) or rec.get("kind") != "kernel":
            continue
        if rec.get("device_kind") != device_kind:
            continue
        kern = rec.get("kernel")
        bucket = rec.get("bucket")
        blocks = rec.get("blocks")
        if kern in _SPACE and isinstance(blocks, dict) and bucket:
            _TUNED[(kern, tuple(int(d) for d in bucket))] = {
                k: int(v) for k, v in blocks.items()}
            n += 1
    return n


def kernel_config_summary():
    """The resolved block shapes per kernel (static defaults overlaid
    with any loaded tuned winners) plus the tuned-bucket count — what
    bench.py stamps on train/decode rows as ``kernel_config``."""
    out = {}
    try:
        for kern in KERNELS:
            out[kern] = dict(resolve_blocks(kern))
    except Exception:
        return {}
    for (kern, _bucket), blocks in sorted(_TUNED.items()):
        out[kern] = dict(blocks)
    out["tuned_buckets"] = len(_TUNED)
    return out


# ---------------------------------------------------------------------------
# measured trials
# ---------------------------------------------------------------------------

#: default representative problem shapes per kernel (CPU CI keeps them
#: tiny — interpret-mode trials are Python-speed; a TPU run tunes real
#: production geometry)
def default_shapes(device_kind=None):
    if _device_family(device_kind) == "cpu":
        return {"flash_attention": [(128, 128, 64)],
                "flash_attention_bwd": [(128, 128, 64)],
                "quantized_matmul": [(128, 128, 128)],
                "fp8_matmul": [(128, 128, 128)],
                "ln_residual": [(256, 128)]}
    return {"flash_attention": [(2048, 2048, 128)],
            "flash_attention_bwd": [(2048, 2048, 128)],
            "quantized_matmul": [(1024, 1024, 4096)],
            "fp8_matmul": [(1024, 1024, 4096)],
            "ln_residual": [(4096, 1024)]}


class _Owner:
    """Compile-count owner for trial_compile_scope (the kernel tuner
    has no Block to charge trial compiles to)."""


_OWNER = _Owner()


def _make_trial_fn(kernel, bucket, interpret):
    """Build inputs once for a bucket and return ``fn(blocks) ->
    seconds-per-call`` timing the REAL kernel (jit + block_until_ready),
    hermetic: fresh arrays, no model state touched."""
    import numpy as onp
    import jax
    import jax.numpy as jnp

    rs = onp.random.RandomState(0)
    if kernel in ("flash_attention", "flash_attention_bwd"):
        from ..ops.pallas.flash_attention import flash_attention
        sq, sk, d = bucket
        q = jnp.asarray(rs.randn(1, 2, sq, d), jnp.float32)
        k = jnp.asarray(rs.randn(1, 2, sk, d), jnp.float32)
        v = jnp.asarray(rs.randn(1, 2, sk, d), jnp.float32)

        def build(blocks):
            if kernel == "flash_attention":
                def f(q_, k_, v_):
                    return flash_attention(q_, k_, v_, causal=True,
                                           interpret=interpret, **blocks)
            else:
                def f(q_, k_, v_):
                    def loss(qq):
                        return flash_attention(
                            qq, k_, v_, causal=True, interpret=interpret,
                            bwd_block_q=blocks["block_q"],
                            bwd_block_k=blocks["block_k"]).sum()
                    return jax.grad(loss)(q_)
            return jax.jit(f), (q, k, v)
    elif kernel in ("quantized_matmul", "fp8_matmul"):
        m, n, kk = bucket
        x = jnp.asarray(rs.randn(m, kk), jnp.float32)
        ws = jnp.asarray(onp.abs(rs.randn(n)) / 127.0 + 1e-4, jnp.float32)
        xs = jnp.float32(0.05)
        if kernel == "quantized_matmul":
            from ..ops.pallas.quant_matmul import quantized_matmul as mm
            w = jnp.asarray(rs.randint(-127, 128, (n, kk)), jnp.int8)
        else:
            from ..ops.pallas.quant_matmul import (FP8_FORMATS,
                                                   fp8_matmul as mm)
            w = jnp.asarray(rs.randn(n, kk), FP8_FORMATS["e4m3"][0])

        def build(blocks):
            def f(x_, w_, ws_, xs_):
                return mm(x_, w_, ws_, xs_, interpret=interpret, **blocks)
            return jax.jit(f), (x, w, ws, xs)
    elif kernel == "ln_residual":
        from ..ops.pallas.ln_residual import ln_residual_dropout
        rows, dim = bucket
        x = jnp.asarray(rs.randn(rows, dim), jnp.float32)
        h = jnp.asarray(rs.randn(rows, dim), jnp.float32)
        g = jnp.ones((dim,), jnp.float32)
        b = jnp.zeros((dim,), jnp.float32)

        def build(blocks):
            def f(x_, h_, g_, b_):
                return ln_residual_dropout(x_, h_, g_, b_,
                                           interpret=interpret, **blocks)
            return jax.jit(f), (x, h, g, b)
    else:
        raise MXNetError(f"unknown kernel {kernel!r}")

    def run(blocks, trial_seconds, warmup, max_calls=50):
        fn, args = build(blocks)
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))    # compile
        _telemetry.note_compile(_OWNER, f"autotune.kernel:{kernel}",
                                time.perf_counter() - t0)
        for _ in range(max(0, warmup - 1)):
            fn(*args)
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        pilot = max(time.perf_counter() - t0, 1e-7)
        calls = min(max_calls, max(1, math.ceil(trial_seconds / pilot)))
        t0 = time.perf_counter()
        out = None
        for _ in range(calls):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / calls

    return run


class KernelSearchResult:
    """Outcome of one :func:`search_kernels` call: per-(kernel, bucket)
    winners, the raw trials, and what the ranking model was."""

    def __init__(self, device_kind):
        self.device_kind = device_kind
        self.searches = []       # per-bucket dicts
        self.trials = []         # raw trial records
        self.tuned = {}          # (kernel, bucket) -> blocks
        self.cache_hits = 0
        self.ranked_by = "analytic"
        self.learned_corr = None
        self.analytic_corr = None
        self.wall_s = 0.0

    @property
    def n_trials(self):
        return len(self.trials)

    def summary(self):
        out = {"device_kind": self.device_kind,
               "searches": self.searches,
               "trials": len(self.trials),
               "cache_hits": self.cache_hits,
               "ranked_by": self.ranked_by,
               "wall_s": round(self.wall_s, 3),
               "kernel_trials": self.trials}
        if self.learned_corr is not None:
            out["learned_rank_corr"] = round(self.learned_corr, 4)
            out["analytic_rank_corr"] = round(self.analytic_corr, 4)
        return out


def search_kernels(kernels=None, shapes=None, measure=None, force=False,
                   persist=True, publish=True, trial_seconds=None,
                   warmup=None, fraction=None, use_learned=True,
                   interpret=None, telemetry_jsonl=None):
    """Search tuned block shapes for ``kernels`` over ``shapes``.

    ``shapes`` maps kernel -> problem-shape list (defaults to one
    representative shape per kernel); each distinct shape bucket gets
    its own search.  ``measure(kernel, bucket, blocks) -> seconds``
    injects a deterministic backend (tests/chaos); the real path times
    jitted kernel calls hermetically under ``trial_compile_scope``.
    Winners persist to winners.json (schema 2) and — with ``publish`` —
    load into the process-global table immediately; the drift Retuner
    passes ``publish=False`` and applies at a checkpoint boundary.
    """
    global _LAST_KERNELS
    t_start = time.perf_counter()
    import jax
    devs = jax.devices()
    device_kind = getattr(devs[0], "device_kind", "cpu") if devs else "cpu"
    if interpret is None:
        interpret = not devs or devs[0].platform not in ("tpu", "axon")
    if fraction is None:
        fraction = float(_config.get("autotune.kernel_trial_fraction"))
    if trial_seconds is None:
        trial_seconds = float(_config.get("autotune.kernel_trial_seconds"))
    if warmup is None:
        warmup = int(_config.get("autotune.trial_warmup"))
    kernels = tuple(kernels) if kernels else KERNELS
    for kern in kernels:
        if kern not in _SPACE:
            raise MXNetError(f"unknown kernel {kern!r}; one of {KERNELS}")
    if shapes is None:
        shapes = default_shapes(device_kind)
    path = winners_path()
    result = KernelSearchResult(device_kind)

    # the learned model trains on every recorded trial this host can
    # see: the winners-file ring plus (optionally) a fleet-aggregated
    # TrainingTelemetry JSONL
    records = list(load_trials(path)) if persist else []
    if telemetry_jsonl:
        from .learned import load_telemetry_records
        records.extend(load_telemetry_records(telemetry_jsonl))
    model = LearnedCostModel()
    use_model = False
    if use_learned and records:
        model.fit(records)
        use_model, lc, ac = rank_gate(model, records)
        result.learned_corr, result.analytic_corr = lc, ac
        _telemetry.set_gauge("autotune.learned_rank_corr", lc)
    result.ranked_by = "learned" if use_model else "analytic"

    vmem_budget = int(VMEM_BYTES * VMEM_FRACTION)
    root = _trace.begin("autotune.kernel_search", category="autotune",
                        kernels=",".join(kernels)) if _trace._active else None

    with trial_compile_scope(_OWNER):
        for kern in kernels:
            for shape in shapes.get(kern, ()):
                bucket = shape_bucket(kern, shape)
                key = kernel_key(kern, bucket, device_kind)
                if persist and not force:
                    rec = load_all(path).get(key)
                    if rec is not None and isinstance(
                            rec.get("blocks"), dict):
                        blocks = {k: int(v)
                                  for k, v in rec["blocks"].items()}
                        if publish:
                            _TUNED[(kern, bucket)] = blocks
                        result.tuned[(kern, bucket)] = blocks
                        result.cache_hits += 1
                        result.searches.append(
                            {"key": key, "reused": True, "blocks": blocks})
                        _telemetry.inc("autotune.kernel_cache_hits_total")
                        continue

                cands = kernel_candidates(kern, bucket)
                _telemetry.inc("autotune.candidates_total", len(cands))
                kept, n_vmem = [], 0
                for blocks in cands:
                    if kernel_tile_bytes(kern, bucket,
                                         blocks) > vmem_budget:
                        n_vmem += 1
                        _telemetry.inc("autotune.pruned_total",
                                       reason="vmem")
                    else:
                        kept.append(blocks)
                if not kept:          # degenerate budget: keep the default
                    kept = [static_blocks(kern, device_kind)]
                if use_model:
                    kept.sort(key=lambda b: model.predict(kern, bucket, b))
                else:
                    kept.sort(key=lambda b: kernel_cost(kern, bucket, b))
                n_measure = max(1, int(fraction * len(kept)))
                default = static_blocks(kern, device_kind)
                eff_default = _clamped(kern, bucket, default)
                chosen = kept[:n_measure]
                if not any(_clamped(kern, bucket, b) == eff_default
                           for b in chosen):
                    # the static default always gets a measured baseline;
                    # it replaces the worst-ranked pick so the fraction
                    # cap holds
                    chosen[-1] = default
                for blocks in kept[len(chosen):]:
                    _telemetry.inc("autotune.pruned_total",
                                   reason="ranked_out")

                trial_fn = None
                trials_here = []
                for blocks in chosen:
                    sp = _trace.begin(
                        "autotune.trial", category="autotune",
                        parent=(root.context if root else None),
                        kernel=kern, **blocks) if _trace._active else None
                    t0 = time.perf_counter()
                    rec = {"kernel": kern, "bucket": list(bucket),
                           "blocks": dict(blocks),
                           "device_kind": device_kind, "status": "ok",
                           "created": time.time()}
                    try:
                        if _fault._active and _fault.fire(
                                "autotune.trial_oom"):
                            raise TrialOOM(
                                f"injected OOM for {kern}{blocks}")
                        if measure is not None:
                            sec = float(measure(kern, bucket, blocks))
                        else:
                            if trial_fn is None:
                                trial_fn = _make_trial_fn(kern, bucket,
                                                          interpret)
                            sec = trial_fn(blocks, trial_seconds, warmup)
                        rec["seconds"] = sec
                    except Exception as e:
                        rec["status"] = ("oom" if _is_oom(e) else "error")
                        rec["error"] = f"{type(e).__name__}: {e}"[:300]
                        if rec["status"] == "oom":
                            _telemetry.inc("autotune.trials_oom_total")
                            _fault.record("autotune.trial_oom")
                    rec["wall_s"] = round(time.perf_counter() - t0, 4)
                    if sp is not None:
                        sp.end(status=rec["status"],
                               seconds=rec.get("seconds", 0.0))
                    _telemetry.inc("autotune.kernel_trials_total")
                    trials_here.append(rec)
                result.trials.extend(trials_here)

                ok = [t for t in trials_here if t["status"] == "ok"]
                if not ok:
                    result.searches.append(
                        {"key": key, "reused": False, "blocks": None,
                         "trials": len(trials_here)})
                    continue
                best = min(ok, key=lambda t: t["seconds"])
                dflt = next((t for t in ok
                             if _clamped(kern, bucket, t["blocks"])
                             == eff_default), None)
                speedup = (dflt["seconds"] / best["seconds"]
                           if dflt and best["seconds"] > 0 else None)
                blocks = dict(best["blocks"])
                result.tuned[(kern, bucket)] = blocks
                if publish:
                    _TUNED[(kern, bucket)] = blocks
                result.searches.append(
                    {"key": key, "reused": False, "blocks": blocks,
                     "trials": len(trials_here),
                     "seconds": round(best["seconds"], 6),
                     "speedup_vs_default": (round(speedup, 4)
                                            if speedup else None)})
                if speedup:
                    _telemetry.set_gauge("autotune.best_speedup", speedup)
                if persist:
                    save_winner(key, {"kind": "kernel", "kernel": kern,
                                      "bucket": list(bucket),
                                      "blocks": blocks,
                                      "seconds": best["seconds"],
                                      "speedup_vs_default": speedup,
                                      "device_kind": device_kind,
                                      "created": time.time()}, path)
    if root is not None:
        root.end(trials=len(result.trials))
    if persist and result.trials:
        append_trials(result.trials, path)
    result.wall_s = time.perf_counter() - t_start
    _telemetry.observe("autotune.search_seconds", result.wall_s)
    _LAST_KERNELS = result.summary()
    return result


# ---------------------------------------------------------------------------
# drift-triggered online re-tuning
# ---------------------------------------------------------------------------

class Retuner:
    """Online re-tune state machine: ARMED -> (insight.drift) ->
    SEARCHING (background thread) -> STAGED -> (checkpoint boundary)
    -> swap via ``ShardedTrainStep.rebuild`` -> ARMED.

    The drift hook only fires a search when ``autotune.retune_on_drift``
    is on and no search is already in flight; the winner is never
    applied mid-step — :meth:`checkpoint` publishes the staged table
    and re-jits the step at the caller's checkpoint boundary, so the
    loss trajectory continues uninterrupted on the same weights and
    ``_n_step``.
    """

    def __init__(self, kernels=None, shapes=None, measure=None,
                 trial_seconds=None, fraction=None):
        self._kw = dict(kernels=kernels, shapes=shapes, measure=measure,
                        trial_seconds=trial_seconds, fraction=fraction,
                        force=True, publish=False)
        self._lock = threading.Lock()
        self._thread = None
        self._staged = None
        self._armed = False
        self.searches = 0
        self.applied = 0

    def arm(self):
        """Register on the insight drift plane; idempotent."""
        if not self._armed:
            from .. import insight as _insight
            _insight.on_drift(self._on_drift)
            self._armed = True
        return self

    def disarm(self):
        if self._armed:
            from .. import insight as _insight
            _insight.remove_drift_hook(self._on_drift)
            self._armed = False
        return self

    def _on_drift(self, source, event):
        if not _config.get("autotune.retune_on_drift"):
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return                      # one re-search at a time
            if self._staged is not None:
                return                      # a winner already awaits swap
            self.searches += 1
            self._thread = threading.Thread(
                target=self._search, name="mx-autotune-retune",
                daemon=True)
            self._thread.start()

    def _search(self):
        # the background re-search competes with training for host
        # cycles: its lifetime is retune badput in the goodput ledger
        tok = _goodput.begin("retune") if _goodput._active else None
        try:
            self._staged = search_kernels(**self._kw)
        except Exception as e:   # a failed re-search must not kill training
            _telemetry.note_event("autotune.retune_failed",
                                  f"{type(e).__name__}: {e}"[:200])
        finally:
            _goodput.end(tok)

    def join(self, timeout=None):
        t = self._thread
        if t is not None:
            t.join(timeout)
        return self

    @property
    def pending(self):
        """True when a finished background search awaits the next
        checkpoint boundary."""
        return self._staged is not None

    def checkpoint(self, step=None):
        """Checkpoint-boundary hook: when a re-search result is staged,
        publish its winners into the process-global table and rebuild
        ``step`` (same mesh, weights synced) so the next jit picks the
        new blocks up.  Returns the (possibly rebuilt) step — callers
        use it as ``step = retuner.checkpoint(step)`` right where they
        save a checkpoint.  No-op (and zero-cost) while nothing is
        staged."""
        res = self._staged
        if res is None:
            return step
        self._staged = None
        sp = _trace.begin("autotune.retune", category="autotune",
                          buckets=len(res.tuned)) if _trace._active else None
        tok = _goodput.begin("retune") if _goodput._active else None
        try:
            _TUNED.update(res.tuned)
            if step is not None and \
                    getattr(step, "mesh_config", None) is not None:
                step = step.rebuild(step.mesh_config)
        finally:
            _goodput.end(tok)
        self.applied += 1
        _telemetry.inc("autotune.retunes_total")
        if sp is not None:
            sp.end(applied=True)
        return step

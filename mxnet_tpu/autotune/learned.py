"""Learned kernel cost model: ridge regression over hashed features.

"A Learned Performance Model for Tensor Processing Units" (arXiv
2008.01040) shows a small regressor over op shapes/flops/bytes predicts
TPU kernel runtime well enough to rank a tile search.  This is the
minimal honest version of that result: a feature-hashed ridge regressor
(pure stdlib — the normal equations are solved with Gaussian
elimination, no sklearn/scipy) trained on the measured kernel trials
every tuned run already persists (``winners.json`` ``"trials"`` plane,
persist.py) plus the ``"autotune"`` plane of ``TrainingTelemetry``
JSONL run reports the fleet accumulates for free.

The model never gets authority it hasn't earned: before it ranks a
search, :func:`rank_gate` compares its Spearman rank correlation on the
recorded trials against the analytic :func:`~.cost.kernel_cost` — only
a model that beats (or ties) the closed form replaces it, and the
margin lands on the ``autotune.learned_rank_corr`` gauge either way.
"""
from __future__ import annotations

import json
import math

from ..base import MXNetError
from .cost import kernel_cost, kernel_tile_bytes

__all__ = ["LearnedCostModel", "spearman", "rank_gate",
           "load_telemetry_records", "MIN_FIT_RECORDS"]

#: below this many recorded trials the learned model abstains (the
#: analytic model ranks) — a 2-point fit "beating" the closed form is
#: noise, not evidence
MIN_FIT_RECORDS = 8


def _stable_hash(s):
    """Deterministic string hash (Python's builtin hash is salted per
    process — useless for a model whose weights must mean the same thing
    across runs)."""
    h = 2166136261
    for ch in s.encode():
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h


def spearman(xs, ys):
    """Spearman rank correlation of two equal-length sequences (average
    ranks on ties); 0.0 when degenerate (n < 2 or a constant side)."""
    n = len(xs)
    if n != len(ys):
        raise MXNetError(f"spearman: length mismatch {n} vs {len(ys)}")
    if n < 2:
        return 0.0

    def _ranks(vs):
        order = sorted(range(n), key=lambda i: vs[i])
        ranks = [0.0] * n
        i = 0
        while i < n:
            j = i
            while j + 1 < n and vs[order[j + 1]] == vs[order[i]]:
                j += 1
            avg = (i + j) / 2.0 + 1.0
            for t in range(i, j + 1):
                ranks[order[t]] = avg
            i = j + 1
        return ranks

    rx, ry = _ranks(list(xs)), _ranks(list(ys))
    mx_ = sum(rx) / n
    my = sum(ry) / n
    cov = sum((a - mx_) * (b - my) for a, b in zip(rx, ry))
    vx = sum((a - mx_) ** 2 for a in rx)
    vy = sum((b - my) ** 2 for b in ry)
    if vx <= 0 or vy <= 0:
        return 0.0
    return cov / math.sqrt(vx * vy)


class LearnedCostModel:
    """Feature-hashed ridge regressor: trial record -> log runtime.

    Features per (kernel, bucket, blocks) point: one hashed categorical
    slot per ``kernel`` and per ``block=value`` pair, plus hashed
    numeric slots carrying log2 of every bucket dim and block value, the
    log tile footprint and the log analytic cost — so the learned model
    starts from everything the closed form knows and corrects it from
    measurements.
    """

    def __init__(self, dim=32, l2=1e-2):
        self.dim = int(dim)
        self.l2 = float(l2)
        self.w = [0.0] * self.dim
        self.n_fit = 0

    def featurize(self, kernel, bucket, blocks):
        x = [0.0] * self.dim

        def _add(name, value):
            x[_stable_hash(name) % self.dim] += value

        _add(f"kernel={kernel}", 1.0)
        for i, d in enumerate(bucket):
            _add(f"{kernel}.dim{i}", math.log2(max(1, int(d))))
        for k, v in sorted(dict(blocks).items()):
            _add(f"{k}={v}", 1.0)
            _add(f"{kernel}.{k}", math.log2(max(1, int(v))))
        _add("tile_bytes",
             math.log2(max(1, kernel_tile_bytes(kernel, bucket, blocks))))
        _add("analytic",
             math.log2(max(1e-9, kernel_cost(kernel, bucket, blocks))))
        _add("bias", 1.0)
        return x

    def fit(self, records):
        """Ridge fit on trial records (``{"kernel", "bucket", "blocks",
        "seconds"}``); records without a positive measurement are
        skipped.  Returns the number of records used."""
        rows, ys = [], []
        for r in records:
            sec = r.get("seconds")
            if not sec or sec <= 0:
                continue
            try:
                rows.append(self.featurize(r["kernel"], tuple(r["bucket"]),
                                           r["blocks"]))
            except (KeyError, MXNetError):
                continue
            ys.append(math.log(sec))
        self.n_fit = len(rows)
        if not rows:
            return 0
        d = self.dim
        # normal equations (X^T X + l2 I) w = X^T y, Gaussian elimination
        a = [[self.l2 if i == j else 0.0 for j in range(d)]
             for i in range(d)]
        b = [0.0] * d
        for x, y in zip(rows, ys):
            for i in range(d):
                xi = x[i]
                if xi == 0.0:
                    continue
                b[i] += xi * y
                for j in range(d):
                    if x[j] != 0.0:
                        a[i][j] += xi * x[j]
        for col in range(d):
            piv = max(range(col, d), key=lambda r_: abs(a[r_][col]))
            if abs(a[piv][col]) < 1e-12:
                continue
            a[col], a[piv] = a[piv], a[col]
            b[col], b[piv] = b[piv], b[col]
            inv = 1.0 / a[col][col]
            for r_ in range(d):
                if r_ == col:
                    continue
                f = a[r_][col] * inv
                if f == 0.0:
                    continue
                for j in range(col, d):
                    a[r_][j] -= f * a[col][j]
                b[r_] -= f * b[col]
        self.w = [b[i] / a[i][i] if abs(a[i][i]) > 1e-12 else 0.0
                  for i in range(d)]
        return self.n_fit

    def predict(self, kernel, bucket, blocks):
        """Predicted log-runtime (relative — only the order is used)."""
        x = self.featurize(kernel, tuple(bucket), blocks)
        return sum(wi * xi for wi, xi in zip(self.w, x))


def rank_gate(model, records):
    """Score the learned model against the analytic ``kernel_cost`` on
    the recorded trials: Spearman(predicted, measured) for both.
    Returns ``(use_learned, learned_corr, analytic_corr)`` — the learned
    model ranks only when fitted on enough records AND its correlation
    is at least the closed form's."""
    pts = [r for r in records
           if r.get("seconds") and r["seconds"] > 0
           and "kernel" in r and "bucket" in r and "blocks" in r]
    if len(pts) < 2:
        return False, 0.0, 0.0
    measured = [r["seconds"] for r in pts]
    learned = [model.predict(r["kernel"], tuple(r["bucket"]), r["blocks"])
               for r in pts]
    analytic = [kernel_cost(r["kernel"], tuple(r["bucket"]), r["blocks"])
                for r in pts]
    lc = spearman(learned, measured)
    ac = spearman(analytic, measured)
    use = model.n_fit >= MIN_FIT_RECORDS and lc >= ac
    return use, lc, ac


def load_telemetry_records(path):
    """Harvest kernel trial records from a ``TrainingTelemetry`` JSONL
    run-report file: every report whose ``"autotune"`` plane carries a
    ``"kernel_trials"`` list contributes its records.  Malformed lines
    are skipped — fleet-aggregated files splice reports from many hosts
    and one torn line must not poison the training set."""
    records = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return records
    for line in lines:
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        plane = doc.get("autotune") if isinstance(doc, dict) else None
        trials = (plane or {}).get("kernel_trials")
        if isinstance(trials, list):
            records.extend(t for t in trials if isinstance(t, dict))
    return records

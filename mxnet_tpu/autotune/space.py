"""Search space for the compiled-step config search.

A ``Candidate`` is one point in the grid the tuner considers:

    {batch_size, steps_per_call, grad_accum, zero, remat, prefetch_depth,
     precision}

— the knobs ``ShardedTrainStep`` + ``DevicePrefetcher`` accept, plus a
``precision`` axis for inference tuning (the numeric format is a config
dimension like any other per "A Learned Performance Model for TPUs" —
see PRECISION_VALUES).  Values are JSON-native (remat is
``False``/``'dots'``/``True``) so winners round-trip through the
persisted winners file unchanged; configs persisted before the precision
axis load as ``precision="fp32"``.
"""
from __future__ import annotations

import itertools

from .. import config as _config
from ..base import MXNetError

__all__ = ["Candidate", "SearchSpace", "REMAT_VALUES", "PRECISION_VALUES"]

#: remat axis values, cheapest-compute first (order matters for docs only)
REMAT_VALUES = (False, "dots", True)

#: precision axis values an inference search may enumerate: compute
#: formats (fp32/bf16/int8/fp8) and the serve weight-storage modes.
#: Free-form strings are allowed — the trial builder decides what a
#: value means; these are the ones bench.py / mx.serve understand.
PRECISION_VALUES = ("fp32", "bf16", "int8", "fp8", "int8_weights",
                    "int4_weights")


class Candidate:
    """One grid point; hashable on its config tuple."""

    __slots__ = ("batch_size", "steps_per_call", "grad_accum", "zero",
                 "remat", "prefetch_depth", "precision")

    def __init__(self, batch_size, steps_per_call=1, grad_accum=1, zero=0,
                 remat=False, prefetch_depth=None, precision="fp32"):
        self.batch_size = int(batch_size)
        self.steps_per_call = int(steps_per_call)
        self.grad_accum = int(grad_accum)
        self.zero = int(zero)
        self.remat = remat
        self.prefetch_depth = (None if prefetch_depth is None
                               else int(prefetch_depth))
        self.precision = str(precision)

    def config(self):
        """JSON-safe config dict (the shape persisted in winners.json and
        recorded per bench row)."""
        return {"batch_size": self.batch_size,
                "steps_per_call": self.steps_per_call,
                "grad_accum": self.grad_accum,
                "zero": self.zero,
                "remat": self.remat,
                "prefetch_depth": self.prefetch_depth,
                "precision": self.precision}

    @classmethod
    def from_config(cls, cfg):
        # .get keeps winners persisted before the precision axis loading
        return cls(precision=cfg.get("precision", "fp32"),
                   **{k: cfg[k] for k in
                      ("batch_size", "steps_per_call", "grad_accum", "zero",
                       "remat", "prefetch_depth")})

    def key(self):
        return (self.batch_size, self.steps_per_call, self.grad_accum,
                self.zero, self.remat, self.prefetch_depth, self.precision)

    def __eq__(self, other):
        return isinstance(other, Candidate) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return ("Candidate(bs={batch_size}, spc={steps_per_call}, "
                "ga={grad_accum}, zero={zero}, remat={remat}, "
                "prefetch={prefetch_depth}, prec={precision})"
                ).format(**self.config())


class SearchSpace:
    """Cartesian grid over the step-config axes.

    Axis defaults are the production-relevant neighborhoods around the
    untuned step (steps_per_call 1/2/4, grad_accum 1/2, all zero levels,
    all remat policies, the configured prefetch depth); any axis can be
    overridden with an explicit list.  ``candidates()`` enumerates the
    full grid in deterministic order — validity/pruning is the cost
    model's job (cost.py), not the space's.
    """

    def __init__(self, batch_size, steps_per_call=(1, 2, 4),
                 grad_accum=(1, 2), zero=(0, 1, 2), remat=REMAT_VALUES,
                 prefetch_depth=None, precision="fp32"):
        def _axis(v):
            return tuple(v) if isinstance(v, (tuple, list)) else (v,)
        self.batch_size = _axis(batch_size)
        self.steps_per_call = _axis(steps_per_call)
        self.grad_accum = _axis(grad_accum)
        self.zero = _axis(zero)
        self.remat = _axis(remat)
        if prefetch_depth is None:
            prefetch_depth = (_config.get("pipeline.prefetch_depth"),)
        self.prefetch_depth = _axis(prefetch_depth)
        # single-value by default so train searches are unchanged; an
        # inference search passes e.g. precision=("bf16", "int8")
        self.precision = _axis(precision)
        if not self.batch_size:
            raise MXNetError("SearchSpace needs at least one batch size")
        for z in self.zero:
            if z not in (0, 1, 2):
                raise MXNetError(f"zero axis value {z!r} not in (0, 1, 2)")
        if not self.precision:
            raise MXNetError("SearchSpace needs at least one precision")

    @classmethod
    def default(cls, batch_size):
        """The default neighborhood around an untuned step with per-update
        batch ``batch_size``."""
        return cls(batch_size=batch_size)

    def default_candidate(self):
        """The untuned point: first batch size, no step fusion, no memory
        knobs, configured prefetch depth — the baseline every winner's
        speedup is reported against."""
        return Candidate(self.batch_size[0], steps_per_call=1, grad_accum=1,
                         zero=0, remat=False,
                         prefetch_depth=self.prefetch_depth[0],
                         precision=self.precision[0])

    def candidates(self):
        """Enumerate the grid (deterministic order; includes the default
        candidate by construction)."""
        out = []
        for bs, spc, ga, z, rm, pf, pr in itertools.product(
                self.batch_size, self.steps_per_call, self.grad_accum,
                self.zero, self.remat, self.prefetch_depth, self.precision):
            out.append(Candidate(bs, spc, ga, z, rm, pf, pr))
        return out

    def __len__(self):
        return (len(self.batch_size) * len(self.steps_per_call)
                * len(self.grad_accum) * len(self.zero) * len(self.remat)
                * len(self.prefetch_depth) * len(self.precision))

"""Search space for the compiled-step config search.

A ``Candidate`` is one point in the grid the tuner considers:

    {batch_size, steps_per_call, grad_accum, zero, remat, prefetch_depth}

— exactly the knobs ``ShardedTrainStep`` + ``DevicePrefetcher`` accept,
so every candidate maps 1:1 onto a constructible training step.  Values
are JSON-native (remat is ``False``/``'dots'``/``True``) so winners
round-trip through the persisted winners file unchanged.
"""
from __future__ import annotations

import itertools

from .. import config as _config
from ..base import MXNetError

__all__ = ["Candidate", "SearchSpace", "REMAT_VALUES"]

#: remat axis values, cheapest-compute first (order matters for docs only)
REMAT_VALUES = (False, "dots", True)


class Candidate:
    """One grid point; hashable on its config tuple."""

    __slots__ = ("batch_size", "steps_per_call", "grad_accum", "zero",
                 "remat", "prefetch_depth")

    def __init__(self, batch_size, steps_per_call=1, grad_accum=1, zero=0,
                 remat=False, prefetch_depth=None):
        self.batch_size = int(batch_size)
        self.steps_per_call = int(steps_per_call)
        self.grad_accum = int(grad_accum)
        self.zero = int(zero)
        self.remat = remat
        self.prefetch_depth = (None if prefetch_depth is None
                               else int(prefetch_depth))

    def config(self):
        """JSON-safe config dict (the shape persisted in winners.json and
        recorded per bench row)."""
        return {"batch_size": self.batch_size,
                "steps_per_call": self.steps_per_call,
                "grad_accum": self.grad_accum,
                "zero": self.zero,
                "remat": self.remat,
                "prefetch_depth": self.prefetch_depth}

    @classmethod
    def from_config(cls, cfg):
        return cls(**{k: cfg[k] for k in
                      ("batch_size", "steps_per_call", "grad_accum", "zero",
                       "remat", "prefetch_depth")})

    def key(self):
        return (self.batch_size, self.steps_per_call, self.grad_accum,
                self.zero, self.remat, self.prefetch_depth)

    def __eq__(self, other):
        return isinstance(other, Candidate) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return ("Candidate(bs={batch_size}, spc={steps_per_call}, "
                "ga={grad_accum}, zero={zero}, remat={remat}, "
                "prefetch={prefetch_depth})").format(**self.config())


class SearchSpace:
    """Cartesian grid over the step-config axes.

    Axis defaults are the production-relevant neighborhoods around the
    untuned step (steps_per_call 1/2/4, grad_accum 1/2, all zero levels,
    all remat policies, the configured prefetch depth); any axis can be
    overridden with an explicit list.  ``candidates()`` enumerates the
    full grid in deterministic order — validity/pruning is the cost
    model's job (cost.py), not the space's.
    """

    def __init__(self, batch_size, steps_per_call=(1, 2, 4),
                 grad_accum=(1, 2), zero=(0, 1, 2), remat=REMAT_VALUES,
                 prefetch_depth=None):
        def _axis(v):
            return tuple(v) if isinstance(v, (tuple, list)) else (v,)
        self.batch_size = _axis(batch_size)
        self.steps_per_call = _axis(steps_per_call)
        self.grad_accum = _axis(grad_accum)
        self.zero = _axis(zero)
        self.remat = _axis(remat)
        if prefetch_depth is None:
            prefetch_depth = (_config.get("pipeline.prefetch_depth"),)
        self.prefetch_depth = _axis(prefetch_depth)
        if not self.batch_size:
            raise MXNetError("SearchSpace needs at least one batch size")
        for z in self.zero:
            if z not in (0, 1, 2):
                raise MXNetError(f"zero axis value {z!r} not in (0, 1, 2)")

    @classmethod
    def default(cls, batch_size):
        """The default neighborhood around an untuned step with per-update
        batch ``batch_size``."""
        return cls(batch_size=batch_size)

    def default_candidate(self):
        """The untuned point: first batch size, no step fusion, no memory
        knobs, configured prefetch depth — the baseline every winner's
        speedup is reported against."""
        return Candidate(self.batch_size[0], steps_per_call=1, grad_accum=1,
                         zero=0, remat=False,
                         prefetch_depth=self.prefetch_depth[0])

    def candidates(self):
        """Enumerate the grid (deterministic order; includes the default
        candidate by construction)."""
        out = []
        for bs, spc, ga, z, rm, pf in itertools.product(
                self.batch_size, self.steps_per_call, self.grad_accum,
                self.zero, self.remat, self.prefetch_depth):
            out.append(Candidate(bs, spc, ga, z, rm, pf))
        return out

    def __len__(self):
        return (len(self.batch_size) * len(self.steps_per_call)
                * len(self.grad_accum) * len(self.zero) * len(self.remat)
                * len(self.prefetch_depth))

"""Search space for the compiled-step config search.

A ``Candidate`` is one point in the grid the tuner considers:

    {batch_size, steps_per_call, grad_accum, zero, remat, prefetch_depth,
     precision, mesh}

— the knobs ``ShardedTrainStep`` + ``DevicePrefetcher`` accept, plus a
``precision`` axis for inference tuning (the numeric format is a config
dimension like any other per "A Learned Performance Model for TPUs" —
see PRECISION_VALUES) and a ``mesh`` axis searching the device layout
itself (``parallel.mesh_factorizations`` enumerates the valid
``(dp, tp, pp, sp)`` factorizations of the device count).  Values are
JSON-native (remat is ``False``/``'dots'``/``True``, mesh a plain
``{axis: size}`` dict or None) so winners round-trip through the
persisted winners file unchanged; configs persisted before the
precision/mesh axes load as ``precision="fp32"`` / ``mesh=None``.
"""
from __future__ import annotations

import itertools

from .. import config as _config
from ..base import MXNetError

__all__ = ["Candidate", "SearchSpace", "REMAT_VALUES", "PRECISION_VALUES",
           "as_axis"]


def as_axis(v):
    """Normalize one grid axis: a scalar becomes a single-value axis, a
    list/tuple passes through as a tuple (shared with the kernel-level
    block-shape space in kernels.py)."""
    return tuple(v) if isinstance(v, (tuple, list)) else (v,)


def _mesh_value(v):
    """Normalize one mesh-axis value: None (use the caller's mesh), a
    ``MeshConfig`` or a ``{axis: size}`` dict -> plain int dict."""
    if v is None:
        return None
    shape = getattr(v, "shape", v)
    if not isinstance(shape, dict):
        raise MXNetError(
            f"mesh axis value {v!r}: expected None, a MeshConfig or a "
            "{'dp': n, ...} dict")
    return {str(a): int(s) for a, s in shape.items()}

#: remat axis values, cheapest-compute first (order matters for docs only)
REMAT_VALUES = (False, "dots", True)

#: precision axis values an inference search may enumerate: compute
#: formats (fp32/bf16/int8/fp8) and the serve weight-storage modes.
#: Free-form strings are allowed — the trial builder decides what a
#: value means; these are the ones bench.py / mx.serve understand.
PRECISION_VALUES = ("fp32", "bf16", "int8", "fp8", "int8_weights",
                    "int4_weights")


class Candidate:
    """One grid point; hashable on its config tuple."""

    __slots__ = ("batch_size", "steps_per_call", "grad_accum", "zero",
                 "remat", "prefetch_depth", "precision", "mesh")

    def __init__(self, batch_size, steps_per_call=1, grad_accum=1, zero=0,
                 remat=False, prefetch_depth=None, precision="fp32",
                 mesh=None):
        self.batch_size = int(batch_size)
        self.steps_per_call = int(steps_per_call)
        self.grad_accum = int(grad_accum)
        self.zero = int(zero)
        self.remat = remat
        self.prefetch_depth = (None if prefetch_depth is None
                               else int(prefetch_depth))
        self.precision = str(precision)
        self.mesh = _mesh_value(mesh)

    def config(self):
        """JSON-safe config dict (the shape persisted in winners.json and
        recorded per bench row)."""
        return {"batch_size": self.batch_size,
                "steps_per_call": self.steps_per_call,
                "grad_accum": self.grad_accum,
                "zero": self.zero,
                "remat": self.remat,
                "prefetch_depth": self.prefetch_depth,
                "precision": self.precision,
                "mesh": self.mesh}

    @classmethod
    def from_config(cls, cfg):
        # .get keeps winners persisted before the precision/mesh axes
        # loading
        return cls(precision=cfg.get("precision", "fp32"),
                   mesh=cfg.get("mesh"),
                   **{k: cfg[k] for k in
                      ("batch_size", "steps_per_call", "grad_accum", "zero",
                       "remat", "prefetch_depth")})

    def key(self):
        mesh = (tuple(sorted(self.mesh.items()))
                if self.mesh is not None else None)
        return (self.batch_size, self.steps_per_call, self.grad_accum,
                self.zero, self.remat, self.prefetch_depth, self.precision,
                mesh)

    def __eq__(self, other):
        return isinstance(other, Candidate) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return ("Candidate(bs={batch_size}, spc={steps_per_call}, "
                "ga={grad_accum}, zero={zero}, remat={remat}, "
                "prefetch={prefetch_depth}, prec={precision}, "
                "mesh={mesh})").format(**self.config())


class SearchSpace:
    """Cartesian grid over the step-config axes.

    Axis defaults are the production-relevant neighborhoods around the
    untuned step (steps_per_call 1/2/4, grad_accum 1/2, all zero levels,
    all remat policies, the configured prefetch depth); any axis can be
    overridden with an explicit list.  ``candidates()`` enumerates the
    full grid in deterministic order — validity/pruning is the cost
    model's job (cost.py), not the space's.
    """

    def __init__(self, batch_size, steps_per_call=(1, 2, 4),
                 grad_accum=(1, 2), zero=(0, 1, 2), remat=REMAT_VALUES,
                 prefetch_depth=None, precision="fp32", mesh=None):
        _axis = as_axis
        self.batch_size = _axis(batch_size)
        self.steps_per_call = _axis(steps_per_call)
        self.grad_accum = _axis(grad_accum)
        self.zero = _axis(zero)
        self.remat = _axis(remat)
        if prefetch_depth is None:
            prefetch_depth = (_config.get("pipeline.prefetch_depth"),)
        self.prefetch_depth = _axis(prefetch_depth)
        # single-value by default so train searches are unchanged; an
        # inference search passes e.g. precision=("bf16", "int8")
        self.precision = _axis(precision)
        # single-value None by default (trials run on the caller's mesh);
        # a layout search passes mesh=parallel.mesh_factorizations(8)
        self.mesh = tuple(_mesh_value(m) for m in _axis(mesh))
        if not self.batch_size:
            raise MXNetError("SearchSpace needs at least one batch size")
        for z in self.zero:
            if z not in (0, 1, 2):
                raise MXNetError(f"zero axis value {z!r} not in (0, 1, 2)")
        if not self.precision:
            raise MXNetError("SearchSpace needs at least one precision")

    @classmethod
    def default(cls, batch_size):
        """The default neighborhood around an untuned step with per-update
        batch ``batch_size``."""
        return cls(batch_size=batch_size)

    def default_candidate(self):
        """The untuned point: first batch size, no step fusion, no memory
        knobs, configured prefetch depth — the baseline every winner's
        speedup is reported against."""
        return Candidate(self.batch_size[0], steps_per_call=1, grad_accum=1,
                         zero=0, remat=False,
                         prefetch_depth=self.prefetch_depth[0],
                         precision=self.precision[0], mesh=self.mesh[0])

    def candidates(self):
        """Enumerate the grid (deterministic order; includes the default
        candidate by construction)."""
        out = []
        for bs, spc, ga, z, rm, pf, pr, me in itertools.product(
                self.batch_size, self.steps_per_call, self.grad_accum,
                self.zero, self.remat, self.prefetch_depth, self.precision,
                self.mesh):
            out.append(Candidate(bs, spc, ga, z, rm, pf, pr, me))
        return out

    def __len__(self):
        return (len(self.batch_size) * len(self.steps_per_call)
                * len(self.grad_accum) * len(self.zero) * len(self.remat)
                * len(self.prefetch_depth) * len(self.precision)
                * len(self.mesh))

"""mx.autotune — measured config search for the compiled training step.

Reference parity: none (the reference tunes by hand-edited perf.md
tables).  On a compiler-backed stack the throughput of one model is a
function of a small discrete config — ``{batch_size, steps_per_call,
grad_accum, zero, remat, prefetch_depth}`` — and the honest way to pick
it is TVM-style (arxiv 1802.04799): an analytic cost model prunes the
grid, short measured trials of the *real* compiled step rank the
survivors, and the winner persists next to the XLA compile cache so the
next run starts tuned with zero trials.

Three surfaces::

    # training-step API
    tuned_step, result = step.autotune(loader)

    # estimator API
    est.fit(train_data, epochs=2, autotune=True)

    # CLI
    JAX_PLATFORMS=cpu python tools/autotune.py --model mlp --assert

A second tier tunes the layer BELOW the step: kernels.py searches the
Pallas block/grid shapes every TPU kernel hard-codes (flash attention
q/k tiles, the int8/fp8 matmul m/n tiles, the ln_residual row tile),
ranked by a learned cost model (learned.py, fed by persisted trials and
fleet telemetry run reports) when it out-ranks the closed form, and
re-tuned online when mx.insight flags step-time drift::

    # kernel-level API (winners share winners.json, schema 2)
    mx.autotune.search_kernels()
    mx.autotune.resolve_blocks("flash_attention", (sq, sk, d))

    # CLI
    JAX_PLATFORMS=cpu python tools/autotune.py --kernels --assert

See docs/PERFORMANCE.md ("Autotuning the compiled step").
"""
from __future__ import annotations

from .cost import (CostModel, ModelStats, REMAT_FLOPS_FACTOR,
                   REMAT_MEM_FRACTION, VMEM_BYTES, kernel_cost,
                   kernel_tile_bytes)
from .kernels import (KERNELS, KernelSearchResult, Retuner,
                      kernel_candidates, kernel_config_summary, load_tuned,
                      resolve_blocks, search_kernels, shape_bucket,
                      static_blocks)
from .learned import (LearnedCostModel, load_telemetry_records, rank_gate,
                      spearman)
from .persist import (cache_dir, kernel_key, load_trials, load_winner,
                      model_fingerprint, save_winner, winner_key,
                      winners_path)
from .search import (SearchResult, TrialOOM, TrialParity, TrialResult,
                     last_summary, search, trial_compile_scope,
                     tune_estimator)
from .space import Candidate, SearchSpace

__all__ = [
    "Candidate", "SearchSpace", "CostModel", "ModelStats",
    "REMAT_MEM_FRACTION", "REMAT_FLOPS_FACTOR",
    "SearchResult", "TrialResult", "TrialOOM", "TrialParity",
    "search", "tune_estimator", "trial_compile_scope", "last_summary",
    "cache_dir", "winners_path", "model_fingerprint", "winner_key",
    "load_winner", "save_winner",
    "KERNELS", "KernelSearchResult", "Retuner", "kernel_candidates",
    "kernel_config_summary", "load_tuned", "resolve_blocks",
    "search_kernels", "shape_bucket", "static_blocks",
    "kernel_key", "load_trials", "kernel_cost", "kernel_tile_bytes",
    "VMEM_BYTES", "LearnedCostModel", "rank_gate", "spearman",
    "load_telemetry_records",
]

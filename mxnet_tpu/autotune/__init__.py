"""mx.autotune — measured config search for the compiled training step.

Reference parity: none (the reference tunes by hand-edited perf.md
tables).  On a compiler-backed stack the throughput of one model is a
function of a small discrete config — ``{batch_size, steps_per_call,
grad_accum, zero, remat, prefetch_depth}`` — and the honest way to pick
it is TVM-style (arxiv 1802.04799): an analytic cost model prunes the
grid, short measured trials of the *real* compiled step rank the
survivors, and the winner persists next to the XLA compile cache so the
next run starts tuned with zero trials.

Three surfaces::

    # training-step API
    tuned_step, result = step.autotune(loader)

    # estimator API
    est.fit(train_data, epochs=2, autotune=True)

    # CLI
    JAX_PLATFORMS=cpu python tools/autotune.py --model mlp --assert

See docs/PERFORMANCE.md ("Autotuning the compiled step").
"""
from __future__ import annotations

from .cost import (CostModel, ModelStats, REMAT_FLOPS_FACTOR,
                   REMAT_MEM_FRACTION)
from .persist import (cache_dir, load_winner, model_fingerprint,
                      save_winner, winner_key, winners_path)
from .search import (SearchResult, TrialOOM, TrialResult, last_summary,
                     search, trial_compile_scope, tune_estimator)
from .space import Candidate, SearchSpace

__all__ = [
    "Candidate", "SearchSpace", "CostModel", "ModelStats",
    "REMAT_MEM_FRACTION", "REMAT_FLOPS_FACTOR",
    "SearchResult", "TrialResult", "TrialOOM",
    "search", "tune_estimator", "trial_compile_scope", "last_summary",
    "cache_dir", "winners_path", "model_fingerprint", "winner_key",
    "load_winner", "save_winner",
]

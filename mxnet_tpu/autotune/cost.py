"""Analytic cost model: prune the candidate grid before anything compiles.

Two estimates per candidate, both cheap closed forms over quantities
probed once from the model (no tracing, no compilation):

- **HBM bytes per device** — params + gradients (1/dp at zero>=2) +
  optimizer state (1/dp at zero>=1, slot count probed from the real
  ``create_state``) + live activations scaled by the remat policy's
  keep-fraction + staged input batches times the prefetch depth.
  Candidates whose estimate exceeds the budget are rejected with reason
  ``"hbm"`` — the budget itself comes from the ``memory.*`` gauges
  (PJRT ``memory_stats``), see search.py.
- **Relative compute cost per item** — logical batch FLOPs times the
  remat policy's recompute factor, plus the ZeRO collective and
  grad-accum loop penalties, plus per-launch dispatch overhead amortized
  over ``batch * steps_per_call`` items.

Pruning is dominance, not prediction: within a group of candidates that
differ only in the *memory* knobs (zero level, grad_accum, remat), every
knob strictly costs compute — so whenever the cheapest-compute member
fits the budget, the rest of the group is ``"dominated"`` and never
measured.  With 3 zero levels x 2 grad_accum x 3 remat policies per
group this alone rejects 17/18 of the grid, which is how the tuner hits
the >=50%-pruned-without-compiling target even when no budget is known
(CPU CI, where ``memory_stats`` is empty).
"""
from __future__ import annotations

import numpy as onp

from .. import config as _config
from ..base import MXNetError
from .space import Candidate

__all__ = ["ModelStats", "CostModel", "REMAT_MEM_FRACTION",
           "REMAT_FLOPS_FACTOR", "PRECISION_COMPUTE_FACTOR",
           "VMEM_BYTES", "VMEM_FRACTION", "kernel_tile_bytes",
           "kernel_cost"]

#: fraction of peak live activation bytes kept under each remat policy
#: (full remat keeps only layer inputs; 'dots' keeps matmul outputs)
REMAT_MEM_FRACTION = {False: 1.0, "dots": 0.45, True: 0.18}
#: recompute multiplier on fwd+bwd FLOPs (full remat replays the forward:
#: 4 passes instead of 3 -> 4/3)
REMAT_FLOPS_FACTOR = {False: 1.0, "dots": 1.15, True: 4.0 / 3.0}

#: compute penalties for the memory knobs (relative, used only to order
#: candidates inside a dominance group — never to predict wall time)
_ZERO_PENALTY = 0.05        # all-gather/reduce-scatter per update
_ACCUM_PENALTY = 0.02       # scan-carry overhead per extra microbatch

#: relative time-per-flop by precision: MXU peak ratios (bf16 2x fp32,
#: int8/fp8 2x bf16 on generations that rate them — bench.py
#: PEAK_INT8_FACTOR carries the per-chip truth; this table only orders
#: candidates). Weight-only modes move bytes, not flops: the matmuls
#: still run in the activation dtype, so they rank as bf16-ish.
PRECISION_COMPUTE_FACTOR = {
    "fp32": 1.0, "bf16": 0.5, "int8": 0.25, "fp8": 0.25,
    "int8_weights": 0.5, "int4_weights": 0.5,
}


def _state_slots(optimizer, dtype):
    """Probe how many bytes of optimizer state one parameter element
    costs by asking the real ``create_state`` for a tiny weight."""
    from ..numpy.multiarray import _wrap
    import jax
    import jax.numpy as jnp
    try:
        s = optimizer.create_state(
            "autotune_probe", _wrap(jnp.zeros((8,), dtype)))
        leaves = [l for l in jax.tree_util.tree_leaves(s) if l is not None]
        return sum(jnp.dtype(getattr(l, "dtype", jnp.float32)).itemsize
                   for l in leaves)
    except Exception:
        return 8  # adam-class fallback: two fp32 slots


class ModelStats:
    """Per-model quantities the cost model runs on.  ``probe`` derives
    them from the live block/optimizer; tests construct directly."""

    def __init__(self, param_count, param_bytes, state_bytes, dp,
                 flops_per_item=None, act_bytes_per_item=None,
                 sample_item_bytes=0):
        self.param_count = int(param_count)
        self.param_bytes = int(param_bytes)
        self.state_bytes = int(state_bytes)
        self.dp = max(1, int(dp))
        # 6ND rule: fwd + 2x bwd over every weight, per sample
        self.flops_per_item = (float(flops_per_item) if flops_per_item
                               else 6.0 * self.param_count)
        if act_bytes_per_item is None:
            # crude proxy when the caller has no profile: activations per
            # sample scale with the input sample plus a slice of the
            # weights touched per layer.  Only relative accuracy matters —
            # real OOMs are still caught per-trial by the search loop.
            act_bytes_per_item = 8 * sample_item_bytes + param_bytes // 64
        self.act_bytes_per_item = int(act_bytes_per_item)
        self.sample_item_bytes = int(sample_item_bytes)

    @classmethod
    def probe(cls, block, optimizer, sample_batch, dp,
              flops_per_item=None, act_bytes_per_item=None):
        from .. import functional
        trainable, _aux = functional.split_params(block)
        param_count = sum(int(onp.prod(v.shape) or 1)
                          for v in trainable.values())
        param_bytes = sum(
            int(onp.prod(v.shape) or 1) * onp.dtype(v.dtype).itemsize
            for v in trainable.values())
        first = next(iter(trainable.values()), None)
        dtype = getattr(first, "dtype", onp.float32)
        state_bytes = param_count * _state_slots(optimizer, dtype)
        sample_item_bytes = 0
        for a in sample_batch:
            a = onp.asarray(getattr(a, "_data", a))
            n = int(onp.prod(a.shape[1:]) or 1)  # per-sample, batch axis off
            sample_item_bytes += n * a.dtype.itemsize
        return cls(param_count, param_bytes, state_bytes, dp,
                   flops_per_item=flops_per_item,
                   act_bytes_per_item=act_bytes_per_item,
                   sample_item_bytes=sample_item_bytes)


class CostModel:
    """Prunes a candidate grid down to the points worth a measured trial."""

    def __init__(self, stats, hbm_budget=None, zero_ok=True,
                 launch_overhead_items=None, max_trials=None):
        self.stats = stats
        self.hbm_budget = hbm_budget
        self.zero_ok = zero_ok
        self.launch_overhead_items = (
            _config.get("autotune.launch_overhead_items")
            if launch_overhead_items is None else launch_overhead_items)
        self.max_trials = (_config.get("autotune.max_trials")
                           if max_trials is None else max_trials)

    # -- per-candidate estimates ------------------------------------------
    def hbm_bytes(self, c):
        """Estimated peak HBM bytes per device for candidate ``c``."""
        st = self.stats
        dp = st.dp
        params = st.param_bytes
        grads = st.param_bytes // (dp if c.zero >= 2 else 1)
        state = st.state_bytes // (dp if c.zero >= 1 else 1)
        micro = max(1, c.batch_size // max(1, c.grad_accum))
        acts = int(st.act_bytes_per_item * micro / dp
                   * REMAT_MEM_FRACTION.get(c.remat, 1.0))
        staged = (st.sample_item_bytes * c.batch_size * c.steps_per_call
                  // dp)
        inputs = staged * (1 + max(0, c.prefetch_depth or 0))
        return params + grads + state + acts + inputs

    def compute_cost(self, c):
        """Relative time per item — orders candidates inside a dominance
        group; the memory knobs only ever add cost."""
        st = self.stats
        f = st.flops_per_item * REMAT_FLOPS_FACTOR.get(c.remat, 1.0)
        f *= PRECISION_COMPUTE_FACTOR.get(
            getattr(c, "precision", "fp32"), 1.0)
        if c.zero and st.dp > 1:
            f *= 1.0 + _ZERO_PENALTY
        f *= 1.0 + _ACCUM_PENALTY * (c.grad_accum - 1)
        overhead = (self.launch_overhead_items * st.flops_per_item
                    / max(1, c.batch_size * c.steps_per_call))
        return f + overhead

    def fits(self, c):
        return self.hbm_budget is None or self.hbm_bytes(c) <= self.hbm_budget

    def invalid_reason(self, c):
        st = self.stats
        if c.batch_size < 1 or c.steps_per_call < 1 or c.grad_accum < 1:
            return "invalid"
        if c.batch_size % c.grad_accum:
            return "invalid"            # microbatch must be whole
        if (c.batch_size // c.grad_accum) % st.dp:
            return "invalid"            # microbatch must shard over dp
        if c.zero and st.dp == 1:
            return "dominated"          # nothing to shard, pure overhead
        if c.zero and not self.zero_ok:
            return "invalid"            # optimizer not ZeRO-partitionable
        return None

    # -- grid -> trial plan -----------------------------------------------
    def plan(self, candidates, default=None):
        """Split the grid into (keep, pruned).

        ``keep`` is the measured-trial list (predicted-best first);
        ``pruned`` is ``[(candidate, reason)]`` with reasons ``invalid``,
        ``dominated``, ``hbm`` or ``ranked_out``.  ``default`` (when in
        the grid) is always kept so the best-vs-default speedup has a
        measured baseline.
        """
        keep, pruned = [], []
        groups = {}
        for c in candidates:
            reason = self.invalid_reason(c)
            if reason is not None and c != default:
                pruned.append((c, reason))
                continue
            # precision is in the group key: a cheaper format is not a
            # dominance win over a slower one (different numerics), so
            # formats are only ever compared by measured trials
            groups.setdefault(
                (c.batch_size, c.steps_per_call, c.prefetch_depth,
                 getattr(c, "precision", "fp32")),
                []).append(c)
        for members in groups.values():
            fitting = [c for c in members if self.fits(c)]
            best = min(fitting, key=self.compute_cost) if fitting else None
            for c in members:
                if c is best or c == default:
                    keep.append(c)
                elif not self.fits(c):
                    pruned.append((c, "hbm"))
                else:
                    pruned.append((c, "dominated"))
        keep.sort(key=self.compute_cost)
        limit = self.max_trials
        if limit and len(keep) > limit:
            ranked, extra = keep[:limit], keep[limit:]
            if default is not None and default in extra:
                # the default always gets a measured baseline: it replaces
                # the worst-predicted ranked member so the cap holds
                extra.remove(default)
                if ranked:
                    extra.append(ranked.pop())
                ranked.append(default)
            pruned.extend((c, "ranked_out") for c in extra)
            keep = ranked
        return keep, pruned


# ---------------------------------------------------------------------------
# kernel-level analytics (kernels.py): VMEM footprint + relative tile cost
# ---------------------------------------------------------------------------

#: per-core VMEM capacity the tile footprint must fit (TPU v4/v5/v6 all
#: carry ~16 MB; the interpreter has no real limit but honoring it keeps
#: CPU-CI pruning representative)
VMEM_BYTES = 16 * 2 ** 20
#: fraction of VMEM the tuner budgets for one kernel's resident tiles
#: (the rest is Mosaic's: double-buffered DMA staging, scratch, spills)
VMEM_FRACTION = 0.5


def kernel_tile_bytes(kernel, bucket, blocks):
    """Estimated VMEM bytes resident for one grid step of ``kernel`` at
    ``blocks`` on a ``bucket``-shaped problem — the kernel tuner's
    pre-compile OOM guard (prune reason ``"vmem"``)."""
    b = dict(blocks)
    if kernel in ("flash_attention", "flash_attention_bwd"):
        sq, sk, d = bucket
        d = max(128, int(d))  # head_dim zero-pads to the lane width
        bq = min(int(b["block_q"]), int(sq))
        bk = min(int(b["block_k"]), int(sk))
        # q/o/acc tiles + k/v tiles + the (bq, bk) score block, fp32;
        # the bwd kernels additionally hold do/dq (q-shaped) and dk/dv
        # (k-shaped) accumulators
        tiles = 3 * bq * d + 2 * bk * d + bq * bk
        if kernel == "flash_attention_bwd":
            tiles += 2 * bq * d + 2 * bk * d
        return 4 * tiles
    if kernel in ("quantized_matmul", "fp8_matmul"):
        m, n, k = bucket
        bm = min(int(b["block_m"]), int(m))
        bn = min(int(b["block_n"]), int(n))
        kp = max(128, int(k))
        # one (bm, K) activation tile (fp32 in + int8/fp8 quantized copy),
        # one (bn, K) low-bit weight tile, the fp32 (bm, bn) output tile
        return 5 * bm * kp + bn * kp + 4 * bm * bn
    if kernel == "ln_residual":
        rows, dim = bucket
        br = min(int(b["block_rows"]), max(8, int(rows)))
        # x/h/mask/out tiles plus fp32 row stats
        return 4 * (4 * br * dim + 2 * br)
    raise MXNetError(f"kernel_tile_bytes: unknown kernel {kernel!r}")


def kernel_cost(kernel, bucket, blocks):
    """Relative analytic cost of ``blocks`` on a ``bucket``-shaped
    problem: per-tile work plus a fixed launch overhead per grid step,
    plus an MXU/VPU under-utilization penalty for tiles below the native
    (8/32 x 128) shape.  Only the ORDER matters — this is the ranking
    the learned model (learned.py) must beat on Spearman correlation to
    replace it."""
    b = dict(blocks)
    launch = 1.0   # relative dispatch cost per grid step

    def _grid_and_util(sizes, tiles, aligns):
        steps, util = 1.0, 1.0
        for size, tile, align in zip(sizes, tiles, aligns):
            size = max(1, int(size))
            tile = max(1, min(int(tile), size))
            steps *= -(-size // tile)          # ceil-div grid steps
            util *= min(1.0, tile / align)     # sub-native-tile penalty
        return steps, util

    if kernel in ("flash_attention", "flash_attention_bwd"):
        sq, sk, d = bucket
        steps, util = _grid_and_util((sq, sk), (b["block_q"], b["block_k"]),
                                     (256, 256))
        work = (min(b["block_q"], sq) * min(b["block_k"], sk)
                * max(128, d)) / 2 ** 20
        passes = 3.0 if kernel == "flash_attention_bwd" else 1.0
    elif kernel in ("quantized_matmul", "fp8_matmul"):
        m, n, k = bucket
        steps, util = _grid_and_util((m, n), (b["block_m"], b["block_n"]),
                                     (256, 256))
        work = (min(b["block_m"], m) * min(b["block_n"], n)
                * max(128, k)) / 2 ** 20
        passes = 1.0
    elif kernel == "ln_residual":
        rows, dim = bucket
        steps, util = _grid_and_util((rows,), (b["block_rows"],), (256,))
        work = (min(b["block_rows"], rows) * dim) / 2 ** 17
        passes = 1.0
    else:
        raise MXNetError(f"kernel_cost: unknown kernel {kernel!r}")
    return passes * steps * (launch + work / util)

"""Winner persistence: tuned configs survive the process, keyed like the
XLA compile cache they sit next to.

Winners live in ONE JSON file (``winners.json``) under, in order of
preference: the ``autotune.cache_dir`` knob, the persistent XLA compile
cache directory (``compilation_cache_dir`` — "next to the XLA cache", so
one cache volume carries both the compiled executables and the configs
that produced them), or ``<mxnet home>/autotune``.

Keys are ``<model fingerprint>|<device_kind>|dp<N>[|mesh:<axes>]``: the
fingerprint hashes the parameter inventory (structural name, shape,
dtype) plus the block/loss/optimizer identities, so any architecture
change invalidates the entry; device_kind, dp size and the mesh shape
(every axis with size > 1, e.g. ``mesh:dp2xtp2``) key the hardware point
the measurement is only valid for — a winner tuned on one topology never
loads on another.  Writes are atomic (tmp + rename) — a preempted run
never leaves a torn winners file.

Schema 2 adds kernel-level winners in the SAME file, keyed
``<kernel>|<shape bucket>|<device_kind>`` (kernel records carry
``"kind": "kernel"``; step records are unmarked), plus a bounded
``"trials"`` plane of raw measured kernel trials — the training set the
learned cost model (learned.py) fits.  Schema-1 files migrate on load:
step-winner records pass through unchanged, so a PR-7-era cache keeps
answering searches with zero re-trials.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile

from .. import config as _config

__all__ = ["cache_dir", "winners_path", "model_fingerprint", "winner_key",
           "kernel_key", "load_winner", "save_winner", "load_all",
           "append_trials", "load_trials"]

_FILE = "winners.json"
_SCHEMA = 2
#: schema versions load_all accepts; 1 is the PR-7 step-winner format
#: whose records are forward-compatible verbatim
_COMPAT_SCHEMAS = (1, 2)
#: cap on persisted raw trial records (oldest evicted first)
_TRIALS_CAP = 512


def cache_dir():
    """Resolve the winners directory (see module docstring)."""
    path = _config.get("autotune.cache_dir")
    if not path:
        path = _config.get("compilation_cache_dir")
    if not path:
        path = os.path.join(_config.get("home"), "autotune")
    return os.path.abspath(os.path.expanduser(path))


def winners_path():
    return os.path.join(cache_dir(), _FILE)


def model_fingerprint(block, loss_fn=None, optimizer=None):
    """Hash of everything a stale winner must not survive: the parameter
    inventory (name, shape, dtype — sorted, so dict order is irrelevant),
    the block class, and the loss/optimizer identities."""
    from .. import functional
    trainable, aux = functional.split_params(block)
    items = []
    for n, v in sorted({**trainable, **aux}.items()):
        items.append(f"{n}:{tuple(v.shape)}:{v.dtype}")
    items.append(f"block={type(block).__module__}.{type(block).__qualname__}")
    if loss_fn is not None:
        items.append(f"loss={getattr(loss_fn, '__qualname__', None) or type(loss_fn).__qualname__}")
    if optimizer is not None:
        items.append(f"opt={type(optimizer).__qualname__}")
    h = hashlib.sha256("\n".join(items).encode()).hexdigest()
    return h[:16]


def winner_key(fingerprint, device_kind, dp, mesh=None):
    """``mesh`` (a MeshConfig, jax Mesh or {axis: size} dict) appends the
    topology to the key so a winner measured on dp2xtp2 never loads on
    dp4; omit it for the pre-mesh key format (dp-only searches)."""
    key = f"{fingerprint}|{device_kind}|dp{int(dp)}"
    if mesh is not None:
        shape = dict(getattr(mesh, "shape", mesh))
        axes = "x".join(f"{a}{int(s)}" for a, s in sorted(shape.items())
                        if int(s) > 1)
        key += f"|mesh:{axes or '1'}"
    return key


def kernel_key(kernel, bucket, device_kind):
    """Key for one kernel-level winner: the kernel name, its shape
    bucket (problem dims rounded to powers of two, joined with ``x``)
    and the device kind the tile timing is only valid for."""
    if isinstance(bucket, (tuple, list)):
        bucket = "x".join(str(int(d)) for d in bucket)
    return f"{kernel}|{bucket}|{device_kind}"


def _load_doc(path):
    """Parse the full winners document (any compatible schema) ->
    ``{"winners": {...}, "trials": [...]}``; empty planes when the file
    is absent, corrupt, or from an unknown schema."""
    empty = {"winners": {}, "trials": []}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return empty
    if not isinstance(data, dict):
        return empty
    # schema 1 files carry only {"version": 1, "winners": ...}; their
    # step-winner records are schema-2-compatible verbatim (kernel
    # records are distinguished by "kind", which schema 1 never wrote)
    schema = data.get("schema", data.get("version"))
    if schema not in _COMPAT_SCHEMAS:
        return empty
    winners = data.get("winners")
    trials = data.get("trials")
    return {"winners": winners if isinstance(winners, dict) else {},
            "trials": trials if isinstance(trials, list) else []}


def _save_doc(doc, path):
    """Atomically write the full document at the current schema."""
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".winners.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({"schema": _SCHEMA, "version": _SCHEMA,
                       "winners": doc["winners"],
                       "trials": doc["trials"][-_TRIALS_CAP:]},
                      f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_all(path=None):
    """Parse a winners file -> {key: record}; {} when absent/corrupt.
    Accepts schema 1 (step winners only) and schema 2."""
    return _load_doc(path or winners_path())["winners"]


def load_winner(key, path=None):
    return load_all(path).get(key)


def save_winner(key, record, path=None):
    """Merge one winner into the file atomically; returns the path.
    A schema-1 file is migrated to schema 2 in place on first write —
    every existing step winner survives verbatim."""
    path = path or winners_path()
    doc = _load_doc(path)
    doc["winners"][key] = record
    return _save_doc(doc, path)


def append_trials(records, path=None):
    """Append raw measured trial records (bounded ring, oldest evicted)
    — the persisted training set for the learned cost model."""
    path = path or winners_path()
    doc = _load_doc(path)
    doc["trials"].extend(records)
    return _save_doc(doc, path)


def load_trials(path=None):
    """The persisted raw kernel-trial records (possibly empty)."""
    return _load_doc(path or winners_path())["trials"]

"""Winner persistence: tuned configs survive the process, keyed like the
XLA compile cache they sit next to.

Winners live in ONE JSON file (``winners.json``) under, in order of
preference: the ``autotune.cache_dir`` knob, the persistent XLA compile
cache directory (``compilation_cache_dir`` — "next to the XLA cache", so
one cache volume carries both the compiled executables and the configs
that produced them), or ``<mxnet home>/autotune``.

Keys are ``<model fingerprint>|<device_kind>|dp<N>[|mesh:<axes>]``: the
fingerprint hashes the parameter inventory (structural name, shape,
dtype) plus the block/loss/optimizer identities, so any architecture
change invalidates the entry; device_kind, dp size and the mesh shape
(every axis with size > 1, e.g. ``mesh:dp2xtp2``) key the hardware point
the measurement is only valid for — a winner tuned on one topology never
loads on another.  Writes are atomic (tmp + rename) — a preempted run
never leaves a torn winners file.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile

from .. import config as _config

__all__ = ["cache_dir", "winners_path", "model_fingerprint", "winner_key",
           "load_winner", "save_winner", "load_all"]

_FILE = "winners.json"
_VERSION = 1


def cache_dir():
    """Resolve the winners directory (see module docstring)."""
    path = _config.get("autotune.cache_dir")
    if not path:
        path = _config.get("compilation_cache_dir")
    if not path:
        path = os.path.join(_config.get("home"), "autotune")
    return os.path.abspath(os.path.expanduser(path))


def winners_path():
    return os.path.join(cache_dir(), _FILE)


def model_fingerprint(block, loss_fn=None, optimizer=None):
    """Hash of everything a stale winner must not survive: the parameter
    inventory (name, shape, dtype — sorted, so dict order is irrelevant),
    the block class, and the loss/optimizer identities."""
    from .. import functional
    trainable, aux = functional.split_params(block)
    items = []
    for n, v in sorted({**trainable, **aux}.items()):
        items.append(f"{n}:{tuple(v.shape)}:{v.dtype}")
    items.append(f"block={type(block).__module__}.{type(block).__qualname__}")
    if loss_fn is not None:
        items.append(f"loss={getattr(loss_fn, '__qualname__', None) or type(loss_fn).__qualname__}")
    if optimizer is not None:
        items.append(f"opt={type(optimizer).__qualname__}")
    h = hashlib.sha256("\n".join(items).encode()).hexdigest()
    return h[:16]


def winner_key(fingerprint, device_kind, dp, mesh=None):
    """``mesh`` (a MeshConfig, jax Mesh or {axis: size} dict) appends the
    topology to the key so a winner measured on dp2xtp2 never loads on
    dp4; omit it for the pre-mesh key format (dp-only searches)."""
    key = f"{fingerprint}|{device_kind}|dp{int(dp)}"
    if mesh is not None:
        shape = dict(getattr(mesh, "shape", mesh))
        axes = "x".join(f"{a}{int(s)}" for a, s in sorted(shape.items())
                        if int(s) > 1)
        key += f"|mesh:{axes or '1'}"
    return key


def load_all(path=None):
    """Parse a winners file -> {key: record}; {} when absent/corrupt."""
    path = path or winners_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("version") != _VERSION:
        return {}
    winners = data.get("winners")
    return winners if isinstance(winners, dict) else {}


def load_winner(key, path=None):
    return load_all(path).get(key)


def save_winner(key, record, path=None):
    """Merge one winner into the file atomically; returns the path."""
    path = path or winners_path()
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    winners = load_all(path)
    winners[key] = record
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".winners.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({"version": _VERSION, "winners": winners}, f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path

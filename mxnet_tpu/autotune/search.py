"""Measured config search over the compiled training step.

The loop (TVM-style measure-and-prune, arxiv 1802.04799):

1. Enumerate the ``SearchSpace`` grid.
2. ``CostModel.plan`` rejects >=50% of it analytically (dominance + HBM
   budget) — nothing pruned here is ever compiled.
3. Each surviving candidate gets a short **hermetic** measured trial of
   the real ``ShardedTrainStep``: params re-read from the block (never
   written back), the optimizer deep-cloned, trial compiles accounted
   through the recompile detector under a trial-scoped limit, and device
   OOM recorded as a trial outcome instead of killing the search.
4. The measured items/s winner persists to ``winners.json`` keyed by
   ``(model fingerprint, device_kind, dp size)`` — the next run with the
   same key reloads it and runs **zero** trials.

``measure=`` injects a deterministic measurement backend (tests); the
HBM budget defaults to ``"auto"``: read from the same PJRT
``memory_stats`` that feed the ``memory.*`` gauges, scaled by
``autotune.hbm_fraction`` (None on backends without memory stats — the
dominance rules still prune, and real OOMs are caught per trial).
"""
from __future__ import annotations

import contextlib
import copy
import math
import time

import numpy as onp

from .. import config as _config
from .. import fault as _fault
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..base import MXNetError
from .cost import CostModel, ModelStats
from .persist import (load_winner, model_fingerprint, save_winner,
                      winner_key, winners_path)
from .space import Candidate, SearchSpace

__all__ = ["TrialOOM", "TrialParity", "TrialResult", "SearchResult",
           "search", "tune_estimator", "trial_compile_scope",
           "last_summary"]

#: summary of the most recent search in this process — surfaced as the
#: "autotune" plane of TrainingTelemetry run reports
_LAST = None


class TrialOOM(MXNetError):
    """A measured trial exhausted device memory (real RESOURCE_EXHAUSTED,
    or injected via the ``autotune.trial_oom`` fault point)."""


class TrialParity(MXNetError):
    """A reduced-precision candidate failed its loss-parity probe against
    the fp32 reference (relative loss delta beyond
    ``autotune.fp8_parity_tol``).  The candidate is disqualified — fp8
    ships only on shape buckets where trials PROVE parity — but the
    search continues (status "parity" in the trial record)."""


def _is_oom(exc):
    if isinstance(exc, TrialOOM):
        return True
    msg = f"{type(exc).__name__}: {exc}".lower()
    return ("resource_exhausted" in msg or "resource exhausted" in msg
            or "out of memory" in msg or "oom" in msg.split())


@contextlib.contextmanager
def trial_compile_scope(owner, limit=None):
    """Route trial compiles through the recompile detector without letting
    them poison the caller's budget: the per-block compile count and the
    warn-once latch (telemetry.note_compile state) are saved and restored,
    and ``telemetry.recompile_limit`` is raised to the trial allowance for
    the duration — warmup compiles across N candidate configs are
    expected, so they must not trip ``RecompileWarning`` during or after
    the search."""
    if limit is None:
        limit = _config.get("autotune.recompile_limit")
    d = owner.__dict__
    saved = (d.get("_telemetry_compiles", 0),
             d.get("_telemetry_recompile_warned", False))
    saved_limit = _config.get("telemetry.recompile_limit")
    _config.set("telemetry.recompile_limit", int(limit))
    try:
        yield
    finally:
        _config.set("telemetry.recompile_limit", saved_limit)
        d["_telemetry_compiles"] = saved[0]
        d["_telemetry_recompile_warned"] = saved[1]


def _clone_optimizer(opt):
    """Hermetic per-trial optimizer: same hyperparameters/schedule, fresh
    bookkeeping — trials advance the clone's ``num_update``, never the
    caller's."""
    clone = copy.copy(opt)
    clone.param_dict = {}
    clone.idx2name = dict(opt.idx2name)
    clone.lr_mult = dict(opt.lr_mult)
    clone.wd_mult = dict(opt.wd_mult)
    clone._index_update_count = {}
    clone._master_weights = {}
    return clone


class TrialResult:
    """Outcome of one measured (or cached) candidate."""

    def __init__(self, candidate, items_per_s=None, status="ok",
                 seconds=0.0, error=None):
        self.candidate = candidate
        self.items_per_s = items_per_s
        self.status = status          # ok | oom | error | parity | cached
        self.seconds = seconds
        self.error = error

    def summary(self):
        out = {"config": self.candidate.config(), "status": self.status,
               "seconds": round(self.seconds, 4)}
        if self.items_per_s is not None:
            out["items_per_s"] = round(self.items_per_s, 3)
        if self.error:
            out["error"] = self.error
        return out


class SearchResult:
    """What a search produced: the winner, the measured trials, the
    pruned grid, and where the winner persisted."""

    def __init__(self, key, path, n_candidates, trials, pruned, best,
                 default, reused=False, wall_s=0.0, hbm_budget=None):
        self.key = key
        self.path = path
        self.n_candidates = n_candidates
        self.trials = trials
        self.pruned = pruned
        self.best = best
        self.default = default
        self.reused = reused
        self.wall_s = wall_s
        self.hbm_budget = hbm_budget

    @property
    def config(self):
        return self.best.candidate.config() if self.best else None

    @property
    def speedup(self):
        if (self.best and self.default
                and self.best.items_per_s and self.default.items_per_s):
            return self.best.items_per_s / self.default.items_per_s
        return None

    @property
    def pruned_fraction(self):
        if not self.n_candidates:
            return 0.0
        return len(self.pruned) / self.n_candidates

    def summary(self):
        reasons = {}
        for _c, reason in self.pruned:
            reasons[reason] = reasons.get(reason, 0) + 1
        oom = sum(1 for t in self.trials if t.status == "oom")
        out = {"key": self.key, "path": self.path, "reused": self.reused,
               "candidates": self.n_candidates,
               "trials": len(self.trials), "trials_oom": oom,
               "pruned": len(self.pruned), "pruned_by_reason": reasons,
               "pruned_fraction": round(self.pruned_fraction, 4),
               "wall_s": round(self.wall_s, 3),
               "hbm_budget": self.hbm_budget,
               "best": self.best.summary() if self.best else None,
               "default": self.default.summary() if self.default else None}
        if self.speedup is not None:
            out["speedup_vs_default"] = round(self.speedup, 4)
        return out


def last_summary():
    """Summary dict of the most recent search in this process (None when
    no search ran) — merged into TrainingTelemetry run reports.  A
    kernel-level search (kernels.py) contributes a ``"kernels"`` plane
    and the raw ``"kernel_trials"`` records the learned cost model
    harvests back out of fleet-aggregated report files."""
    from . import kernels as _kernels
    ks = _kernels.last_kernel_summary()
    if ks is None:
        return _LAST
    out = dict(_LAST or {})
    out["kernels"] = {k: v for k, v in ks.items() if k != "kernel_trials"}
    out["kernel_trials"] = ks.get("kernel_trials", [])
    return out


def _hbm_budget(devices=None):
    """Per-device HBM budget from the runtime: min ``bytes_limit`` across
    devices (refreshing the ``memory.*`` gauges on the way when telemetry
    is enabled) times ``autotune.hbm_fraction``.  None when the backend
    reports no memory stats (CPU)."""
    if devices is None:
        import jax
        devices = jax.local_devices()
    _telemetry.record_memory(devices)
    limits = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_limit"):
            limits.append(int(stats["bytes_limit"]))
    if not limits:
        return None
    return int(min(limits) * _config.get("autotune.hbm_fraction"))


def _stacked_batch(sample_batch, candidate):
    """Shape the sample batch for a candidate: resize the batch axis to
    ``batch_size * steps_per_call`` samples (cyclic tiling) and fold in
    the leading grad_accum/steps axes exactly as ShardedTrainStep
    expects them."""
    c = candidate
    total = c.batch_size * c.steps_per_call
    micro = c.batch_size // c.grad_accum
    out = []
    for a in sample_batch:
        a = onp.asarray(getattr(a, "_data", a))
        flat = onp.resize(a, (total,) + a.shape[1:])
        lead = ()
        if c.steps_per_call > 1:
            lead += (c.steps_per_call,)
        if c.grad_accum > 1:
            lead += (c.grad_accum,)
        out.append(flat.reshape(lead + (micro if c.grad_accum > 1
                                        else c.batch_size,) + a.shape[1:]))
    return tuple(out)


def _sync(loss):
    return float(onp.asarray(getattr(loss, "_data", loss)))


def _parity_probe(c, fp8_step, block, loss_fn, optimizer, mesh,
                  batch_specs, batch, n_labels, param_specs, dp_axis,
                  steps=2):
    """Run the fp8 candidate and an identically-configured fp32 reference
    a few steps on the SAME batch and compare losses; raises TrialParity
    beyond ``autotune.fp8_parity_tol``.  Doubles as extra fp8 warmup —
    the throughput measurement that follows is unaffected by the probe
    having advanced the trial's (hermetic) weights."""
    from ..parallel.train import ShardedTrainStep
    import jax.numpy as jnp
    tol = float(_config.get("autotune.fp8_parity_tol"))
    ref = ShardedTrainStep(
        block, loss_fn, _clone_optimizer(optimizer), mesh, batch_specs,
        n_labels=n_labels, param_specs=param_specs,
        steps_per_call=c.steps_per_call, zero=c.zero,
        grad_accum=c.grad_accum, remat=c.remat, dp_axis=dp_axis)
    ref.trainable = {n: jnp.copy(v) for n, v in ref.trainable.items()}
    ref.aux = {n: jnp.copy(v) for n, v in ref.aux.items()}
    ref._insight_label = fp8_step._insight_label + ":parity_ref"
    for _ in range(max(1, steps)):
        l8 = fp8_step(*batch)
        lref = ref(*batch)
    l8, lref = _sync(l8), _sync(lref)
    denom = max(abs(lref), 1e-8)
    rel = abs(l8 - lref) / denom
    if not math.isfinite(l8) or rel > tol:
        raise TrialParity(
            f"fp8 parity probe failed for {c!r}: fp8 loss {l8:.6g} vs "
            f"fp32 {lref:.6g} (rel delta {rel:.3g} > tol {tol})")


def _measure_candidate(candidate, block, loss_fn, optimizer, mesh,
                       batch_specs, sample_batch, n_labels, param_specs,
                       dp_axis, trial_seconds, warmup, max_calls=200):
    """One hermetic measured trial -> items/s.  Raises TrialOOM on device
    memory exhaustion (or when the ``autotune.trial_oom`` fault point
    fires — the chaos path CI uses to prove OOM survival)."""
    from ..parallel.mesh import MeshConfig
    from ..parallel.train import ShardedTrainStep
    if _fault._active and _fault.fire("autotune.trial_oom"):
        raise TrialOOM(f"injected OOM for {candidate!r}")
    c = candidate
    batch = _stacked_batch(sample_batch, c)
    if c.mesh is not None:
        # mesh-axis candidate: the trial runs on ITS layout, not the
        # caller's — batch/param specs re-derive from the MeshConfig
        # (megatron tp specs auto-apply inside ShardedTrainStep)
        mesh = MeshConfig(**c.mesh)
        batch_specs = mesh.batch_specs(*[a.ndim for a in sample_batch])
        param_specs = None
        dp_axis = "dp"
    # the precision axis maps onto the training step: "fp8" builds a real
    # fp8 step (delayed scaling state and all), every other value runs
    # the fp32 training path (bf16/int8* are inference-search formats)
    precision = getattr(c, "precision", "fp32")
    step_precision = "fp8" if precision == "fp8" else "fp32"
    step = ShardedTrainStep(
        block, loss_fn, _clone_optimizer(optimizer), mesh, batch_specs,
        n_labels=n_labels, param_specs=param_specs,
        steps_per_call=c.steps_per_call, zero=c.zero,
        grad_accum=c.grad_accum, remat=c.remat, dp_axis=dp_axis,
        precision=step_precision)
    # Hermeticity: the constructor's device_put can ALIAS the block's own
    # param buffers (a same-layout put is a no-op), and the step donates
    # its inputs — without a copy, the first trial call would delete the
    # caller's parameter arrays.  Give the trial its own buffers.
    import jax.numpy as jnp
    step.trainable = {n: jnp.copy(v) for n, v in step.trainable.items()}
    step.aux = {n: jnp.copy(v) for n, v in step.aux.items()}
    # mx.insight attribution label: each measured trial registers its
    # own cost-analysis entry instead of masquerading as the train step
    step._insight_label = (f"autotune.trial[bs{c.batch_size}"
                           f"x{c.steps_per_call},ga{c.grad_accum},"
                           f"zero{c.zero},{step_precision}]")
    if step_precision == "fp8":
        # loss-parity gate BEFORE timing: fp8 may only win a bucket where
        # its loss curve tracks the fp32 reference within
        # autotune.fp8_parity_tol — a fast format with broken numerics
        # must not be selected (raises TrialParity -> status "parity")
        _parity_probe(c, step, block, loss_fn, optimizer, mesh,
                      batch_specs, batch, n_labels, param_specs, dp_axis)
    # first call = trace + compile; account it through the detector so
    # the trial-scoped limit governs it like any hybridized compile
    t0 = time.perf_counter()
    _sync(step(*batch))
    _telemetry.note_compile(block, f"autotune:{type(block).__name__}",
                            time.perf_counter() - t0)
    for _ in range(max(0, warmup - 1)):
        step(*batch)
    t0 = time.perf_counter()
    _sync(step(*batch))
    pilot = max(time.perf_counter() - t0, 1e-6)
    calls = min(max_calls, max(1, math.ceil(trial_seconds / pilot)))
    t0 = time.perf_counter()
    for _ in range(calls):
        loss = step(*batch)
    _sync(loss)  # single host fetch syncs the whole chain
    sec = (time.perf_counter() - t0) / calls
    return c.batch_size * c.steps_per_call / sec


def search(block, loss_fn, optimizer, mesh, batch_specs, sample_batch,
           n_labels=1, space=None, hbm_budget="auto", devices=None,
           measure=None, force=False, persist=True, dp_axis="dp",
           param_specs=None, stats=None, trial_seconds=None, warmup=None,
           flops_per_item=None, act_bytes_per_item=None, max_trials=None):
    """Run the config search; returns a ``SearchResult``.

    block/loss_fn/optimizer/mesh/batch_specs/n_labels/param_specs mirror
    ``ShardedTrainStep`` — every trial builds a real step from them.
    ``sample_batch`` is one representative batch (inputs then labels,
    host arrays); candidates re-shape it to their own geometry.

    The search is hermetic: the block's parameters and the caller's
    optimizer are read, never written.
    """
    from ..optimizer import optimizer as opt_mod
    global _LAST
    t_start = time.perf_counter()
    if isinstance(optimizer, str):
        optimizer = opt_mod.create(optimizer)
    sample_batch = tuple(onp.asarray(getattr(b, "_data", b))
                         for b in sample_batch)
    if not sample_batch:
        raise MXNetError("autotune.search needs a non-empty sample_batch")
    dp = int(mesh.shape.get(dp_axis, 1))
    if space is None:
        space = SearchSpace.default(int(sample_batch[0].shape[0]))
    default = space.default_candidate()

    import jax
    device_kind = getattr(jax.devices()[0], "device_kind", "cpu")
    fp = model_fingerprint(block, loss_fn, optimizer)
    # the mesh shape keys the winner — a layout tuned on dp2xtp2 never
    # loads on dp4 (mesh-axis searches store the winning layout in the
    # record's config["mesh"])
    key = winner_key(fp, device_kind, dp, mesh=dict(mesh.shape))
    path = winners_path()

    candidates = space.candidates()
    n_candidates = len(candidates)

    if persist and not force:
        rec = load_winner(key, path)
        if rec is not None:
            _telemetry.inc("autotune.cache_hits_total")
            best = TrialResult(Candidate.from_config(rec["config"]),
                               items_per_s=rec.get("items_per_s"),
                               status="cached")
            dflt = TrialResult(default,
                               items_per_s=rec.get("default_items_per_s"),
                               status="cached")
            result = SearchResult(key, path, n_candidates, [], [], best,
                                  dflt, reused=True,
                                  wall_s=time.perf_counter() - t_start)
            _LAST = result.summary()
            return result

    if hbm_budget == "auto":
        hbm_budget = _hbm_budget(devices)
    if stats is None:
        stats = ModelStats.probe(block, optimizer, sample_batch, dp,
                                 flops_per_item=flops_per_item,
                                 act_bytes_per_item=act_bytes_per_item)
    zero_ok = bool(getattr(type(optimizer), "_zero_partitionable", False))
    model = CostModel(stats, hbm_budget=hbm_budget, zero_ok=zero_ok,
                      max_trials=max_trials)
    keep, pruned = model.plan(candidates, default)

    _telemetry.inc("autotune.candidates_total", n_candidates)
    for _c, reason in pruned:
        _telemetry.inc("autotune.pruned_total", reason=reason)

    if trial_seconds is None:
        trial_seconds = _config.get("autotune.trial_seconds")
    if warmup is None:
        warmup = _config.get("autotune.trial_warmup")

    trials = []
    root = _trace.begin("autotune.search", category="autotune",
                        candidates=n_candidates, kept=len(keep),
                        pruned=len(pruned)) if _trace._active else None
    with trial_compile_scope(block):
        for c in keep:
            t0 = time.perf_counter()
            # trial span carries the candidate config as attrs, so a
            # trace export reads as (config -> measured wall time) pairs
            sp = _trace.begin("autotune.trial", category="autotune",
                             parent=(root.context if root else None),
                             **c.config()) if _trace._active else None
            try:
                if measure is not None:
                    if _fault._active and _fault.fire("autotune.trial_oom"):
                        raise TrialOOM(f"injected OOM for {c!r}")
                    ips = measure(c)
                else:
                    ips = _measure_candidate(
                        c, block, loss_fn, optimizer, mesh, batch_specs,
                        sample_batch, n_labels, param_specs, dp_axis,
                        trial_seconds, warmup)
                trials.append(TrialResult(
                    c, float(ips), "ok", time.perf_counter() - t0))
            except Exception as e:  # a dead candidate must not kill the search
                status = ("oom" if _is_oom(e)
                          else "parity" if isinstance(e, TrialParity)
                          else "error")
                trials.append(TrialResult(
                    c, None, status, time.perf_counter() - t0,
                    error=f"{type(e).__name__}: {e}"[:300]))
                if status == "oom":
                    _telemetry.inc("autotune.trials_oom_total")
                    _fault.record("autotune.trial_oom")
                elif status == "parity":
                    _telemetry.inc("autotune.trials_parity_total")
            if sp is not None:
                last = trials[-1]
                sp.end(status=last.status,
                       items_per_s=(last.items_per_s or 0.0))
            _telemetry.inc("autotune.trials_total")
    if root is not None:
        root.end(trials=len(trials))

    ok = [t for t in trials if t.status == "ok"]
    best = max(ok, key=lambda t: t.items_per_s) if ok else None
    dflt = next((t for t in trials if t.candidate == default), None)
    wall_s = time.perf_counter() - t_start
    result = SearchResult(key, path, n_candidates, trials, pruned, best,
                          dflt, wall_s=wall_s, hbm_budget=hbm_budget)
    _telemetry.observe("autotune.search_seconds", wall_s)
    if result.speedup is not None:
        _telemetry.set_gauge("autotune.best_speedup", result.speedup)
    if persist and best is not None:
        rec = {"config": best.candidate.config(),
               "items_per_s": best.items_per_s,
               "default_items_per_s":
                   dflt.items_per_s if dflt else None,
               "speedup_vs_default": result.speedup,
               "device_kind": device_kind, "dp": dp,
               "fingerprint": fp, "created": time.time()}
        save_winner(key, rec, path)
    _LAST = result.summary()
    return result


def tune_estimator(estimator, train_data, space=None, apply=True, **kw):
    """`estimator.fit(autotune=True)` backend: search around the
    estimator's net/loss/optimizer using one batch drawn from the loader
    (batch size stays the loader's — the loader owns it), then apply what
    an eager fit can use: the winning remat policy (re-hybridize) and
    prefetch depth (``pipeline.prefetch_depth`` knob).  The full result
    lands on ``estimator.autotune_result`` so a ShardedTrainStep caller
    can lift the rest (zero/grad_accum/steps_per_call)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .. import pipeline as _pipeline
    from ..parallel.mesh import make_mesh

    batch = next(iter(_pipeline.take(train_data, 1)), None)
    if batch is None:
        raise MXNetError("autotune: train_data yielded no batch")
    arrs = tuple(onp.asarray(getattr(b, "_data", b)) for b in batch)
    b0 = int(arrs[0].shape[0])
    ndev = len(jax.devices())
    dp = ndev if b0 % ndev == 0 else 1
    mesh = make_mesh({"dp": dp})
    specs = tuple(P("dp") for _ in arrs)

    net, loss = estimator.net, estimator.loss

    def loss_fn(out, *labels):
        import jax.numpy as jnp
        from ..numpy.multiarray import _wrap
        val = loss(_wrap(out), *[_wrap(x) for x in labels])
        return jnp.mean(getattr(val, "_data", val))

    if space is None:
        space = SearchSpace(batch_size=b0)
    result = search(net, loss_fn, estimator.trainer.optimizer, mesh, specs,
                    arrs, n_labels=len(arrs) - 1, space=space, **kw)
    cfg = result.config
    if apply and cfg:
        if cfg.get("prefetch_depth") is not None:
            _config.set("pipeline.prefetch_depth", cfg["prefetch_depth"])
        if cfg.get("remat") and hasattr(net, "hybridize"):
            try:
                net.hybridize(remat=cfg["remat"])
            except Exception:
                pass  # non-hybridizable net: the knob has no eager analog
    estimator.autotune_result = result
    return result

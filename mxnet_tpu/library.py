"""mx.library — runtime extension loading.

Reference parity: python/mxnet/library.py + include/mxnet/lib_api.h (loading
.so plugins that register custom operators/passes). TPU-native equivalent:
extensions are python modules that register custom ops into the op registry
(mxnet_tpu.ops.registry) — including Pallas kernels and XLA custom calls —
plus native .so libraries loaded via ctypes for host-side components.
"""
from __future__ import annotations

import ctypes
import importlib
import os

from .base import MXNetError

_loaded = {}


def load(path, verbose=True):
    """Load an extension.

    - a ``.py`` path or module name: imported; its ``register(registry)``
      hook, if present, is called with the framework op registry.
    - a ``.so`` path: loaded via ctypes for host-native components.
    """
    if path in _loaded:
        return _loaded[path]
    if path.endswith(".so"):
        if not os.path.exists(path):
            raise MXNetError(f"extension library not found: {path}")
        lib = ctypes.CDLL(path, ctypes.RTLD_LOCAL)
        _loaded[path] = lib
        return lib
    name = path[:-3].replace("/", ".") if path.endswith(".py") else path
    mod = importlib.import_module(name)
    hook = getattr(mod, "register", None)
    if hook is not None:
        from .ops import registry
        hook(registry)
    _loaded[path] = mod
    return mod

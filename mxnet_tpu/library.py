"""mx.library — runtime extension loading.

Reference parity: python/mxnet/library.py + include/mxnet/lib_api.h (loading
.so plugins that register custom operators/passes). TPU-native equivalent:
extensions are python modules that register custom ops into the op registry
(mxnet_tpu.ops.registry) — including Pallas kernels and XLA custom calls —
plus native .so libraries loaded via ctypes for host-side components.
"""
from __future__ import annotations

import ctypes
import importlib
import os

from .base import MXNetError

_loaded = {}

#: native operator-plugin ABI this build speaks (reference:
#: src/lib_api.cc MX_LIBRARY_VERSION handshake). A plugin .so exports
#: mxtpu_plugin_abi_version() returning exactly this value, plus
#: name/num_ops/op_name/op_call — see native/mxtpu_plugin_example.cc for
#: the canonical implementation.
PLUGIN_ABI_VERSION = 1


def load(path, verbose=True):
    """Load an extension.

    - a ``.py`` path or module name: imported; its ``register(registry)``
      hook, if present, is called with the framework op registry.
    - a ``.so`` path: if it speaks the versioned operator-plugin ABI
      (exports ``mxtpu_plugin_abi_version``), its ops are registered as
      eager/jit-capable operators; otherwise it is a plain ctypes load
      for host-side components.
    """
    if path in _loaded:
        return _loaded[path]
    if path.endswith(".so"):
        if not os.path.exists(path):
            raise MXNetError(f"extension library not found: {path}")
        lib = ctypes.CDLL(path, ctypes.RTLD_LOCAL)
        if hasattr(lib, "mxtpu_plugin_abi_version"):
            load_native_ops(lib, path, verbose=verbose)
        _loaded[path] = lib
        return lib
    name = path[:-3].replace("/", ".") if path.endswith(".py") else path
    mod = importlib.import_module(name)
    hook = getattr(mod, "register", None)
    if hook is not None:
        from .ops import registry
        hook(registry)
    _loaded[path] = mod
    return mod


def load_native_ops(lib, path, verbose=True):
    """Register a versioned operator plugin's ops (ABI v1).

    Each plugin op becomes a framework operator running as a host
    callback: eager calls hit the C function directly over numpy buffers;
    under jit the call lowers through ``jax.pure_callback`` (the analog of
    the reference's CustomOp FCompute dispatched by the engine,
    src/operator/custom/custom.cc). Elementwise float32 contract, shape-
    preserving; not differentiable (register a python backward via
    ops.registry for that).
    """
    import numpy as onp

    ver_fn = lib.mxtpu_plugin_abi_version
    ver_fn.restype = ctypes.c_int
    ver = ver_fn()
    if ver != PLUGIN_ABI_VERSION:
        raise MXNetError(
            f"plugin {path!r} speaks ABI v{ver}, this build speaks "
            f"v{PLUGIN_ABI_VERSION}; rebuild the plugin against the "
            "matching mxnet_tpu release")
    lib.mxtpu_plugin_name.restype = ctypes.c_char_p
    lib.mxtpu_plugin_num_ops.restype = ctypes.c_int
    lib.mxtpu_plugin_op_name.restype = ctypes.c_char_p
    lib.mxtpu_plugin_op_name.argtypes = [ctypes.c_int]
    lib.mxtpu_plugin_op_call.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int]
    pname = lib.mxtpu_plugin_name().decode()

    from .ops import registry

    def make_op(idx, op_name):
        def host_call(arr, params):
            arr = onp.ascontiguousarray(arr, dtype=onp.float32)
            params = onp.ascontiguousarray(params, dtype=onp.float32)
            out = onp.empty_like(arr)
            lib.mxtpu_plugin_op_call(
                idx,
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                arr.size,
                params.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                params.size)
            return out

        def op(x, params=()):
            import jax
            import jax.numpy as jnp

            from .numpy.multiarray import ndarray, _invoke
            pvec = jnp.asarray(params, jnp.float32).reshape(-1)

            def fn(x_):
                return jax.pure_callback(
                    host_call,
                    jax.ShapeDtypeStruct(x_.shape, jnp.float32),
                    x_.astype(jnp.float32), pvec, vmap_method="sequential")
            if isinstance(x, ndarray):
                return _invoke(fn, (x,), name=op_name)
            return fn(jnp.asarray(x))

        op.__name__ = op_name
        op.__doc__ = f"native plugin op from {pname} (ABI v{ver})"
        return op

    ops = []
    for i in range(lib.mxtpu_plugin_num_ops()):
        op_name = lib.mxtpu_plugin_op_name(i).decode()
        registry.register(op_name, make_op(i, op_name),
                          doc=f"plugin:{pname}", source=f"plugin:{pname}")
        ops.append(op_name)
    if verbose:
        import logging
        logging.info("loaded plugin %s (ABI v%d): %s", pname, ver, ops)
    return ops


# --------------------------------------------------------------------------
# subgraph/partition backends
# --------------------------------------------------------------------------
#
# Reference parity: the subgraph property API
# (src/operator/subgraph/subgraph_property.h:88-252,
# MXNET_REGISTER_SUBGRAPH_BACKEND) lets accelerator backends rewrite the
# graph a CachedOp executes; HybridBlock.optimize_for / hybridize(backend=)
# select one (python/mxnet/gluon/block.py:1160-1163).  TPU-native design:
# a backend is a transform over the *pure traced forward* — it returns a
# wrapped callable with the same signature that _CachedGraph jit-compiles,
# so a backend can rematerialize, recast, shard, or otherwise rewrite the
# computation XLA sees.

_subgraph_backends = {}


def register_subgraph_backend(name, transform=None):
    """Register (or decorate) a subgraph backend.

    ``transform(pure_fn, block, **opts) -> pure_fn`` wraps the traced
    forward; the wrapped callable must keep the signature
    ``(trainable, aux, inputs, rng_key, sig_key)``.
    """
    def deco(fn):
        _subgraph_backends[name] = fn
        return fn
    return deco(transform) if transform is not None else deco


def subgraph_backend(name):
    if name not in _subgraph_backends:
        raise MXNetError(
            f"unknown subgraph backend {name!r}; registered: "
            f"{sorted(_subgraph_backends)}")
    return _subgraph_backends[name]


def list_subgraph_backends():
    return sorted(_subgraph_backends)


@register_subgraph_backend("checkpoint")
def _checkpoint_backend(pure_fn, block, **opts):
    """Rematerialize the forward in backward (the reference's backward
    mirroring, src/nnvm/gradient.cc:131 MXNET_BACKWARD_DO_MIRROR): trades
    FLOPs for activation memory — on TPU, HBM is usually the binding
    constraint."""
    import jax
    ck = jax.checkpoint(
        lambda tr, aux, inp, rng, sig: pure_fn(tr, aux, inp, rng, sig),
        static_argnums=(4,))

    def wrapped(trainable, aux, inputs, rng_key, sig_key):
        return ck(trainable, aux, inputs, rng_key, sig_key)
    return wrapped


@register_subgraph_backend("bf16")
def _bf16_backend(pure_fn, block, **opts):
    """Run the whole forward in bfloat16 (float32 params/inputs cast in,
    float32 results cast back out) — the graph-rewrite analog of
    amp.convert_hybrid_block (reference: src/nnvm/low_precision_pass.cc).
    Natively-bfloat16 models pass through untouched: only values that
    were float32 on the way in are cast back on the way out."""
    import jax
    import jax.numpy as jnp

    def to_bf16(tree):
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if hasattr(a, "dtype") and a.dtype == jnp.float32 else a, tree)

    def wrapped(trainable, aux, inputs, rng_key, sig_key):
        was_f32 = any(
            hasattr(a, "dtype") and a.dtype == jnp.float32
            for a in jax.tree_util.tree_leaves((trainable, aux, inputs)))
        aux_dtypes = {k: v.dtype for k, v in aux.items()}
        out, mutated = pure_fn(to_bf16(trainable), to_bf16(aux),
                               to_bf16(inputs), rng_key, sig_key)
        # mutated aux must keep each param's original dtype invariant
        mutated = {k: v.astype(aux_dtypes[k])
                   if v.dtype == jnp.bfloat16
                   and aux_dtypes[k] == jnp.float32 else v
                   for k, v in mutated.items()}
        if was_f32:
            out = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32)
                if hasattr(a, "dtype") and a.dtype == jnp.bfloat16 else a,
                out)
        return out, mutated
    return wrapped

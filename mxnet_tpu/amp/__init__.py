"""mx.amp — automatic mixed precision.

Reference parity: python/mxnet/amp/ (op-list driven cast insertion at the
python wrapper level amp.py:105-246, fp16/bf16 lists, convert_hybrid_block
via the ReducePrecision NNVM pass src/nnvm/low_precision_pass.cc, dynamic
LossScaler amp/loss_scaler.py:26-60).

TPU-native design: bf16 is the native matmul dtype; "init" installs a dtype
policy that casts inputs of MXU ops (dot/conv/attention) to the target dtype
at dispatch time — the wrapper-level cast strategy of the reference, applied
in _invoke. convert_hybrid_block casts parameters (XLA then propagates).
bf16 needs no loss scaling; the LossScaler is kept for fp16 parity.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from ..base import np_dtype
from .loss_scaler import LossScaler  # noqa: F401
from . import lists  # noqa: F401
from . import fp8  # noqa: F401

_state = threading.local()

_TARGET_OPS = frozenset(lists.TARGET_DTYPE_OPS)
_FP32_OPS = frozenset(lists.FP32_OPS) | lists.conditional_fp32_names()
# lists.WIDEST_TYPE_CASTS is documentation of which combiners rely on
# jnp's dtype promotion for the widest-input behavior; no dispatcher hook
# is needed (test_amp_dtype_drift_oracle locks this in).


def _norm_conditional(ops):
    """User-supplied conditional entries: (op, attr, [values]) triples or
    plain names -> dispatch-name set."""
    out = set()
    for item in ops or ():
        if isinstance(item, str):
            out.add(item)
        else:
            op, _attr, values = item
            out.update(f"{op}:{v}" for v in values)
    return out


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Install the global dtype policy (reference: amp.init)."""
    _state.dtype = np_dtype(target_dtype)
    _state.target_ops = _TARGET_OPS | set(target_precision_ops or ())
    _state.fp32_ops = _FP32_OPS | set(fp32_ops or ()) \
        | _norm_conditional(conditional_fp32_ops)
    _state.active = True


def _deactivate():
    """Turn the policy off (test isolation; the reference has no off switch)."""
    _state.active = False


def is_active():
    return getattr(_state, "active", False)


def target_dtype():
    return getattr(_state, "dtype", jnp.bfloat16)


def _op_cast_dtype(name):
    """dtype the dispatcher should cast `name`'s floating inputs to, or None.

    Called by _invoke (numpy/multiarray.py) on every dispatch, inside the
    traced function so the cast's VJP returns cotangents in the original
    dtype — both the eager path and _CachedGraph trace-time policy
    (reference: amp.py:105-246 wrapper casts + low_precision_pass.cc).
    """
    if not is_active():
        return None
    if name in getattr(_state, "target_ops", _TARGET_OPS):
        return target_dtype()
    if name in getattr(_state, "fp32_ops", _FP32_OPS):
        return jnp.float32
    return None


def _maybe_cast_op_inputs(name, raws):
    """Cast a raw-input list per the active policy (dispatcher helper)."""
    dt = _op_cast_dtype(name)
    if dt is None:
        return raws
    return [r.astype(dt) if hasattr(r, "dtype")
            and jnp.issubdtype(r.dtype, jnp.floating)
            and r.dtype != dt else r for r in raws]


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None,
                         cast_params_offline=True, **kwargs):
    """Cast a block's parameters to the target dtype (reference:
    amp.convert_hybrid_block over low_precision_pass.cc). BatchNorm
    gamma/beta/stats stay fp32 (the AMPInferUnknown behavior)."""
    dt = np_dtype(target_dtype)
    for name, p in block.collect_params().items():
        if name.endswith(("gamma", "beta", "running_mean", "running_var")):
            continue
        p.cast(dt)
    return block


def convert_symbol(sym, target_dtype="bfloat16", target_dtype_ops=None,
                   fp32_ops=None, **kwargs):
    """Mixed-precision graph rewrite of a Symbol DAG.

    Reference parity: amp.convert_symbol over the ReducePrecision NNVM
    pass (src/nnvm/low_precision_pass.cc:152): inputs of MXU-bound ops
    (lists.TARGET_DTYPE_OPS) are cast to the target dtype, inputs of
    numerically sensitive ops (lists.FP32_OPS) back to float32; all other
    ops run in whatever dtype flows in (XLA fuses the casts).
    Returns a NEW symbol; the input graph is untouched.
    """
    from ..symbol.symbol import Symbol, Group
    from . import lists as _lists

    target_ops = set(target_dtype_ops if target_dtype_ops is not None
                     else _lists.TARGET_DTYPE_OPS)
    f32_ops = set(fp32_ops if fp32_ops is not None else _lists.FP32_OPS)

    memo = {}
    cast_memo = {}

    def cast_node(s, dtype):
        key = (id(s), dtype)
        if key not in cast_memo:  # one cast per (producer, dtype) edge
            cast_memo[key] = Symbol("amp_cast", [s], {"dtype": dtype},
                                    name=f"{s.name}_amp_{dtype}")
        return cast_memo[key]

    def rebuild(s):
        if id(s) in memo:
            return memo[id(s)]
        if isinstance(s, Group):
            out = Group([rebuild(h) for h in s.symbols])
            memo[id(s)] = out
            return out
        new_inputs = [rebuild(i) for i in s._inputs]
        if s._op in target_ops:
            new_inputs = [cast_node(i, str(target_dtype))
                          for i in new_inputs]
        elif s._op in f32_ops:
            new_inputs = [cast_node(i, "float32") for i in new_inputs]
        out = Symbol(s._op, new_inputs, dict(s._kwargs), s.name,
                     s._num_outputs, s._output_index)
        memo[id(s)] = out
        return out

    return rebuild(sym)


def scale_loss(loss, trainer):
    """Context helper (reference: amp.scale_loss): scales loss up; trainer
    step is adjusted by the scaler."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler is None:
            scaler = LossScaler()
            trainer._amp_loss_scaler = scaler
        if isinstance(loss, (list, tuple)):
            yield [l * scaler.loss_scale for l in loss]
        else:
            yield loss * scaler.loss_scale
    return _scope()


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._data is not None:
            g = p.grad()
            g._rebind(g._data * inv)

"""mx.amp — automatic mixed precision.

Reference parity: python/mxnet/amp/ (op-list driven cast insertion at the
python wrapper level amp.py:105-246, fp16/bf16 lists, convert_hybrid_block
via the ReducePrecision NNVM pass src/nnvm/low_precision_pass.cc, dynamic
LossScaler amp/loss_scaler.py:26-60).

TPU-native design: bf16 is the native matmul dtype; "init" installs a dtype
policy that casts inputs of MXU ops (dot/conv/attention) to the target dtype
at dispatch time — the wrapper-level cast strategy of the reference, applied
in _invoke. convert_hybrid_block casts parameters (XLA then propagates).
bf16 needs no loss scaling; the LossScaler is kept for fp16 parity.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from ..base import np_dtype
from .loss_scaler import LossScaler  # noqa: F401
from . import lists  # noqa: F401

_state = threading.local()

# ops that should run in low precision (the FP16_FUNCS analog): MXU ops
_WIDEST = ("matmul", "dot", "einsum", "convolution", "fully_connected",
           "multi_head_attention", "interleaved_matmul_selfatt_qk",
           "interleaved_matmul_selfatt_valatt", "batch_dot", "tensordot")


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Install the global dtype policy (reference: amp.init)."""
    _state.dtype = np_dtype(target_dtype)
    _state.active = True


def is_active():
    return getattr(_state, "active", False)


def target_dtype():
    return getattr(_state, "dtype", jnp.bfloat16)


def _maybe_cast_op_inputs(name, raws):
    """Called by the dispatcher for low-precision-listed ops."""
    if not is_active() or name not in _WIDEST:
        return raws
    dt = target_dtype()
    return [r.astype(dt) if hasattr(r, "dtype")
            and jnp.issubdtype(r.dtype, jnp.floating) else r for r in raws]


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None,
                         cast_params_offline=True, **kwargs):
    """Cast a block's parameters to the target dtype (reference:
    amp.convert_hybrid_block over low_precision_pass.cc). BatchNorm
    gamma/beta/stats stay fp32 (the AMPInferUnknown behavior)."""
    dt = np_dtype(target_dtype)
    for name, p in block.collect_params().items():
        if name.endswith(("gamma", "beta", "running_mean", "running_var")):
            continue
        p.cast(dt)
    return block


def convert_symbol(sym, **kwargs):
    raise NotImplementedError(
        "legacy symbol AMP conversion: use convert_hybrid_block")


def scale_loss(loss, trainer):
    """Context helper (reference: amp.scale_loss): scales loss up; trainer
    step is adjusted by the scaler."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler is None:
            scaler = LossScaler()
            trainer._amp_loss_scaler = scaler
        if isinstance(loss, (list, tuple)):
            yield [l * scaler.loss_scale for l in loss]
        else:
            yield loss * scaler.loss_scale
    return _scope()


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._data is not None:
            g = p.grad()
            g._rebind(g._data * inv)

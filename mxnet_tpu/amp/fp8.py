"""fp8 training with per-tensor delayed scaling.

Extends the PR 8 inference-only fp8 path (ops/pallas/quant_matmul.py,
ops/quantization.py) to training: Dense matmuls run e4m3 forward /
e5m2 backward with fp32 master weights and fp32 MXU accumulation, and
every quantization scale is DELAYED — derived from an amax history
carried in the training step's state (next to the AMP LossScaler in
spirit: state that rides the optimizer bundle), not measured in-line.
In-line (just-in-time) scaling would serialize a full-tensor reduction
before every matmul; delayed scaling reads a ready scalar and folds the
amax reduction into the backward pass XLA already runs.

Wiring (docs/PRECISION.md):

- ``ShardedTrainStep(precision="fp8")`` selects the eligible sites
  (2-D ``*.weight`` parameters >= ``amp.fp8_min_elems``), allocates one
  ``{x, w, g}`` amax history per site and threads it through the jitted
  step as donated state.
- Inside the step, :func:`scales_from_state` turns histories into
  scalar scales; the loss closure runs under :func:`scope`, which the
  ``gluon.nn.Dense`` forward consults — matching sites route through
  :func:`dense_fp8` instead of ``npx.fully_connected``.
- Forward amaxes (max |x|, max |w|) are recorded into the scope and
  returned through the loss aux. The GRADIENT amax cannot be observed
  that way — dy only exists inside the backward trace — so
  :func:`fp8_linear`'s custom_vjp returns the measured ``max |dy|`` as
  the "cotangent" of its (otherwise unused) ``g_scale`` input, and the
  step harvests it with ``argnums=(0, 1)``.
- :func:`roll_state` shifts each history one step and inserts the new
  amax; scales for step N+1 come from steps <= N only, so the whole
  update stays one fixed executable (zero post-warmup recompiles).

The forward matmul routes through the Pallas fp8 kernel on fp8-capable
TPUs (v5+, ``fp8_capable``); everywhere else the operands are cast
through the fp8 grid and the dot runs in fp32 — bit-identical value
snapping, so CPU CI exercises the exact training numerics the TPU path
ships (same fallback contract as ``ops.quantization.fp8_dense_fused``).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .. import config as _config
from ..ops.pallas.quant_matmul import FP8_FORMATS, fp8_capable

__all__ = ["FWD_FORMAT", "BWD_FORMAT", "fp8_linear", "dense_fp8",
           "select_sites", "init_state", "scales_from_state", "roll_state",
           "merge_amax", "scope", "current", "record"]

#: training formats per the standard recipe: e4m3 (more mantissa) for
#: activations/weights in the forward, e5m2 (more range) for gradients
FWD_FORMAT = "e4m3"
BWD_FORMAT = "e5m2"

_tls = threading.local()


class _Scope:
    """Per-trace fp8 context: site -> (x_scale, w_scale, g_scale) traced
    scalars, plus the forward-amax collector the loss aux returns."""

    __slots__ = ("scales", "amax")

    def __init__(self, scales):
        self.scales = scales
        self.amax = {}


class scope:
    """Context manager installing a :class:`_Scope` for the enclosed
    (traced) forward; ``Dense.forward`` reads it via :func:`current`."""

    def __init__(self, scales):
        self._scope = _Scope(scales)

    def __enter__(self):
        prev = getattr(_tls, "ctx", None)
        self._prev = prev
        _tls.ctx = self._scope
        return self._scope

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def current():
    """The active fp8 scope, or None — the one-attr-read gate the Dense
    fast path checks."""
    return getattr(_tls, "ctx", None)


def record(site, x_amax, w_amax):
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.amax[site] = (x_amax, w_amax)


# -- quantize / dequantize ---------------------------------------------------

def _qcast(v, scale, fmt):
    """Saturating cast through the fp8 grid: scale maps the delayed amax
    onto the format's absmax, clip guards inter-step amax growth."""
    dt, fmax = FP8_FORMATS[fmt]
    return jnp.clip(v.astype(jnp.float32) * scale, -fmax, fmax).astype(dt)


def _dot(a, b, dims):
    """fp8 x fp8 dot with fp32 accumulation.  On fp8-capable devices the
    operands stay fp8 (the MXU takes them natively); elsewhere they
    upcast first — numerically identical (the information loss happened
    at the cast), and it keeps CPU CI on dtypes XLA:CPU always lowers."""
    if not fp8_capable():
        a, b = a.astype(jnp.float32), b.astype(jnp.float32)
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# -- the fp8 linear primitive ------------------------------------------------

@jax.custom_vjp
def fp8_linear(x, w, b, x_scale, w_scale, g_scale):
    """``x @ w.T + b`` through the fp8 grid with delayed scales.

    x: (..., K); w: (N, K) fp32 master; b: (N,) or None; scales: fp32
    scalars (fmt_absmax / delayed_amax).  ``g_scale`` does not affect
    the value — it is consumed by the backward rule (e5m2 gradient
    quantization), and its custom_vjp cotangent carries the measured
    ``max |dy|`` back to the caller (the delayed-scaling history roll).
    """
    y, _ = _fp8_linear_fwd(x, w, b, x_scale, w_scale, g_scale)
    return y


def _fwd_value(x, w, b, x_scale, w_scale):
    qx = _qcast(x, x_scale, FWD_FORMAT)
    qw = _qcast(w, w_scale, FWD_FORMAT)
    if fp8_capable():
        # Pallas fused kernel (PR 8): per-row scale vector is the
        # broadcast per-tensor scale; kernel dequant is acc*(xs*ws)
        # with the DIVIDE convention, so pass the reciprocals
        from ..ops.pallas.quant_matmul import fp8_matmul
        lead = x.shape[:-1]
        h2 = qx.reshape(-1, x.shape[-1]).astype(jnp.float32) / x_scale
        inv_ws = jnp.full((w.shape[0],), 1.0, jnp.float32) / w_scale
        out = fp8_matmul(h2, qw, inv_ws, 1.0 / x_scale, bias=None,
                         fmt=FWD_FORMAT)
        y = out.reshape(lead + (w.shape[0],))
    else:
        y = _dot(qx, qw, ((x.ndim - 1,), (1,))) / (x_scale * w_scale)
    if b is not None:
        y = y + b
    return y, (qx, qw)


def _fp8_linear_fwd(x, w, b, x_scale, w_scale, g_scale):
    y, (qx, qw) = _fwd_value(x, w, b, x_scale, w_scale)
    # b rides the residuals only for its None-ness: the cotangent
    # structure must mirror the input (None stays None through pytrees)
    return y, (qx, qw, x_scale, w_scale, g_scale, b)


def _fp8_linear_bwd(res, dy):
    qx, qw, x_scale, w_scale, g_scale, b = res
    has_b = b is not None
    g_amax = jnp.max(jnp.abs(dy)).astype(jnp.float32)
    qdy = _qcast(dy, g_scale, BWD_FORMAT)
    # dx = dy @ w: contract dy's N with qw's leading N
    dx = _dot(qdy, qw, ((dy.ndim - 1,), (0,))) / (g_scale * w_scale)
    # dw = dy^T @ x over the flattened lead dims
    m = 1
    for s in dy.shape[:-1]:
        m *= s
    qdy2 = qdy.reshape(m, dy.shape[-1])
    qx2 = qx.reshape(m, qx.shape[-1])
    dw = _dot(qdy2, qx2, ((0,), (0,))) / (g_scale * x_scale)
    db = jnp.sum(dy.astype(jnp.float32),
                 axis=tuple(range(dy.ndim - 1))) if has_b else None
    # zero cotangents for the forward scales; g_scale's slot carries the
    # measured gradient amax out of the backward trace
    zero = jnp.zeros((), jnp.float32)
    return (dx, dw, db, zero, zero, g_amax)


fp8_linear.defvjp(_fp8_linear_fwd, _fp8_linear_bwd)


def dense_fp8(x, w, b, site, flatten=False):
    """The Dense-forward entry: record forward amaxes into the active
    scope and run :func:`fp8_linear` with the site's delayed scales.
    Raw jax arrays in and out (the caller wraps)."""
    ctx = current()
    xs, ws, gs = ctx.scales[site]
    h = x.reshape(x.shape[0], -1) if flatten and x.ndim > 2 else x
    record(site, jnp.max(jnp.abs(h)).astype(jnp.float32),
           jnp.max(jnp.abs(w)).astype(jnp.float32))
    return fp8_linear(h, w, b, xs, ws, gs)


# -- delayed-scaling state ---------------------------------------------------

def select_sites(shapes):
    """Site names eligible for fp8: 2-D ``*.weight`` parameters of at
    least ``amp.fp8_min_elems`` elements, sorted for a deterministic
    state layout.  Name-based so the state is constructible without a
    discovery trace (``Parameter._structure_name`` is the key Dense
    uses at dispatch)."""
    floor = int(_config.get("amp.fp8_min_elems"))
    out = []
    for name, shape in shapes.items():
        if not name.endswith(".weight") and name != "weight":
            continue
        if len(shape) != 2:
            continue
        if int(shape[0]) * int(shape[1]) < floor:
            continue
        out.append(name)
    return sorted(out)


def init_state(sites, history=None):
    """Fresh amax histories: {site: {"x"|"w"|"g": zeros(H,)}}.  All-zero
    means "no observation yet"; :func:`scales_from_state` maps that to
    scale 1.0 (the first step quantizes un-scaled, then the history
    takes over)."""
    if history is None:
        history = int(_config.get("amp.fp8_history"))
    h = max(1, int(history))
    return {site: {k: jnp.zeros((h,), jnp.float32) for k in ("x", "w", "g")}
            for site in sites}


def _scale(hist, fmax, margin):
    amax = jnp.max(hist) * margin
    return jnp.where(amax > 0.0, fmax / jnp.maximum(amax, 1e-30),
                     jnp.float32(1.0)).astype(jnp.float32)


def scales_from_state(state, margin=None):
    """{site: (x_scale, w_scale, g_scale)} from the carried histories —
    scale = fmt_absmax / (margin * max(history))."""
    if margin is None:
        margin = float(_config.get("amp.fp8_margin"))
    _, fwd_max = FP8_FORMATS[FWD_FORMAT]
    _, bwd_max = FP8_FORMATS[BWD_FORMAT]
    return {site: (_scale(h["x"], fwd_max, margin),
                   _scale(h["w"], fwd_max, margin),
                   _scale(h["g"], bwd_max, margin))
            for site, h in state.items()}


def roll_state(state, fwd_amax, g_amax):
    """Shift every history one step and insert the step's measured amax
    at slot 0.  Sites the forward never reached this step (conditional
    branches) keep their history unchanged."""
    new = {}
    for site, h in state.items():
        upd = dict(h)
        if site in fwd_amax:
            xa, wa = fwd_amax[site]
            upd["x"] = jnp.concatenate([xa[None], h["x"][:-1]])
            upd["w"] = jnp.concatenate([wa[None], h["w"][:-1]])
        if site in g_amax:
            upd["g"] = jnp.concatenate([g_amax[site][None], h["g"][:-1]])
        new[site] = upd
    return new


def merge_amax(a, b):
    """Elementwise max-merge of two amax observations (grad_accum
    microbatches roll the history ONCE with the max over the scan)."""
    out = dict(a)
    for k, v in b.items():
        if k in out:
            out[k] = jax.tree_util.tree_map(jnp.maximum, out[k], v)
        else:
            out[k] = v
    return out

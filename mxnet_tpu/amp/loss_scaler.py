"""Dynamic loss scaler (reference: python/mxnet/amp/loss_scaler.py:26-60)."""
from __future__ import annotations

import numpy as onp


class LossScaler:
    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """Check grads for inf/nan (reference: loss_scaler.py has_overflow)."""
        for p in params:
            if p.grad_req != "null" and p._data is not None and \
                    p._data.grad is not None:
                g = p._data.grad.asnumpy()
                if not onp.isfinite(g).all():
                    return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped == self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0

    # -- elastic resume: the scale and its backoff window are training
    # state — losing them on preemption replays the warmup ----------------
    def state_dict(self):
        return {"loss_scale": self.loss_scale, "unskipped": self._unskipped,
                "scale_factor": self._scale_factor,
                "scale_window": self._scale_window}

    def load_state_dict(self, state):
        self.loss_scale = state["loss_scale"]
        self._unskipped = int(state.get("unskipped", 0))
        self._scale_factor = state.get("scale_factor", self._scale_factor)
        self._scale_window = state.get("scale_window", self._scale_window)

"""AMP op lists (reference: python/mxnet/amp/lists/symbol_fp16.py,
symbol_bf16.py). Functional groups instead of the reference's exhaustive
per-op enumeration: jnp names that hit the MXU run low-precision, reductions
and normalizations stay fp32."""

# run in target (bf16/fp16) precision — MXU-bound
TARGET_DTYPE_OPS = [
    "matmul", "dot", "einsum", "tensordot", "convolution",
    "fully_connected", "multi_head_attention",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
]

# always fp32 — numerically sensitive
FP32_OPS = [
    "softmax", "log_softmax", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "sum", "mean", "var", "std", "norm", "exp", "log",
    "erf", "erfinv", "gammaln",
]

# fp32 unless inputs already low precision
CONDITIONAL_FP32_OPS = []

WIDEST_TYPE_CASTS = ["add", "subtract", "multiply", "true_divide", "where"]

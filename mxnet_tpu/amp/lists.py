"""AMP op lists (reference: python/mxnet/amp/lists/symbol_fp16.py,
symbol_bf16.py). Functional groups instead of the reference's exhaustive
per-op enumeration — entries are ``_invoke`` dispatch names, so one entry
covers every call site.  Ops that hit the MXU run low-precision;
reductions/normalizations/transcendentals stay fp32; elementwise
combiners widen to the widest floating input (amp_multicast semantics)."""

# run in target (bf16/fp16) precision — MXU-bound
# (reference FP16_FUNCS: Convolution/Deconvolution/FullyConnected/RNN +
# the attention matmul ops)
TARGET_DTYPE_OPS = [
    "matmul", "dot", "einsum", "tensordot", "convolution", "deconvolution",
    "fused_conv_bn_relu",   # BN statistics accumulate f32 internally
    "fully_connected", "batch_dot", "rnn", "multi_head_attention",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
]

# always fp32 — numerically sensitive
# (reference FP32_FUNCS: norm layers, softmax family, losses, exp/log
# transcendentals, cumulative reductions)
FP32_OPS = [
    "softmax", "log_softmax", "masked_softmax", "masked_log_softmax",
    "softmin", "batch_norm", "layer_norm", "group_norm", "instance_norm",
    "l2_normalization", "lrn",
    "sum", "mean", "var", "std", "norm", "cumsum", "prod", "nansum",
    "exp", "expm1", "log", "log1p", "log2", "log10", "erf", "erfinv",
    "gamma", "gammaln", "digamma", "sqrt", "cbrt",
    "arccos", "arcsin", "arctanh", "arccosh", "cosh", "sinh", "tan",
    "softmax_cross_entropy", "smooth_l1", "ctc_loss", "softmax_output",
    "linear_regression_output", "logistic_regression_output",
    "mae_regression_output", "make_loss",
]

# fp32 only for specific attr values, encoded as dispatch-name suffixes
# ("activation:softrelu") — the analog of the reference's
# CONDITIONAL_FP32_FUNCS [(op, attr, values)] triples
# (amp/lists/symbol_fp16.py CONDITIONAL_FP32_FUNCS)
CONDITIONAL_FP32_OPS = [
    ("activation", "act_type", ["softrelu"]),
    ("leaky_relu", "act_type", ["elu", "selu"]),
    ("pooling", "pool_type", ["lp", "sum"]),
]

# elementwise combiners: cast mixed floating inputs to the widest dtype
# present (reference: WIDEST_TYPE_CASTS via amp_multicast,
# symbol_fp16.py:629-688 — the full npi tail)
WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "true_divide", "divide", "where",
    "maximum", "minimum", "fmax", "fmin", "fmod", "hypot", "mod",
    "remainder", "copysign", "cross", "kron", "ldexp", "arctan2",
    "ediff1d", "logical_and", "logical_or", "logical_xor",
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "concatenate", "stack", "column_stack", "vstack", "hstack", "dstack",
    "dot", "inner", "outer", "vdot",
]


def conditional_fp32_names():
    """The conditional triples expanded to exact dispatch names
    (dispatch names carry the attr value as a suffix)."""
    out = set()
    for op, _attr, values in CONDITIONAL_FP32_OPS:
        for v in values:
            out.add(f"{op}:{v}")
    return out

"""Symbol graph + executor.

Reference parity: python/mxnet/symbol/symbol.py (class Symbol: composition,
list_arguments, infer_shape, bind, eval, tojson/fromjson; executor.py
Executor.forward/backward). The graph is a python DAG whose ops are names
resolved against mx.np / mx.npx / mx.sym registries — the same callables
eager mode uses, so symbolic results match imperative results exactly.
"""
from __future__ import annotations

import json

from ..base import MXNetError
from ..numpy.multiarray import ndarray


class Symbol:
    """A node in the symbolic graph."""

    def __init__(self, op, inputs, kwargs=None, name=None, num_outputs=1,
                 output_index=None):
        from .. import name as _name_mod
        self._op = op                  # op name string; None for variables
        self._inputs = list(inputs)    # Symbol inputs
        self._kwargs = dict(kwargs or {})
        # only unnamed symbols go through the NameManager: explicit names
        # must survive graph reconstruction (load_json, amp rewrite)
        # untouched, or a Prefix scope would corrupt round-trips
        if name is None:
            name = _name_mod.current().get(None, op if op else "sym")
        self.name = name
        self._num_outputs = num_outputs
        self._output_index = output_index

    # -- composition --------------------------------------------------------
    def __add__(self, other):
        return _make("add", self, other)

    def __radd__(self, other):
        return _make("add", other, self)

    def __sub__(self, other):
        return _make("subtract", self, other)

    def __rsub__(self, other):
        return _make("subtract", other, self)

    def __mul__(self, other):
        return _make("multiply", self, other)

    def __rmul__(self, other):
        return _make("multiply", other, self)

    def __truediv__(self, other):
        return _make("divide", self, other)

    def __rtruediv__(self, other):
        return _make("divide", other, self)

    def __pow__(self, other):
        return _make("power", self, other)

    def __neg__(self):
        return _make("negative", self)

    def __getitem__(self, index):
        if isinstance(index, int) and self._num_outputs > 1:
            return Symbol(self._op, self._inputs, self._kwargs,
                          f"{self.name}[{index}]", self._num_outputs, index)
        return _make("slice_index", self, index=index)

    def __getattr__(self, name):
        """Fluent op methods: ``s.abs()``, ``s.argmax(axis=1)``, ... —
        the reference generates a FIXED list of per-op methods on Symbol
        (symbol.py abs/argmax/.../zeros_like); only those names resolve,
        so ``hasattr(sym, 'dtype')``-style duck-typing probes keep their
        AttributeError contract (dtype/array/load are module callables,
        not ops)."""
        if name.startswith("_") or name not in _FLUENT_METHODS:
            if name in ("asnumpy", "asscalar", "tolist", "item",
                        "wait_to_read"):
                # reference raises NotImplementedForSymbol: a symbol has
                # no values until bound/evaluated
                raise AttributeError(
                    f"Symbol.{name} is not supported: symbols are "
                    "abstract; bind/eval first (reference: "
                    "NotImplementedForSymbol)")
            raise AttributeError(f"Symbol has no attribute {name!r}")
        fn = __getattr__(name)  # the module-level op lookup (late-bound)

        def method(*args, **kwargs):
            return fn(self, *args, **kwargs)
        method.__name__ = name
        return method

    def astype(self, dtype):
        return _make("Cast", self, dtype=dtype)

    def detach(self):
        # gradients must NOT flow through (eager ndarray.detach returns
        # an untracked array); stop_gradient is in the legacy op table
        return _make("stop_gradient", self)

    def as_np_ndarray(self):
        return self  # one unified Symbol type (reference has np/legacy)

    def as_nd_ndarray(self):
        return self

    def attr(self, key):
        if key in getattr(self, "_attrs", {}):
            return self._attrs[key]
        return self._kwargs.get(key)

    # -- user attributes (reference: symbol.py list_attr:611, attr_dict:634,
    # _set_attr:665 — the attr-dict graph-surgery surface) ------------------
    def _set_attr(self, **kwargs):
        """Attach/overwrite string attributes on this node (the reference's
        MXSymbolSetAttr; used for __lr_mult__-style graph annotations)."""
        for k, v in kwargs.items():
            if not isinstance(v, str):
                raise MXNetError(
                    f"Set Attr only accepts string values, got {type(v)} "
                    f"for key {k!r}")
        if not hasattr(self, "_attrs"):
            self._attrs = {}
        self._attrs.update(kwargs)

    def list_attr(self, recursive=False):
        if recursive:
            raise MXNetError(
                "list_attr(recursive=True) was deprecated in the reference; "
                "use attr_dict()")
        return dict(getattr(self, "_attrs", {}))

    def attr_dict(self):
        """{node_name: {attr: value}} over the whole graph."""
        out = {}
        for s in self._topo():
            attrs = dict(getattr(s, "_attrs", {}))
            if attrs:
                out[s.name] = attrs
        return out

    # -- introspection ------------------------------------------------------
    def _topo(self):
        seen, order = set(), []

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            order.append(s)
        visit(self)
        return order

    def list_arguments(self):
        """Free variables in topological order (reference:
        symbol.py list_arguments)."""
        return [s.name for s in self._topo() if s._op is None]

    def list_outputs(self):
        return [self.name + "_output"]

    def get_internals(self):
        return Group([s for s in self._topo() if s._op is not None] or [self])

    def infer_shape(self, **kwargs):
        """Shape inference by abstract evaluation (reference infer_shape)."""
        import jax
        import jax.numpy as jnp
        args = self.list_arguments()
        avals = {n: jax.ShapeDtypeStruct(tuple(kwargs[n]), jnp.float32)
                 for n in args if n in kwargs}
        if len(avals) != len(args):
            missing = [n for n in args if n not in avals]
            raise MXNetError(f"infer_shape missing args {missing}")

        def fn(vals):
            out = self._eval_with(vals)
            unwrap = lambda o: o._data if isinstance(o, ndarray) else o
            if isinstance(out, (list, tuple)):
                return [unwrap(o) for o in out]
            return unwrap(out)
        out = jax.eval_shape(fn, avals)
        out_shapes = [tuple(o.shape) for o in
                      (out if isinstance(out, (list, tuple)) else [out])]
        arg_shapes = [tuple(kwargs[n]) for n in args]
        return arg_shapes, out_shapes, []

    def infer_type(self, **kwargs):
        """Dtype inference (reference: symbol.py infer_type:898 over
        nnvm InferType). Propagates dtypes through the DAG: arithmetic
        follows jnp.result_type promotion; op-specific rules (Cast,
        comparisons, index-producing ops) come from a small table. Args
        without a given dtype default to float32 like the reference."""
        return self._infer_type_impl(kwargs, partial=False)

    def infer_type_partial(self, **kwargs):
        """Like infer_type but unknown inputs stay None (reference:
        symbol.py infer_type_partial:967)."""
        return self._infer_type_impl(kwargs, partial=True)

    _TYPE_RULES = {
        "Cast": "dtype", "cast": "dtype", "amp_cast": "dtype",
        **{n: "bool" for n in (
            "equal", "not_equal", "greater", "greater_equal", "less",
            "less_equal", "logical_and", "logical_or", "logical_xor",
            "logical_not", "isnan", "isinf", "isfinite",
            "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
            "broadcast_greater_equal", "broadcast_lesser",
            "broadcast_lesser_equal", "broadcast_logical_and",
            "broadcast_logical_or", "broadcast_logical_xor")},
        **{n: "int" for n in ("argmax", "argmin", "argsort",
                              "argmax_channel")},
    }

    def _infer_type_impl(self, given, partial):
        import jax.numpy as jnp
        import numpy as onp

        from ..base import np_dtype
        dts = {}
        for node in self._topo():
            if node._op is None:
                dt = np_dtype(given.get(node.name))
                if dt is None and not partial:
                    dt = onp.float32
                dts[id(node)] = dt
                continue
            rule = self._TYPE_RULES.get(node._op)
            ins = [dts[id(i)] for i in node._inputs]
            if rule == "dtype":
                dts[id(node)] = np_dtype(node._kwargs.get("dtype")) \
                    or onp.float32
            elif rule == "bool":
                dts[id(node)] = onp.bool_
            elif rule == "int":
                dts[id(node)] = onp.int64
            else:
                known = [d for d in ins if d is not None]
                dts[id(node)] = (onp.dtype(jnp.result_type(*known)).type
                                 if known else (None if partial
                                                else onp.float32))
        arg_types = [dts[id(s)] for s in self._topo() if s._op is None]
        return arg_types, [dts[id(self)]], []

    def gradient(self, wrt):
        """Autodiff symbol: evaluates to the gradients of this (scalar)
        symbol w.r.t. the named arguments. The reference declares this API
        but never implemented it (symbol.py:1879 'currently not
        implemented'); here jax.grad makes it real. Returns a symbol whose
        eval yields one array per name in ``wrt``."""
        if isinstance(wrt, str):
            wrt = [wrt]
        args = self.list_arguments()
        for n in wrt:
            if n not in args:
                raise MXNetError(f"gradient wrt unknown argument {n!r}")
        return _GradSymbol(self, tuple(wrt))

    # -- evaluation ---------------------------------------------------------
    def _eval_with(self, bindings):
        """Interpret the DAG with ndarray ops (cached per-node)."""
        from .. import numpy as np
        from .. import numpy_extension as npx
        values = {}
        for node in self._topo():
            if node._op is None:
                if node.name not in bindings:
                    raise MXNetError(f"unbound variable {node.name!r}")
                values[id(node)] = bindings[node.name]
                continue
            fn = _resolve(node._op)
            args = [values[id(i)] for i in node._inputs]
            out = fn(*args, **node._kwargs)
            if node._output_index is not None:
                out = out[node._output_index]
            values[id(node)] = out
        return values[id(self)]

    def eval(self, ctx=None, **kwargs):
        """Evaluate with keyword bindings (reference: symbol.py eval)."""
        out = self._eval_with(kwargs)
        return out if isinstance(out, (list, tuple)) else [out]

    def optimize_for(self, backend, **kwargs):
        """Backend graph rewrite (reference: symbol.py optimize_for over
        the subgraph property API).  'bf16'/'fp16' apply the AMP
        ReducePrecision rewrite (amp.convert_symbol); 'xla' is the
        identity (XLA subsumes partitioning)."""
        if backend in ("bf16", "bfloat16"):
            from .. import amp
            return amp.convert_symbol(self, target_dtype="bfloat16",
                                      **kwargs)
        if backend in ("fp16", "float16"):
            from .. import amp
            return amp.convert_symbol(self, target_dtype="float16",
                                      **kwargs)
        if backend in ("xla", None, "default"):
            return self
        raise MXNetError(f"unknown symbol backend {backend!r}")

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             **kwargs):
        return Executor(self, args or {}, args_grad, grad_req)

    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        """Allocate zero-filled args from shapes then bind."""
        from .. import numpy as np
        args = {n: np.zeros(tuple(shapes[n])) for n in self.list_arguments()}
        return Executor(self, args, None, grad_req)

    # -- serialization (reference json schema) ------------------------------
    @staticmethod
    def _enc_attr(v):
        """Attr encoder: ndarray constants serialize by value (the
        reference stores constants in the params file; here they live in
        the graph json so a bare json round-trips)."""
        if isinstance(v, ndarray):
            return json.dumps({"__ndarray__": v.asnumpy().tolist(),
                               "dtype": str(v.dtype)})
        return v if isinstance(v, str) else json.dumps(v)

    def tojson(self):
        # Group serializes as multiple heads entries (the reference schema
        # supports this); the synthetic 'group' node itself is not emitted.
        head_syms = self.symbols if isinstance(self, Group) else [self]
        nodes, index, seen = [], {}, set()

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            index[id(s)] = len(nodes)
            node = {
                "op": "null" if s._op is None else s._op,
                "name": s.name,
                "attrs": {k: self._enc_attr(v)
                          for k, v in s._kwargs.items()},
                "inputs": [[index[id(inp)], 0, 0] for inp in s._inputs],
            }
            # user attrs (_set_attr) go under their own key: merging them
            # into op attrs would be ambiguous on reload (any string key
            # is a legal user attr)
            if getattr(s, "_attrs", None):
                node["user_attrs"] = dict(s._attrs)
            nodes.append(node)
        for h in head_syms:
            if isinstance(h, Group):
                raise MXNetError("nested Group symbols do not serialize")
            visit(h)
        arg_nodes = [i for i, n in enumerate(nodes) if n["op"] == "null"]
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": [[index[id(h)], 0, 0] for h in head_syms],
            "attrs": {"mxnet_version": ["int", 20000]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        return f"<Symbol {self.name}>"


class _GradSymbol(Symbol):
    """Symbol computing d(base)/d(wrt args) via jax.grad at eval time."""

    def __init__(self, base, wrt):
        super().__init__("_gradient", [base], {"wrt": wrt},
                         name=f"{base.name}_grad")
        self._base = base
        self._wrt = wrt

    def _eval_with(self, bindings):
        import jax

        from ..numpy.multiarray import _wrap, ndarray as _nd
        raws = {k: (v._data if isinstance(v, _nd) else v)
                for k, v in bindings.items()}

        def loss(wrt_vals):
            b = dict(raws)
            b.update(wrt_vals)
            out = self._base._eval_with(
                {k: _wrap(v) for k, v in b.items()})
            res = out._data if isinstance(out, _nd) else out
            if res.ndim:
                raise MXNetError(
                    "gradient() needs a scalar head symbol; got shape "
                    f"{res.shape}")
            return res

        grads = jax.grad(loss)({k: raws[k] for k in self._wrt})
        return [_wrap(grads[k]) for k in self._wrt]

    def list_outputs(self):
        return [f"{n}_grad" for n in self._wrt]


class Group(Symbol):
    """Multiple outputs (reference: symbol.py Group)."""

    def __init__(self, symbols):
        super().__init__("group", symbols, name="group")
        self.symbols = symbols

    def _eval_with(self, bindings):
        return [s._eval_with(bindings) for s in self.symbols]

    def list_outputs(self):
        return [s.name + "_output" for s in self.symbols]


def Variable(name, shape=None, dtype=None, **kwargs):
    s = Symbol(None, [], kwargs, name)
    s._shape = shape
    s._dtype = dtype
    return s


var = Variable


def _make(op, *inputs, **kwargs):
    syms = []
    for x in inputs:
        if isinstance(x, Symbol):
            syms.append(x)
        else:
            const = Symbol("constant", [], {"value": x},
                           name=f"const{len(syms)}")
            syms.append(const)
    return Symbol(op, syms, kwargs)


def _resolve(op):
    from .. import numpy as np
    from .. import numpy_extension as npx
    from ..ndarray import register as _legacy
    if op == "constant":
        def c(value=None):
            return np.array(value) if not isinstance(value, ndarray) else value
        return c
    if op == "slice_index":
        return lambda x, index=None: x[index]
    fn = _legacy.get(op)
    if fn is not None:
        return fn
    # npx before np: mx.np's jnp/jax.nn fallback would shadow the
    # reference-signature npx ops at eval time (same order as build time)
    for mod in (npx, np):
        fn = getattr(mod, op, None)
        if fn is not None:
            return fn
    raise MXNetError(f"symbolic op {op!r} not found in mx.np/mx.npx")


def load_json(json_str):
    """Rebuild a Symbol from the json schema (reference: fromjson)."""
    data = json.loads(json_str)
    built = []
    for node in data["nodes"]:
        kwargs = {}
        for k, v in node.get("attrs", {}).items():
            try:
                val = json.loads(v)
            except (json.JSONDecodeError, TypeError):
                val = v
            if isinstance(val, dict) and "__ndarray__" in val:
                from ..numpy import array
                val = array(val["__ndarray__"], dtype=val.get("dtype"))
            kwargs[k] = val
        if node["op"] == "null":
            built.append(Variable(node["name"], **kwargs))
        else:
            inputs = [built[i] for i, _, _ in node["inputs"]]
            built.append(Symbol(node["op"], inputs, kwargs, node["name"]))
        user_attrs = node.get("user_attrs")
        if user_attrs:
            built[-1]._set_attr(**user_attrs)
    heads = [built[i] for i, _, _ in data["heads"]]
    return heads[0] if len(heads) == 1 else Group(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


class Executor:
    """Reference: python/mxnet/executor.py Executor (bind product).

    forward() interprets the graph with eager XLA ops; backward() records
    a tape over the forward and writes arg grads (grad_req='write'/'add').
    """

    def __init__(self, symbol, args, args_grad=None, grad_req="write"):
        self._symbol = symbol
        self.arg_dict = dict(args)
        self.grad_dict = dict(args_grad or {})
        self._grad_req = grad_req
        self.outputs = []

    def forward(self, is_train=False, **kwargs):
        self.arg_dict.update(kwargs)
        if is_train:
            from .. import autograd
            for v in self.arg_dict.values():
                if isinstance(v, ndarray) and v._grad_req == "null":
                    v.attach_grad(self._grad_req)
            with autograd.record():
                out = self._symbol._eval_with(self.arg_dict)
                self._recorded = out
        else:
            out = self._symbol._eval_with(self.arg_dict)
        self.outputs = out if isinstance(out, (list, tuple)) else [out]
        return self.outputs

    def backward(self, out_grads=None):
        from .. import autograd
        if not self.outputs:
            raise MXNetError("call forward(is_train=True) first")
        autograd.backward(self.outputs, out_grads)
        for name, arr in self.arg_dict.items():
            if isinstance(arr, ndarray) and arr.grad is not None:
                self.grad_dict[name] = arr.grad
        return self.grad_dict

    # -- reference surface tail (executor.py:232-393) ---------------------
    @property
    def arg_arrays(self):
        return list(self.arg_dict.values())

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self.arg_dict]

    @property
    def aux_dict(self):
        """Aux states: the functional graph keeps none outside arg_dict
        (BatchNorm stats ride Gluon parameters); kept for surface parity."""
        return {}

    @property
    def aux_arrays(self):
        return list(self.aux_dict.values())

    @property
    def output_dict(self):
        names = self._symbol.list_outputs()
        return {n: o for n, o in zip(names, self.outputs)}

    def get_optimized_symbol(self):
        """XLA owns graph optimization; the bound symbol IS the graph
        (reference: executor.py:126 returns the partitioned symbol)."""
        return self._symbol

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Load a parameter dict into the bound arrays
        (reference: executor.py:342)."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                dst = self.arg_dict[name]
                dst._rebind(array._data.astype(dst.dtype))
            elif not allow_extra_params:
                raise ValueError(
                    f'Find name "{name}" that is not in the arguments')
        for name in (aux_params or {}):
            if not allow_extra_params:
                raise ValueError(
                    f"Find name {name} that is not in the auxiliary states")


def __getattr__(name):
    """Any mx.np / mx.npx / legacy-table op lifted to symbolic composition
    (the analog of symbol/register.py generated wrappers)."""
    from .. import numpy as np
    from .. import numpy_extension as npx
    from ..ndarray import register as _legacy
    # npx before np: mx.np's __getattr__ falls back to jnp/jax.nn for
    # unknown names, which would shadow reference-signature npx ops
    # (softmax temperature=, one_hot on_value=, ...)
    target = _legacy.get(name) or getattr(npx, name, None) \
        or getattr(np, name, None)
    if target is None or not callable(target):
        raise AttributeError(name)

    def symbolic(*args, **kwargs):
        if any(isinstance(a, Symbol) for a in args):
            return _make(name, *args, **kwargs)
        return target(*args, **kwargs)
    symbolic.__name__ = name
    return symbolic


# the reference's generated fluent-method list (symbol.py def tail),
# minus names that are real methods/properties here and the
# NotImplementedForSymbol set handled in __getattr__
_FLUENT_METHODS = frozenset("""
abs arccos arccosh arcsin arcsinh arctan arctanh argmax argmax_channel
argmin argsort broadcast_axes broadcast_like broadcast_to cbrt ceil clip
cos cosh degrees depth_to_space diag exp expand_dims expm1 fix flatten
flip floor log log10 log1p log2 log_sigmoid log_softmax max mean min
mish nanprod nansum norm one_hot ones_like pad pick prod radians rcbrt
reciprocal relu repeat reshape reshape_like rint round rsqrt shape_array
sigmoid sign sin sinh size_array slice slice_axis slice_like softmax
softmin sort space_to_depth split split_v2 sqrt square squeeze sum
swapaxes take tan tanh tile topk transpose trunc zeros_like
""".split())


"""mx.sym — symbolic graph frontend.

Reference parity: python/mxnet/symbol/ (15.8k LoC: Symbol graph building
over NNVM, bind/simple_bind executors, tojson/load). TPU-native design: a
Symbol is a small python DAG over the same op implementations the eager
frontend uses; ``bind`` interprets it eagerly (NDArray ops → XLA) and
``Executor.forward`` under jit via hybridization semantics. The graph
serializes to the reference's json shape (nodes/arg_nodes/heads) so
model-symbol.json round-trips.
"""
from .symbol import (  # noqa: F401
    Symbol, Variable, var, Group, load, load_json, Executor,
)
from . import symbol as _symbol_mod


def __getattr__(name):
    return getattr(_symbol_mod, name)

"""mx.init — parameter initializers.

Reference parity: python/mxnet/initializer.py (registry + Uniform/Normal/
Xavier/MSRAPrelu/Orthogonal/Constant/One/Zero/Bilinear/LSTMBias). Samplers
draw from the global threefry stream (mx.random), so seeding is reproducible.
"""
from __future__ import annotations

import math
import re

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError, _Registry
from . import random as _random

_registry = _Registry("initializer")
register = _registry.register


class Initializer:
    """Base initializer (reference: initializer.py:45)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr=None):
        # supports both init(desc, arr) legacy and init(arr) forms
        if arr is None:
            name, arr = "weight", name
        if isinstance(name, InitDesc):
            # reference initializer.py:131-142: an attrs['__init__'] config
            # overrides everything; otherwise the name-pattern dispatch
            # below runs with this initializer as the fallback
            if name.global_init is None:
                name.global_init = self
            attr_init = name.attrs.get("__init__", "")
            if attr_init:
                # reference calls _init_weight directly: the attr config
                # REPLACES the name-pattern dispatch (a bias with
                # init='one' must come out ones, not pattern-zeroed)
                create(attr_init)._init_weight(str(name), arr)
                return
        self.init_weight(str(name), arr)

    def init_weight(self, name, arr):
        if name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith(("beta", "bias", "mean", "moving_mean")):
            self._init_zero(arr)
        elif "running_var" in name or "moving_var" in name:
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_zero(self, arr):
        arr._rebind(jnp.zeros(arr.shape, arr.dtype))

    def _init_one(self, arr):
        arr._rebind(jnp.ones(arr.shape, arr.dtype))

    def dumps(self):
        import json
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register("zero")
@register("zeros")
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


@register("one")
@register("ones")
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


@register()
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._rebind(jnp.full(arr.shape, self.value, arr.dtype))


@register()
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr._rebind(jax.random.uniform(_random._next_key(), arr.shape,
                                       jnp.float32, -self.scale,
                                       self.scale).astype(arr.dtype))


@register()
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr._rebind((jax.random.normal(_random._next_key(), arr.shape)
                     * self.sigma).astype(arr.dtype))


@register()
class Xavier(Initializer):
    """Reference: initializer.py Xavier (rnd_type/factor_type/magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires ndim>=2 param, got {shape} for {name}")
        if len(shape) > 2:
            hw_scale = float(onp.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        key = _random._next_key()
        if self.rnd_type == "uniform":
            val = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            val = jax.random.normal(key, shape) * scale
        arr._rebind(val.astype(arr.dtype))


@register()
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register()
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(onp.prod(arr.shape[1:]))
        val = jax.random.orthogonal(_random._next_key(), max(nout, nin))
        arr._rebind((self.scale * val[:nout, :nin]).reshape(arr.shape)
                    .astype(arr.dtype))


@register()
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = onp.zeros(arr.shape, dtype=onp.float32)
        n = arr.shape[0] // 4
        b[n:2 * n] = self.forget_bias
        arr._rebind(jnp.asarray(b, arr.dtype))


@register()
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = onp.zeros(int(onp.prod(shape)), dtype=onp.float32)
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._rebind(jnp.asarray(weight.reshape(shape), arr.dtype))


class Mixed:
    """Pattern-matched initializer dispatch (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name!r}")


class InitDesc(str):
    """Initialization descriptor: a parameter NAME carrying its symbol
    attrs and the global fallback initializer (reference
    initializer.py:36 — init_weight dispatches on this string)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Load:
    """Initialize by name from a params file or dict (reference
    initializer.py:316; 'arg:'/'aux:' prefixes stripped like 1.x
    checkpoints carry)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from . import npx
            param = npx.load(param)
        if not isinstance(param, dict):
            raise MXNetError("param must be a filename or a name->array "
                             "dict")
        self.param = {}
        for name, arr in param.items():
            key = name[4:] if name.startswith(("arg:", "aux:")) else name
            self.param[key] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        import logging
        name = str(name)
        if name in self.param:
            src = self.param[name]
            if tuple(arr.shape) != tuple(src.shape):
                raise MXNetError(
                    f"parameter {name} cannot be initialized by loading: "
                    f"shape {tuple(arr.shape)} vs loaded "
                    f"{tuple(src.shape)}")
            arr[:] = src
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise MXNetError(
                    f"cannot initialize {name}: not found in loaded "
                    "params and no default initializer given")
            self.default_init(name, arr)
            if self.verbose:
                logging.info("Initialized %s by default", name)


# expose this module's registry through the generic mx.registry factory
# (reference initializer.py:277-279 builds its triple the same way), so
# alias/create share one namespace and one config grammar with it
from . import registry as _registry_mod  # noqa: E402

_registry_mod._REGISTRIES[Initializer] = _registry
alias = _registry_mod.get_alias_func(Initializer, "initializer")
create = _registry_mod.get_create_func(Initializer, "initializer")

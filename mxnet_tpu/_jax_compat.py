"""Version-portability shims over the JAX API surface.

The supported JAX range moves APIs around between releases; every such
rename is absorbed here once so the rest of the codebase imports one
stable spelling.  Robustness: an import-time failure in a shim would take
the whole package down (every module transitively imports this), so each
shim must resolve across the full supported range.

- ``shard_map``: top-level ``jax.shard_map`` from 0.5; lived at
  ``jax.experimental.shard_map.shard_map`` through 0.4.x.  Newer jax also
  renamed the ``check_rep`` kwarg to ``check_vma``; callers use the new
  spelling and the shim translates down when needed.
- ``enable_x64``: top-level ``jax.enable_x64`` from 0.5; lived at
  ``jax.experimental.enable_x64`` before.
"""
from __future__ import annotations

import functools
import inspect

try:
    from jax import shard_map as _shard_map  # jax >= 0.5
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

try:
    from jax import enable_x64  # jax >= 0.5
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental import enable_x64

__all__ = ["shard_map", "enable_x64"]

"""Execution-engine facade.

Reference parity: src/engine/ (ThreadedEnginePerDevice) + python/mxnet/engine.py.

TPU-native design: there is no user-visible dependency engine to rebuild —
JAX/PJRT *is* the async engine. Every op dispatch enqueues work on the device
stream and returns a future-like jax.Array; program order per buffer gives the
same write-after-read guarantees MXNet's versioned vars provide, and
``block_until_ready`` is ``WaitForVar``. This module keeps the MXNet knobs as
functional facades so reference code runs, and tracks recently dispatched
arrays so ``waitall`` has real semantics.
"""
from __future__ import annotations

import contextlib
import threading
import weakref

import jax

_lock = threading.Lock()
_pending = weakref.WeakSet()
_bulk_size = 0


def _track(arr):
    """Register a dispatched jax.Array for waitall. Cheap: WeakSet add."""
    try:
        with _lock:
            _pending.add(arr)
    except TypeError:
        pass


def wait_all():
    """Engine::WaitForAll analog: block on every live dispatched array."""
    from . import pipeline as _pipeline  # engine imports before pipeline
    if _pipeline._guard_depth:
        _pipeline.note_host_sync("engine.wait_all")
    with _lock:
        arrs = list(_pending)
        _pending.clear()
    for a in arrs:
        try:
            a.block_until_ready()
        except Exception:  # noqa: BLE001 - deferred async errors surface here
            raise


def set_bulk_size(size):
    """Reference: mx.engine.set_bulk_size (op bulking, threaded_engine.h:433).

    XLA fuses/bulks automatically under jit; eager dispatch is already async.
    Kept as a stored knob for API parity; returns the previous value.
    """
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size=None):
    if size is None:
        from . import config
        size = config.get("engine.bulk_size")
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)

"""mx.name — symbol name manager.

Reference parity: python/mxnet/name.py (NameManager thread/with-scoped
auto-naming of symbols, Prefix variant).
"""
from __future__ import annotations

import threading

_local = threading.local()


class NameManager:
    """Auto-generates unique names per op type (reference: name.py
    NameManager; `with NameManager():` scopes it)."""

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        self._old = current()
        _local.manager = self
        return self

    def __exit__(self, *exc):
        _local.manager = self._old


class Prefix(NameManager):
    """Prepends a prefix to every generated name (reference: Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current():
    mgr = getattr(_local, "manager", None)
    if mgr is None:
        mgr = NameManager()
        _local.manager = mgr
    return mgr

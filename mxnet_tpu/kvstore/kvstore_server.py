"""KVStore server-role entrypoint — documented N/A pointer.

Reference parity: python/mxnet/kvstore/kvstore_server.py (KVStoreServer
wraps the C++ ps-lite server loop: a dedicated process applies optimizer
updates for dist_sync/dist_async workers, launched with DMLC_ROLE=server
by tools/launch.py).

TPU-native design has NO server processes: parameters and optimizer
state live sharded on the workers themselves and reduce via XLA
collectives over the mesh (kvstore/dist.py over jax.distributed), which
is strictly stronger — the "server" is the ICI/DCN fabric. This module
keeps the import path and the launcher contract: a process started with
a server role gets a clear explanation instead of a silent hang.
"""
from __future__ import annotations

import os

from ..base import MXNetError

__all__ = ["KVStoreServer", "init_server_module"]

_MSG = ("parameter-server roles do not exist on the TPU backend: "
        "optimizer state is worker-sharded and gradients reduce via mesh "
        "collectives (kvstore/dist.py). Launch every process as a worker "
        "(tools/launch.py does this; drop -s/--num-servers).")


class KVStoreServer:
    """Reference: kvstore_server.py KVStoreServer(kvstore). Constructing
    one is accepted (scripts instantiate before run()); run() fails with
    the architectural pointer."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore

    def run(self):
        raise MXNetError(_MSG)


def init_server_module():
    """Reference: _init_kvstore_server_module — called at import when
    DMLC_ROLE=server to hijack the process into the server loop. Here it
    fails fast with the pointer instead of hanging a misconfigured
    launch."""
    if os.environ.get("DMLC_ROLE") == "server":
        raise MXNetError(_MSG)

"""Gradient compression for the cross-process (DCN) push path.

Reference parity: src/kvstore/gradient_compression.h:37-127 (+ .cc/.cu
kernels): 1-bit/2-bit stochastic quantization with an error-feedback
residual kept on the worker, applied to worker->server pushes;
docs/static_site/src/pages/api/faq/gradient_compression.md.

TPU-native design: quantization is a jitted elementwise XLA program; the
residual is per-key device state. The quantized tensor's values are exact
multiples of the threshold, so summing dequantized contributions across
processes (an XLA psum over the DCN axis) is bit-identical to the
reference's server-side dequantize-then-accumulate. ``pack_codes`` /
``unpack_codes`` give the 2-bit-per-value (or 1-bit) byte wire format for
transports outside XLA collectives.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError

__all__ = ["GradientCompression", "pack_codes", "unpack_codes"]


@functools.partial(jax.jit, static_argnames=("mode",))
def _quantize(x, residual, threshold, mode):
    """q in {-t, 0, +t} ('2bit') or {-t, +t} ('1bit'); returns (q, new_res)."""
    acc = x + residual
    t = jnp.asarray(threshold, x.dtype)
    if mode == "2bit":
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t,
                                             jnp.zeros((), x.dtype)))
    else:  # 1bit: sign quantization around 0
        q = jnp.where(acc >= 0, t, -t)
    return q, acc - q


class GradientCompression:
    """Per-key quantizer with error-feedback residual (worker side)."""

    def __init__(self, type="2bit", threshold=0.5):
        if type not in ("1bit", "2bit"):
            raise MXNetError(f"unsupported compression type {type!r} "
                             "(reference supports '1bit'/'2bit')")
        if float(threshold) <= 0:
            raise MXNetError("compression threshold must be positive")
        self.type = type
        self.threshold = float(threshold)
        self._residual = {}

    def quantize(self, key, grad):
        """Quantize one key's local gradient (raw jax array in, raw out)."""
        res = self._residual.get(key)
        if res is None or res.shape != grad.shape:
            res = jnp.zeros_like(grad)
        q, self._residual[key] = _quantize(grad, res, self.threshold,
                                           self.type)
        return q

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}


def _bits(mode):
    return 2 if mode == "2bit" else 1


def pack_codes(q, threshold, mode="2bit"):
    """Quantized values -> packed uint8 wire bytes.

    2-bit codes (reference encoding: 0 -> 00, +t -> 01, -t -> 10) packed 4
    per byte, little-end first; 1-bit codes (+t -> 1, -t -> 0) packed 8 per
    byte. Returns (packed uint8 ndarray, element count).
    """
    flat = onp.asarray(q, dtype="float32").reshape(-1)
    if mode == "2bit":
        codes = onp.where(flat > 0, 1, onp.where(flat < 0, 2, 0)).astype("uint8")
        per, width = 4, 2
    else:
        codes = (flat >= 0).astype("uint8")
        per, width = 8, 1
    pad = (-len(codes)) % per
    codes = onp.pad(codes, (0, pad))
    packed = onp.zeros(len(codes) // per, dtype="uint8")
    for i in range(per):
        packed |= codes[i::per] << (width * i)
    return packed, len(flat)


def unpack_codes(packed, n, threshold, mode="2bit", dtype="float32"):
    """Packed uint8 wire bytes -> quantized values (inverse of pack_codes)."""
    packed = onp.asarray(packed, dtype="uint8")
    if mode == "2bit":
        per, width, mask = 4, 2, 0b11
        lut = onp.array([0.0, threshold, -threshold, 0.0], dtype=dtype)
    else:
        per, width, mask = 8, 1, 0b1
        lut = onp.array([-threshold, threshold], dtype=dtype)
    codes = onp.zeros(len(packed) * per, dtype="uint8")
    for i in range(per):
        codes[i::per] = (packed >> (width * i)) & mask
    return lut[codes[:n]]

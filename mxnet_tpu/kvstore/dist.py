"""Distributed KVStore ('dist_sync'/'dist_device_sync'/'dist_async').

Reference parity: src/kvstore/kvstore_dist.h + kvstore_dist_server.h (ps-lite
worker/server/scheduler, ZPush/ZPull key slicing, sync/async modes) and
python/mxnet/kvstore/kvstore_server.py.

TPU-native design: there is no parameter server. Cross-host reduction is an
XLA AllReduce over the DCN mesh axis; rendezvous is jax.distributed
(PJRT coordination service replaces the ps-lite scheduler, SURVEY §5).
Workers call pushpull -> psum over all processes. Optimizer-on-server
(update_on_kvstore) runs the updater identically on every worker after the
reduce — bitwise-identical state without a server round-trip.

'dist_async' (DistAsyncKVStore): the reference's async server applies each
worker's update immediately with no cross-worker aggregation
(kvstore_dist_server.h:157 ApplyUpdates in async mode) — workers see stale
state bounded by their pull frequency.  Without a server, the TPU
emulation keeps a store REPLICA per process: push applies the updater to
the local replica immediately (no collective — genuinely asynchronous
progress), and pull reconciles by averaging replicas across processes (a
psum/N at the pull point), which is where other workers' updates become
visible.  Same eventual-consistency contract, staleness window = time
between pulls.

Gradient compression (reference: src/kvstore/gradient_compression.h) applies
on the worker before the cross-process reduce: the local gradient is 1-bit/
2-bit quantized with an error-feedback residual, and the psum accumulates
the (exactly representable) quantized contributions — numerically identical
to the reference's server-side dequantize-then-sum.

Multi-process bring-up is via env vars set by ``tools/launch.py`` (the
dmlc-tracker analog, tests/nightly/test_distributed_training-gpu.sh:25-38):
DMLC_PS_ROOT_URI/PORT, DMLC_NUM_WORKER, DMLC_WORKER_ID; or native
JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID.
"""
from __future__ import annotations

import os
import random as _pyrandom
import threading
import time

import jax
import jax.numpy as jnp

from .. import config as _config
from .. import fault as _fault
from .. import telemetry as _telemetry
from ..base import MXNetError, get_env
from ..numpy.multiarray import ndarray, _wrap
from .kvstore import KVStore


from .._dist_init import ensure_distributed as _ensure_distributed


class CollectiveTimeout(MXNetError):
    """A blocking cross-process collective missed its deadline.

    Structured so supervisors/tests can dispatch on the fields instead of
    parsing the message: ``op`` (collective kind), ``key`` (kvstore key, or
    None), ``rank``/``nprocs``, ``elapsed`` (seconds waited).
    """

    def __init__(self, op, key, rank, nprocs, elapsed, hint=""):
        self.op = op
        self.key = key
        self.rank = rank
        self.nprocs = nprocs
        self.elapsed = elapsed
        msg = (f"collective '{op}' for key {key!r} timed out after "
               f"{elapsed:.1f}s on rank {rank}/{nprocs}."
               f"{(' ' + hint) if hint else ''} Raise mx.config "
               "'kvstore.async_timeout' if the collective is merely slow.")
        super().__init__(msg)


class DistKVStore(KVStore):
    """Multi-host KVStore over XLA collectives."""

    def __init__(self, name="dist_sync"):
        super().__init__(name)
        _ensure_distributed()
        self._nprocs = jax.process_count()
        self._rank = jax.process_index()
        self._gc = None

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nprocs

    def set_gradient_compression(self, compression_params):
        """Install 1-bit/2-bit worker-side compression (reference:
        kvstore.h SetGradientCompression -> gradient_compression.h)."""
        from .gradient_compression import GradientCompression
        self._gc = GradientCompression(**dict(compression_params or {}))

    def _watchdog_engaged(self):
        # multi-process always; single-process only when the chaos point is
        # armed (so tests can exercise the timeout machinery without a
        # second process, and production 1-proc runs pay nothing)
        return self._nprocs > 1 or _fault.armed("kvstore.collective_timeout")

    def _timed_wait(self, op, key, fn, hint=""):
        """Run a blocking collective with a deadline.

        Every cross-process wait in this store goes through here: the
        collective runs on a helper thread, the caller joins with the
        ``kvstore.async_timeout`` deadline, and a miss raises a structured
        ``CollectiveTimeout`` naming the op/key/rank/elapsed — a mismatched
        SPMD schedule becomes a debuggable error instead of a silent
        freeze.  The helper thread is a daemon: if the collective later
        completes it dies quietly; if it never does, it parks forever
        without holding the process's exit hostage.
        """
        timeout = _config.get("kvstore.async_timeout")
        result = {}

        def wait():
            try:
                if _fault._active and \
                        _fault.fire("kvstore.collective_timeout"):
                    time.sleep(timeout + 3600)  # never completes
                    return
                result["value"] = fn()
            except Exception as e:  # noqa: BLE001 - ferried to caller
                result["error"] = e

        start = time.monotonic()
        t = threading.Thread(target=wait, daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive():
            _fault.record("kvstore.collective_timeout_raised")
            raise CollectiveTimeout(op, key, self.rank, self.num_workers,
                                    time.monotonic() - start, hint)
        if "error" in result:
            raise result["error"]
        return result["value"]

    @staticmethod
    def _is_transient(e):
        """Errors worth retrying: the watchdog's structured timeout, plus
        coordination-service/fabric blips whose message marks them as
        transient (a preempted peer shows up as one of these, not as a
        clean exception type)."""
        if isinstance(e, CollectiveTimeout):
            return True
        msg = str(e).lower()
        return any(tok in msg for tok in (
            "deadline exceeded", "unavailable", "connection reset",
            "connection refused", "broken pipe", "barrier timed out"))

    def _rejoin(self, op, attempt):
        """Best-effort re-barrier through the jax.distributed coordination
        service so surviving workers re-align on the retry boundary instead
        of racing into the retried collective skewed.  Failures are counted
        (``resilience.rejoin_failed``), never fatal: with a peer truly gone
        the retried collective itself is the authoritative probe, and a
        single-process chaos run has nobody to wait for."""
        if self._nprocs <= 1:
            return True
        try:
            from jax._src import distributed as _jd
            client = getattr(_jd.global_state, "client", None)
            if client is None:
                return False
            timeout_ms = int(
                float(_config.get("kvstore.rejoin_timeout")) * 1000)
            name = "".join(c if c.isalnum() else "_" for c in str(op))
            client.wait_at_barrier(f"mxtpu_rejoin_{name}_a{attempt}",
                                   timeout_ms)
        except Exception:  # noqa: BLE001 - best-effort by design
            _fault.record("resilience.rejoin_failed")
            if _telemetry._active:
                _telemetry.inc("resilience.rejoin_failed_total", op=op)
            return False
        _fault.record("resilience.rejoin")
        if _telemetry._active:
            _telemetry.inc("resilience.rejoin_total", op=op)
        return True

    def _collective(self, op, key, fn, hint=""):
        """Watchdogged collective with bounded retry-with-rejoin.

        A ``CollectiveTimeout`` (or transient coordination-service error)
        is retried up to ``kvstore.retry_max`` times: exponential backoff
        from ``kvstore.retry_backoff`` with up-to-25% jitter (so respawned
        peers don't stampede the coordinator in lockstep), then a
        best-effort re-barrier (``_rejoin``) before re-entering the
        collective.  An exhausted budget escalates a structured
        ``resilience.WorkerLost`` for the ``mx.resilience.run`` supervisor
        to catch.  ``kvstore.retry_max=0`` restores the raw raise-on-first-
        timeout contract (what a mismatched pull *schedule* needs — a
        deterministic deadlock only gets slower when retried).
        """
        retry_max = int(_config.get("kvstore.retry_max"))
        backoff = float(_config.get("kvstore.retry_backoff"))
        attempt = 0
        while True:
            try:
                return self._timed_wait(op, key, fn, hint)
            except Exception as e:  # noqa: BLE001 - filtered just below
                if retry_max <= 0 or not self._is_transient(e):
                    raise
                attempt += 1
                if attempt > retry_max:
                    from ..resilience import WorkerLost, _event
                    _event("worker_lost_raised", op=op.partition("#")[0])
                    raise WorkerLost(op, key, self.rank, self.num_workers,
                                     attempt, e) from e
                _fault.record("resilience.collective_retry")
                if _telemetry._active:
                    _telemetry.inc("resilience.collective_retry_total",
                                   op=op)
                delay = backoff * (2 ** (attempt - 1))
                if delay > 0:
                    time.sleep(delay * (1.0 + 0.25 * _pyrandom.random()))
                self._rejoin(op, attempt)

    def _count_collective(self, op, t0, payload):
        """Success-path telemetry for one completed collective (errors are
        counted separately in ``kvstore.collective_errors_total`` — a
        timed-out allreduce shipped nothing and must not inflate the
        throughput counters)."""
        if not _telemetry._active:
            return
        _telemetry.observe("kvstore.collective_seconds",
                           time.perf_counter() - t0, op=op)
        _telemetry.inc("kvstore.collective_total", op=op)
        raw = getattr(payload, "_data", payload)
        _telemetry.inc("kvstore.payload_bytes_total",
                       int(getattr(raw, "nbytes", 0)))

    def _allreduce(self, merged):
        """Cross-process sum (no deadline — see ``_timed_wait`` callers).
        Single process: identity. Multi-process: a tiny pjit'd psum over a
        global 1-d process mesh (DCN axis)."""
        if self._nprocs == 1:
            return merged
        from ..parallel.collectives import allreduce_across_processes
        return _wrap(allreduce_across_processes(merged._data))

    def _waited_allreduce(self, value):
        """Allreduce + completion wait, for use inside ``_timed_wait`` (the
        deadline must cover the async DCN wait, not just dispatch)."""
        out = self._allreduce(value)
        raw = getattr(out, "_data", out)
        if hasattr(raw, "block_until_ready"):
            raw.block_until_ready()
        return out

    def _merged(self, k, vs):
        """Local device reduce, optional quantization, cross-process sum
        (under the collective watchdog when engaged).  Telemetry times the
        cross-process phase and counts the payload actually shipped (the
        post-quantization bytes, so compression shows up in the metric)."""
        merged = self._reduce(vs)
        if self._gc is not None:
            merged = _wrap(self._gc.quantize(k, merged._data))
        t0 = time.perf_counter()
        try:
            if not self._watchdog_engaged():
                out = self._allreduce(merged)
            else:
                out = self._collective(
                    "allreduce", k,
                    lambda: self._waited_allreduce(merged))
        except Exception:
            if _telemetry._active:
                _telemetry.inc("kvstore.collective_errors_total",
                               op="allreduce")
            raise
        self._count_collective("allreduce", t0, merged)
        return out

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            merged = self._merged(k, vs)
            if self._updater is not None:
                self._updater(self._key_int(k), merged, self._store[k])
            else:
                self._store[k]._rebind(merged._data.astype(self._store[k].dtype))

    def pushpull(self, key, value, out=None, priority=0):
        keys, values = self._normalize(key, value)
        merged_list = []
        for k, vs in zip(keys, values):
            merged = self._merged(k, vs)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                self._updater(self._key_int(k), merged, self._store[k])
                merged = self._store[k]
            merged_list.append(merged)
        if out is None:
            return
        _, outs = self._normalize(key, out)
        for merged, o in zip(merged_list, outs):
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._rebind(merged._data.astype(t.dtype))


class DistAsyncKVStore(DistKVStore):
    """'dist_async': per-process immediate updates, reconciling pulls.

    Reference: kvstore_dist_server.h async mode — the server applies each
    worker's gradient the moment it arrives; nothing waits for the other
    workers.  Here every process owns a store replica:

    - ``push`` runs the updater on the LOCAL replica with only the local
      gradient (no collective — workers make progress independently; this
      is where the semantics genuinely diverge from dist_sync);
    - ``pull``/``pushpull(out=...)`` reconcile: replicas are averaged
      across processes and the local replica adopts the average.  Until a
      worker pulls, it does not see other workers' updates (staleness).

    CAVEAT (differs from a true parameter server): reconciliation is an
    XLA collective, so every process must call ``pull`` for the same keys
    in the same order the same number of times — mismatched pull counts
    deadlock, exactly like any SPMD collective.  Asynchrony lives between
    pulls (pushes never synchronize), not in the pull schedule.  The
    reference's ZMQ server has no such constraint; workloads needing
    fully unscheduled pulls are out of scope for the collective backend.
    """

    def __init__(self, name="dist_async"):
        super().__init__(name)

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            merged = self._reduce(vs)  # local devices only; NO cross-process
            if self._gc is not None:
                merged = _wrap(self._gc.quantize(k, merged._data))
            if self._updater is not None:
                self._updater(self._key_int(k), merged, self._store[k])
            else:
                self._store[k]._rebind(
                    merged._data.astype(self._store[k].dtype))

    def _reconcile(self, k):
        """Average replicas across processes; adopt the average locally.

        Watchdog: the reconciling psum is an SPMD collective, so a
        mismatched pull schedule across processes HANGS inside XLA (the
        documented divergence from the reference's ZMQ server, which has
        no such constraint). The collective's completion wait runs on a
        helper thread with a deadline; on timeout this raises a diagnostic
        naming the key and this process's reconcile sequence number so the
        mismatched schedule is debuggable instead of a silent freeze.
        """
        if self._watchdog_engaged():
            self._reconcile_seq = getattr(self, "_reconcile_seq", 0) + 1

            def run():
                out = self._waited_allreduce(self._store[k])
                return getattr(out, "_data", out)

            t0 = time.perf_counter()
            try:
                summed = self._collective(
                    f"reconcile#{self._reconcile_seq}", k, run,
                    hint="Every process must pull the same keys in the "
                         "same order the same number of times (SPMD "
                         "collective constraint); a data-dependent pull "
                         "schedule deadlocks here — align the pull "
                         "schedule.")
            except Exception:
                if _telemetry._active:
                    _telemetry.inc("kvstore.collective_errors_total",
                                   op="reconcile")
                raise
            self._count_collective("reconcile", t0, summed)
            avg = summed / self._nprocs
            self._store[k]._rebind(avg.astype(self._store[k].dtype))
        return self._store[k]

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._reconcile(k)
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._rebind(src._data.astype(t.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

"""Distributed KVStore ('dist_sync'/'dist_device_sync'/'dist_async').

Reference parity: src/kvstore/kvstore_dist.h + kvstore_dist_server.h (ps-lite
worker/server/scheduler, ZPush/ZPull key slicing, sync/async modes) and
python/mxnet/kvstore/kvstore_server.py.

TPU-native design: there is no parameter server. Cross-host reduction is an
XLA AllReduce over the DCN mesh axis; rendezvous is jax.distributed
(PJRT coordination service replaces the ps-lite scheduler, SURVEY §5).
Workers call pushpull -> psum over all processes. 'dist_async' has no XLA
analog and is executed as sync (documented divergence; the reference itself
only guarantees eventual consistency there). Optimizer-on-server
(update_on_kvstore) runs the updater identically on every worker after the
reduce — bitwise-identical state without a server round-trip.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..base import MXNetError, get_env
from ..numpy.multiarray import ndarray, _wrap
from .kvstore import KVStore


def _ensure_distributed():
    """Initialize jax.distributed from MXNet-style or native env vars."""
    if jax.process_count() > 1:
        return
    coord = (os.environ.get("JAX_COORDINATOR_ADDRESS")
             or os.environ.get("DMLC_PS_ROOT_URI"))
    nproc = get_env("DMLC_NUM_WORKER", None, int) or get_env("JAX_NUM_PROCESSES", None, int)
    pid = get_env("DMLC_WORKER_ID", None, int) or get_env("JAX_PROCESS_ID", None, int)
    if coord and nproc and nproc > 1:
        port = os.environ.get("DMLC_PS_ROOT_PORT", "1234")
        addr = coord if ":" in coord else f"{coord}:{port}"
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=nproc, process_id=pid or 0)


class DistKVStore(KVStore):
    """Multi-host KVStore over XLA collectives."""

    def __init__(self, name="dist_sync"):
        super().__init__(name)
        _ensure_distributed()
        self._nprocs = jax.process_count()
        self._rank = jax.process_index()

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nprocs

    def _allreduce(self, merged):
        """Cross-process sum. Single process: identity. Multi-process: a
        tiny pjit'd psum over a global 1-d process mesh (DCN axis)."""
        if self._nprocs == 1:
            return merged
        from ..parallel.collectives import allreduce_across_processes
        return _wrap(allreduce_across_processes(merged._data))

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            merged = self._allreduce(self._reduce(vs))
            if self._updater is not None:
                self._updater(self._key_int(k), merged, self._store[k])
            else:
                self._store[k]._rebind(merged._data.astype(self._store[k].dtype))

    def pushpull(self, key, value, out=None, priority=0):
        keys, values = self._normalize(key, value)
        merged_list = []
        for k, vs in zip(keys, values):
            merged = self._allreduce(self._reduce(vs))
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                self._updater(self._key_int(k), merged, self._store[k])
                merged = self._store[k]
            merged_list.append(merged)
        if out is None:
            return
        _, outs = self._normalize(key, out)
        for merged, o in zip(merged_list, outs):
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._rebind(merged._data.astype(t.dtype))

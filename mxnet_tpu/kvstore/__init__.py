"""mx.kvstore (reference: python/mxnet/kvstore/__init__.py)."""
from .base import KVStoreBase, create  # noqa: F401
from .kvstore import KVStore  # noqa: F401
from .dist import DistAsyncKVStore, DistKVStore  # noqa: F401
from .horovod import Horovod, BytePS  # noqa: F401

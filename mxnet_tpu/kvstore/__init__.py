"""mx.kvstore (reference: python/mxnet/kvstore/__init__.py)."""
from .base import KVStoreBase, TestStore, create  # noqa: F401
from .kvstore import KVStore  # noqa: F401
from .dist import CollectiveTimeout, DistAsyncKVStore, DistKVStore  # noqa: F401
from .horovod import Horovod, BytePS  # noqa: F401
from .kvstore_server import KVStoreServer, init_server_module  # noqa: F401

# a process launched with DMLC_ROLE=server must fail fast at import with
# the architectural pointer (reference runs _init_kvstore_server_module
# at import the same way), not hang as a mislabelled worker
init_server_module()

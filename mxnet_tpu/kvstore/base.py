"""KVStore base + plugin registry.

Reference parity: python/mxnet/kvstore/base.py (KVStoreBase.register at :74,
create at :432 — local/device/nccl/dist_sync/dist_device_sync/dist_async/
horovod/byteps).

TPU-native design: all backends resolve to XLA collectives. 'local'/'device'/
'nccl' are the single-process store (reduction on device; the ICI analog of
CommDevice/NCCL); 'dist_*' layer the same interface over a multi-host mesh
(DCN axis) via jax.distributed + psum — see kvstore.py and
mxnet_tpu.parallel.collectives.
"""
from __future__ import annotations

from ..base import MXNetError


class KVStoreBase:
    """Plugin interface (reference: kvstore/base.py:74-230)."""

    kv_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    # interface
    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    @staticmethod
    def is_capable(capability):
        raise NotImplementedError

    @property
    def type(self):
        raise NotImplementedError

    @property
    def local_rank(self):
        return 0

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError

    OPTIMIZER = "optimizer"


def create(name="local"):
    """Factory (reference: kvstore/base.py:432 create)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    name = name.lower()
    from .kvstore import KVStore
    from .horovod import Horovod  # noqa: F401 (registers)
    if name in ("local", "device", "nccl", "local_allreduce_device",
                "local_allreduce_cpu"):
        return KVStore(name)
    if name.startswith("dist"):
        from .dist import DistAsyncKVStore, DistKVStore
        if "async" in name:
            return DistAsyncKVStore(name)
        return DistKVStore(name)
    if name in KVStoreBase.kv_registry:
        return KVStoreBase.kv_registry[name]()
    raise MXNetError(f"unknown KVStore type {name!r}")


@KVStoreBase.register
class TestStore(KVStoreBase):
    """In-memory single-process store exercising the plugin interface
    (reference base.py:246 — registered as 'teststore' so KVStoreBase
    plugin tests have a trivial backend)."""

    def broadcast(self, key, value, out, priority=0):  # noqa: ARG002
        outs = out if isinstance(out, list) else [out]
        for o in outs:
            o[:] = value

    def pushpull(self, key, value, out=None, priority=0):  # noqa: ARG002
        from ..numpy.multiarray import ndarray
        if isinstance(value, ndarray):
            if out is not None:
                for o in (out if isinstance(out, list) else [out]):
                    o[:] = value
            return
        reduced = value[0]
        for v in value[1:]:
            reduced = reduced + v
        targets = value if out is None else (
            out if isinstance(out, list) else [out])
        for t in targets:
            t[:] = reduced

    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER,)

    @property
    def type(self):
        return "teststore"

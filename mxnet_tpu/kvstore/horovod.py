"""Horovod/BytePS-style plugin backends.

Reference parity: python/mxnet/kvstore/horovod.py:27-132 and byteps.py:29 —
MPI-launched allreduce plugins registered through KVStoreBase.register.

TPU-native: collectives are native (XLA), so these plugins delegate to the
same mesh-psum path; they exist to honor kv.create('horovod') call sites.
"""
from __future__ import annotations

from .base import KVStoreBase
from .kvstore import KVStore


@KVStoreBase.register
class Horovod(KVStore):
    def __init__(self):
        super().__init__("horovod")

    def broadcast_parameters(self, params, root_rank=0):
        for k, v in params.items():
            self.init(k, v)


@KVStoreBase.register
class BytePS(KVStore):
    def __init__(self):
        super().__init__("byteps")

"""Single-process KVStore ('local'/'device'/'nccl').

Reference parity: python/mxnet/kvstore/kvstore.py over src/kvstore/
kvstore_local.h (GroupKVPairs push/pull grouping, merge buffers,
CommCPU/CommDevice reduce at src/kvstore/comm.h:104,474) and kvstore_nccl.h.

TPU-native design: values live as jax Arrays (possibly sharded over the local
mesh). 'Reduce' is a jnp tree-sum — when the per-device values are shards of
a mesh-sharded array, XLA emits the ICI all-reduce; there is no host staging,
which is what CommDevice's P2P ring approximates on GPU. Per-key updaters
(optimizer-on-kvstore) match the reference's semantics.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..numpy.multiarray import ndarray, _wrap
from .base import KVStoreBase


class KVStore(KVStoreBase):
    """In-process key-value store with device reduction."""

    def __init__(self, name="device"):
        self._type = name
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._updater_states = {}

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @staticmethod
    def is_capable(capability):
        return capability in ("optimizer",)

    # -- core ops ----------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v.copy() if isinstance(v, ndarray) else v

    def _normalize(self, key, value):
        if isinstance(key, (list, tuple)):
            return list(key), list(value)
        return [key], [value]

    @staticmethod
    def _one_device(v):
        ds = v._data.devices() if hasattr(v._data, "devices") else set()
        return next(iter(ds)) if len(ds) == 1 else None

    @staticmethod
    def _reduce_parts(vals):
        """Sum a list of per-device arrays (CommDevice::Reduce analog,
        src/kvstore/comm.h:474).

        When each value lives on a distinct device, the sum is ONE XLA
        all-reduce over a mesh of those devices (psum rides ICI on real
        chips), and the result list keeps one reduced copy resident on each
        contributing device — the CommDevice reduce+broadcast without host
        staging. Otherwise falls back to a tree-sum on the common device.
        Returns a list aligned with ``vals``.
        """
        if len(vals) == 1:
            return [vals[0]]
        devs = []
        for v in vals:
            d = KVStore._one_device(v)
            if d is None or d in devs or v.shape != vals[0].shape:
                devs = None
                break
            devs.append(d)
        if devs is None:
            acc = vals[0]._data
            for v in vals[1:]:
                acc = acc + v._data
            merged = _wrap(acc)
            return [merged] * len(vals)

        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        import functools

        from .._jax_compat import shard_map

        n, shape = len(vals), tuple(vals[0].shape)
        mesh = Mesh(onp.array(devs), ("kv",))
        glob = jax.make_array_from_single_device_arrays(
            (n,) + shape, NamedSharding(mesh, P("kv")),
            [v._data[None] for v in vals])

        @functools.partial(shard_map, mesh=mesh, in_specs=P("kv"),
                           out_specs=P("kv"))
        def _psum(x):
            return jax.lax.psum(x, "kv")

        out = _psum(glob)
        shards = sorted(out.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return [_wrap(s.data.reshape(shape)) for s in shards]

    @staticmethod
    def _reduce(vals):
        """Merged value of a push (single reduced copy). Row-sparse values
        reduce sparsely (reference: comm.h ReduceRowSparse)."""
        from ..ndarray.sparse import BaseSparseNDArray, add as _sp_add
        if isinstance(vals, (ndarray, BaseSparseNDArray)):
            return vals
        if any(isinstance(v, BaseSparseNDArray) for v in vals):
            merged = vals[0]
            for v in vals[1:]:
                merged = _sp_add(merged, v)
            return merged
        return KVStore._reduce_parts(vals)[0]

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            merged = self._reduce(vs)
            if self._updater is not None:
                self._updater(self._key_int(k), merged, self._store[k])
            else:
                from ..ndarray.sparse import BaseSparseNDArray
                if isinstance(merged, BaseSparseNDArray):
                    merged = merged.tostype("default")
                self._store[k]._rebind(merged._data.astype(self._store[k].dtype))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._rebind(src._data.astype(t.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: kvstore.h PushPull; the fast path
        Trainer uses when update_on_kvstore=False)."""
        keys, values = self._normalize(key, value)
        merged_list = []
        for k, vs in zip(keys, values):
            if isinstance(vs, ndarray):
                parts = [vs]
            else:
                parts = self._reduce_parts(vs)
            merged = parts[0]
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                self._updater(self._key_int(k), merged, self._store[k])
                merged, parts = self._store[k], None
            merged_list.append((merged, parts))
        if out is None:
            return
        _, outs = self._normalize(key, out)
        for (merged, parts), o in zip(merged_list, outs):
            targets = o if isinstance(o, (list, tuple)) else [o]
            if parts is not None and len(targets) == len(parts):
                # per-device reduced copies: each target keeps its placement
                for t, part in zip(targets, parts):
                    t._rebind(part._data.astype(t.dtype))
            else:
                for t in targets:
                    t._rebind(merged._data.astype(t.dtype))

    def broadcast(self, key, value, out, priority=0):
        """init + pull (reference: kvstore/base.py broadcast)."""
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` (reference: kvstore.h
        PullRowSparse / python kvstore.py row_sparse_pull). Returns (and
        writes into row_sparse ``out`` targets) a RowSparseNDArray holding
        just those rows — the distributed-embedding fast path."""
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        from ..ndarray.sparse import RowSparseNDArray
        keys, _ = self._normalize(key, None)
        rids = (row_ids if isinstance(row_ids, (list, tuple))
                else [row_ids] * len(keys))
        results = []
        for k, rid in zip(keys, rids):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            from ..ndarray.sparse import _IDX
            ids = jnp.unique(rid._data if isinstance(rid, ndarray)
                             else jnp.asarray(rid)).astype(_IDX)
            vals = src._data[ids]
            results.append(RowSparseNDArray(_wrap(vals), _wrap(ids),
                                            src.shape))
        if out is not None:
            _, outs = self._normalize(key, out)
            for rsp, o in zip(results, outs):
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    if isinstance(t, RowSparseNDArray):
                        t.data = rsp.data
                        t.indices = rsp.indices
                        t.shape = rsp.shape
                    else:  # dense target: retained rows, zeros elsewhere
                        t._rebind(rsp.tostype("default")._data.astype(t.dtype))
        return results[0] if not isinstance(key, (list, tuple)) else results

    # -- updater / optimizer ----------------------------------------------
    @staticmethod
    def _key_int(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    def _set_updater(self, updater):
        self._updater = updater

    set_updater = _set_updater

    def set_optimizer(self, optimizer):
        from ..optimizer import get_updater
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        from .. import serialization
        serialization.atomic_write_bytes(
            fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def set_gradient_compression(self, compression_params):
        """Reference: kvstore.h SetGradientCompression. As in the reference,
        compression only applies to the cross-process push path — a dist
        kvstore (see dist.py); single-process stores reject it."""
        raise MXNetError(
            "gradient compression requires a dist kvstore "
            "(reference: src/kvstore/kvstore_dist.h only)")

"""mx.telemetry — framework-wide always-on metrics + training run reports.

Reference parity: the reference's engine-integrated profiler
(src/profiler/profiler.h) answers "where did the time go?" per op; it has
no always-on layer answering "why is this RUN slow or flaky?".  On a
compiler-backed TPU stack the dominant production pathologies are
invisible to a span profiler: XLA recompilation storms from
shape-polymorphic hybridized blocks, dataloader stalls, collective
latency, and steps silently skipped by the resilience layer
(docs/FAULT_TOLERANCE.md).  This module is the metrics plane for those:

- **Registry**: process-wide counters, gauges and bucketed histograms,
  lock-protected, optionally labelled (low-cardinality labels only —
  block names, collective ops, fault event names).
- **Near-zero disabled cost**: mirroring ``fault.py``, every
  instrumentation site in the stack gates on one module-attribute read
  (``_active``); with telemetry off (the default) a hook is a single
  ``if`` on a False attribute.  The CI ``telemetry`` stage enforces the
  <2% overhead budget on a tight eager-op loop
  (benchmark/telemetry_overhead.py).
- **Wired subsystems**: cached-graph compile/cache-hit accounting +
  recompilation detector (gluon/block.py), dataloader batch wait / queue
  depth / respawns (gluon/data/dataloader.py), trainer step time /
  grad-norm / non-finite skips (gluon/trainer.py), per-collective latency
  and payload bytes (kvstore/dist.py), and every ``mx.fault`` event
  (injections and recoveries mirror into ``fault.events_total``).
- **Recompilation detector**: one hybridized block re-tracing more than
  ``telemetry.recompile_limit`` times is the classic TPU
  shape-polymorphism pitfall (a new XLA compile per input signature); the
  detector emits one structured :class:`RecompileWarning` per block,
  carrying the block name and compile count.
- **Reporters**: ``exposition()`` renders a Prometheus-style text dump;
  :class:`TrainingTelemetry` emits periodic JSONL step records and a
  final structured run report, and bridges emitted records into
  ``mx.profiler`` events when the profiler runs.  ``profiler.set_state
  ("run")`` auto-enables telemetry, so one switch captures everything.

Enable via ``mx.telemetry.enable()`` or the ``MXNET_TELEMETRY`` env alias
of the ``telemetry.enable`` config knob (read at import, like
``MXNET_FAULT_SPEC``).
"""
from __future__ import annotations

import bisect
import contextlib
import json
import os
import re
import threading
import time

from . import config as _config
from .base import MXNetError

__all__ = ["enable", "disable", "configure", "active", "inc", "set_gauge",
           "observe", "timed", "declare_metric", "note_compile", "counters",
           "summary_line", "snapshot", "exposition", "serve_http",
           "stop_http", "reset", "RecompileWarning", "TrainingTelemetry",
           "CATALOG", "EXPOSITION_CONTENT_TYPE", "register_health",
           "unregister_health", "health", "note_event", "events"]

_lock = threading.Lock()
#: hot-path gate — instrumentation sites read this one attribute; False
#: keeps every hook a single no-op branch (same design as fault._active)
_active = False

_counters: dict[tuple[str, tuple], float] = {}
_gauges: dict[tuple[str, tuple], float] = {}
_hists: dict[tuple[str, tuple], "_Hist"] = {}

# -- metric catalog ---------------------------------------------------------

#: seconds-scale latencies (compile, step, batch wait, collectives)
TIME_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                float("inf"))
#: wide-dynamic-range magnitudes (gradient norms)
MAGNITUDE_BUCKETS = tuple(10.0 ** e for e in range(-4, 7)) + (float("inf"),)

_Kind = str  # "counter" | "gauge" | "histogram"
CATALOG: dict[str, tuple[_Kind, str, tuple | None]] = {}


def declare_metric(name, kind, doc, buckets=None):
    """Register a metric in the catalog (drives exposition() HELP/TYPE
    lines and docs/OBSERVABILITY.md's table).  Undeclared names are
    auto-registered on first use with a generic doc."""
    if kind not in ("counter", "gauge", "histogram"):
        raise MXNetError(f"unknown metric kind {kind!r}")
    with _lock:
        CATALOG.setdefault(name, (kind, doc,
                                  tuple(buckets) if buckets else None))
    return name


declare_metric("invoke.ops_total", "counter",
               "eager ops dispatched through _invoke")
declare_metric("cached_graph.compile_total", "counter",
               "XLA trace+compiles of hybridized blocks, by block class")
declare_metric("cached_graph.compile_seconds", "histogram",
               "wall time of one hybridized trace+compile",
               buckets=TIME_BUCKETS)
declare_metric("cached_graph.cache_hit_total", "counter",
               "compiled-forward replays served from the signature cache")
declare_metric("cached_graph.cache_miss_total", "counter",
               "calls whose signature required a fresh trace")
declare_metric("cached_graph.signatures", "gauge",
               "live signatures in a block's executable cache")
declare_metric("cached_graph.recompile_warnings_total", "counter",
               "blocks flagged by the recompilation detector")
declare_metric("dataloader.wait_seconds", "histogram",
               "time the training loop blocked waiting for the next batch",
               buckets=TIME_BUCKETS)
declare_metric("dataloader.queue_depth", "gauge",
               "in-flight prefetch tasks when the loop asked for a batch")
declare_metric("dataloader.batches_total", "counter",
               "batches produced by worker-backed loaders")
declare_metric("dataloader.respawn_total", "counter",
               "worker-pool respawns after a crash or missed heartbeat")
declare_metric("dataloader.shm_created_total", "counter",
               "SharedMemory segments created by process workers")
declare_metric("dataloader.shm_reused_total", "counter",
               "batch leaves served from the shm reuse pool instead of a "
               "fresh segment")
declare_metric("trainer.step_seconds", "histogram",
               "wall time of Trainer.step (allreduce + update)",
               buckets=TIME_BUCKETS)
declare_metric("trainer.steps_total", "counter",
               "optimizer steps applied")
declare_metric("trainer.grad_norm", "histogram",
               "global gradient L2 norm per step (finite steps only)",
               buckets=MAGNITUDE_BUCKETS)
declare_metric("trainer.nonfinite_total", "counter",
               "steps skipped by the non-finite gradient guard")
declare_metric("kvstore.collective_seconds", "histogram",
               "latency of one cross-process collective, by op",
               buckets=TIME_BUCKETS)
declare_metric("kvstore.collective_total", "counter",
               "cross-process collectives issued, by op")
declare_metric("kvstore.payload_bytes_total", "counter",
               "bytes moved through cross-process collectives, by op")
declare_metric("kvstore.collective_errors_total", "counter",
               "cross-process collectives that failed (timeout or fabric "
               "error), by op — disjoint from collective_total, which "
               "counts successes only")
declare_metric("resilience.collective_retry_total", "counter",
               "collective attempts retried after a transient failure, "
               "by op")
declare_metric("resilience.rejoin_total", "counter",
               "successful pre-retry coordination-service re-barriers")
declare_metric("resilience.rejoin_failed_total", "counter",
               "best-effort re-barriers that timed out (peer gone or "
               "still inside the collective)")
declare_metric("resilience.worker_lost_raised_total", "counter",
               "collective retry budgets exhausted -> WorkerLost raised")
declare_metric("resilience.bundle_save_total", "counter",
               "TrainState bundles written")
declare_metric("resilience.bundle_restore_total", "counter",
               "TrainState bundles restored")
declare_metric("resilience.preempt_signal_total", "counter",
               "preemption signals observed, by signal")
declare_metric("resilience.restart_total", "counter",
               "supervised train-fn restarts after WorkerLost")
declare_metric("resilience.restart_budget_reset_total", "counter",
               "restart budgets reset after a healthy-progress window "
               "(resilience.restart_window_steps) between WorkerLost "
               "events")
declare_metric("resilience.bundle_gc_total", "counter",
               "TrainState bundle generations deleted by retention GC "
               "(torn, or older than resilience.keep_bundles)")
declare_metric("fault.events_total", "counter",
               "mx.fault injections and recovery events, by event")
declare_metric("train.iter_seconds", "histogram",
               "full training-loop iteration time (TrainingTelemetry.step)",
               buckets=TIME_BUCKETS)
declare_metric("telemetry.records_total", "counter",
               "JSONL records emitted by TrainingTelemetry")
declare_metric("telemetry.events_total", "counter",
               "python warnings and framework log records captured into "
               "the bounded telemetry event ring, by kind")
declare_metric("telemetry.report_rotations_total", "counter",
               "TrainingTelemetry JSONL files rolled to a .gNNNN "
               "generation by the telemetry.report_max_bytes cap")
declare_metric("memory.bytes_in_use", "gauge",
               "per-device live HBM bytes (PJRT memory_stats), by device")
declare_metric("memory.peak_bytes_in_use", "gauge",
               "per-device peak HBM bytes since start, by device")
declare_metric("memory.bytes_limit", "gauge",
               "per-device HBM capacity reported by the runtime, by device")
declare_metric("autotune.candidates_total", "counter",
               "config-search grid points considered by mx.autotune")
declare_metric("autotune.pruned_total", "counter",
               "candidates the analytic cost model rejected without a "
               "compile, by reason (dominated/hbm/invalid/vmem/"
               "ranked_out)")
declare_metric("autotune.trials_total", "counter",
               "measured autotune trials executed (compile + short "
               "timed window), including failed ones")
declare_metric("autotune.trials_oom_total", "counter",
               "autotune trials that died of device OOM (recorded, "
               "search continues)")
declare_metric("autotune.trials_parity_total", "counter",
               "fp8 autotune trials rejected by the loss-parity probe "
               "(relative delta vs the fp32 reference beyond "
               "autotune.fp8_parity_tol; search continues)")
declare_metric("autotune.search_seconds", "histogram",
               "wall time of one full autotune search",
               buckets=TIME_BUCKETS)
declare_metric("autotune.best_speedup", "gauge",
               "measured items/s of the autotune winner over the "
               "untuned default config")
declare_metric("telemetry.scrape_duration_seconds", "gauge",
               "wall time the ops endpoint spent rendering the last "
               "/metrics exposition")
declare_metric("autotune.cache_hits_total", "counter",
               "searches answered from the persisted winners file "
               "(fingerprint match, zero trials re-run)")
declare_metric("autotune.kernel_trials_total", "counter",
               "measured kernel-level block-shape trials executed by "
               "mx.autotune.kernels (including failed ones)")
declare_metric("autotune.kernel_cache_hits_total", "counter",
               "kernel block-shape searches answered from the persisted "
               "winners file (bucket match, zero trials re-run)")
declare_metric("autotune.retunes_total", "counter",
               "drift-triggered kernel re-tunes applied at a checkpoint "
               "boundary (Retuner hot-swaps)")
declare_metric("autotune.learned_rank_corr", "gauge",
               "Spearman rank correlation of the learned kernel cost "
               "model against measured trials at the last rank gate")


# -- switches ---------------------------------------------------------------

def enable(on=True):
    """Turn the registry on/off.  Off (the default) every instrumentation
    hook in the stack is one module-attribute read.  Enabling also arms
    the pipeline sync-site counter so ``snapshot()["sync_sites"]`` and
    ``pipeline.host_syncs_total`` report where host syncs happen."""
    global _active
    _active = bool(on)
    from . import pipeline as _pipeline   # lazy: pipeline imports us
    _pipeline.arm_site_counts("telemetry", _active)
    return _active


def disable():
    enable(False)


def configure():
    """Re-read the ``telemetry.enable`` config knob / ``MXNET_TELEMETRY``
    env alias."""
    return enable(_config.get("telemetry.enable"))


def active():
    return _active


# -- recording --------------------------------------------------------------

def _labels_key(labels):
    return tuple(sorted(labels.items()))


def _auto_register(name, kind):
    existing = CATALOG.get(name)
    if existing is None:
        CATALOG[name] = (kind, "(auto-registered)", None)
    elif existing[0] != kind:
        raise MXNetError(
            f"metric {name!r} is a {existing[0]}, not a {kind}")
    return CATALOG[name]


def inc(name, n=1, **labels):
    """Add ``n`` to a counter (no-op while disabled)."""
    if not _active:
        return
    key = (name, _labels_key(labels))
    with _lock:
        _auto_register(name, "counter")
        _counters[key] = _counters.get(key, 0) + n


def set_gauge(name, value, **labels):
    """Set a gauge to ``value`` (no-op while disabled)."""
    if not _active:
        return
    key = (name, _labels_key(labels))
    with _lock:
        _auto_register(name, "gauge")
        _gauges[key] = value


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.sum += value
        self.count += 1


#: raw-sample listeners (mx.insight's drift feed, mx.goodput's ledger
#: feed): histogram name -> {tag: callable}, each callable receiving
#: every observed value.  Consulted only while the registry is enabled,
#: after the bucket update and OUTSIDE _lock, so a listener may record
#: metrics of its own.
_sample_listeners: dict[str, dict] = {}


def add_sample_listener(name, fn, tag="default"):
    """Register ``fn(value)`` to receive every raw :func:`observe`
    sample for histogram ``name``.  Listeners are keyed by ``tag`` so
    independent planes (insight's drift detector, goodput's ledger)
    coexist on one histogram; re-registering a tag replaces it."""
    _sample_listeners.setdefault(name, {})[tag] = fn


def remove_sample_listener(name, tag="default"):
    fns = _sample_listeners.get(name)
    if fns is not None:
        fns.pop(tag, None)
        if not fns:
            _sample_listeners.pop(name, None)


def observe(name, value, **labels):
    """Record one sample into a bucketed histogram (no-op while
    disabled).  Buckets come from the catalog declaration; undeclared
    histograms get TIME_BUCKETS."""
    if not _active:
        return
    key = (name, _labels_key(labels))
    with _lock:
        spec = _auto_register(name, "histogram")
        h = _hists.get(key)
        if h is None:
            h = _hists[key] = _Hist(spec[2] or TIME_BUCKETS)
        h.observe(value)
    fns = _sample_listeners.get(name)
    if fns is not None:
        for fn in tuple(fns.values()):
            fn(value)


@contextlib.contextmanager
def timed(name, **labels):
    """Context manager observing its wall time into histogram ``name``;
    free when disabled."""
    if not _active:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        observe(name, time.perf_counter() - t0, **labels)


def record_memory(devices=None):
    """Refresh the ``memory.*`` gauges from PJRT ``device.memory_stats()``
    and return ``{device_id: {live, peak, limit}}`` (bytes; keys present
    only when the backend reports them).

    Called at the step loop's drain points (``Trainer.drain_telemetry``,
    ``TrainingTelemetry`` run reports) so live/peak HBM is observable
    without per-step host syncs.  Backends without memory stats (CPU)
    yield an empty dict — a cheap no-op, so callers don't need to gate on
    platform.  No-op while the registry is disabled.
    """
    if not _active:
        return {}
    if devices is None:
        import jax
        devices = jax.local_devices()
    out = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        dev = str(getattr(d, "id", d))
        entry = {}
        live = stats.get("bytes_in_use")
        if live is not None:
            set_gauge("memory.bytes_in_use", int(live), device=dev)
            entry["live"] = int(live)
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            set_gauge("memory.peak_bytes_in_use", int(peak), device=dev)
            entry["peak"] = int(peak)
        limit = stats.get("bytes_limit")
        if limit is not None:
            set_gauge("memory.bytes_limit", int(limit), device=dev)
            entry["limit"] = int(limit)
        if entry:
            out[dev] = entry
    return out


def reset():
    """Drop every recorded value (the catalog and enabled state stay)."""
    global _events
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
    with _events_lock:
        _events = None
    from . import pipeline as _pipeline   # lazy: pipeline imports us
    _pipeline.reset_site_counts()


# -- bounded event ring -----------------------------------------------------

#: bounded ring of structured events — python warnings (RecompileWarning
#: et al.) and framework log records >= WARNING — fed by the capture
#: hooks mx.blackbox installs; postmortem bundles embed it so a crash
#: carries the warnings that preceded it, not just metric totals.
_events = None
_events_lock = threading.Lock()


def note_event(kind, message, **fields):
    """Append one structured event to the bounded ring (capacity from
    the ``telemetry.event_ring`` knob; oldest dropped first).  Unlike the
    metric recorders this does not gate on ``_active`` — the installers
    (mx.blackbox's warning/log capture hooks) are the gate, so an armed
    recorder never loses the event that explains a crash."""
    global _events
    import collections
    entry = {"kind": kind, "message": str(message)[:2048],
             "time": time.time(), **fields}
    with _events_lock:
        if _events is None:
            _events = collections.deque(
                maxlen=max(1, int(_config.get("telemetry.event_ring"))))
        _events.append(entry)
    inc("telemetry.events_total", kind=kind)
    return entry


def events(last=None):
    """Captured ring events, oldest first (``last`` = newest N only)."""
    with _events_lock:
        out = list(_events) if _events is not None else []
    if last is not None:
        out = out[-int(last):]
    return out


# -- recompilation detector -------------------------------------------------

class RecompileWarning(UserWarning):
    """One hybridized block keeps re-tracing: the TPU shape-polymorphism
    pitfall (every new input shape/dtype signature costs a full XLA
    compile).  Structured: ``block`` (class name), ``compiles`` (count so
    far), ``limit`` (the tripped threshold)."""

    def __init__(self, block, compiles, limit):
        self.block = block
        self.compiles = compiles
        self.limit = limit
        super().__init__(
            f"hybridized block {block!r} recompiled {compiles} times "
            f"(telemetry.recompile_limit={limit}): each distinct input "
            "shape/dtype signature triggers a fresh XLA trace+compile. "
            "Pad or bucket input shapes (drop_last/fixed seq-len), or "
            "raise the limit if the signature set is genuinely bounded.")


def note_compile(owner, label, seconds, signatures=None):
    """Account one XLA trace+compile of a hybridized block.

    ``owner`` is the Block instance — the per-block compile count and the
    warn-once latch live on it, so the detector fires exactly once per
    block no matter how many _CachedGraphs (train/eval) it owns.
    """
    if not _active:
        return
    inc("cached_graph.compile_total", block=label)
    observe("cached_graph.compile_seconds", seconds, block=label)
    if signatures is not None:
        set_gauge("cached_graph.signatures", signatures, block=label)
    limit = _config.get("telemetry.recompile_limit")
    with _lock:
        n = owner.__dict__.get("_telemetry_compiles", 0) + 1
        owner.__dict__["_telemetry_compiles"] = n
        fire = (n > limit
                and not owner.__dict__.get("_telemetry_recompile_warned"))
        if fire:
            owner.__dict__["_telemetry_recompile_warned"] = True
    if fire:
        inc("cached_graph.recompile_warnings_total")
        import warnings
        from . import log as _log
        w = RecompileWarning(label, n, limit)
        warnings.warn(w, stacklevel=2)
        _log.get_logger("mxnet_tpu.telemetry").warning("%s", w)


# -- readers ----------------------------------------------------------------

def _render(name, labels, extra=()):
    items = list(labels) + list(extra)
    if not items:
        return name
    return name + "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


def _le(bound):
    return "+Inf" if bound == float("inf") else repr(float(bound))


_QUANTILES = (0.5, 0.95, 0.99)


def _hist_quantiles(h, qs=_QUANTILES):
    """Estimate quantiles from bucket counts by linear interpolation
    inside the containing bucket (the Prometheus histogram_quantile
    rule): the first bucket interpolates from 0, and a quantile landing
    in the +Inf bucket degrades to the highest finite bound — an
    estimate, exact only at bucket edges, but monotone and cheap.
    Returns {q: value}; empty histograms return {}."""
    if h.count == 0:
        return {}
    out = {}
    finite_hi = 0.0
    for bound, c in zip(h.buckets, h.counts):
        if bound != float("inf") and c:
            finite_hi = bound
    for q in qs:
        target = q * h.count
        acc = 0
        lo = 0.0
        val = finite_hi
        for bound, c in zip(h.buckets, h.counts):
            if acc + c >= target and c:
                if bound == float("inf"):
                    val = lo if lo else finite_hi
                else:
                    val = lo + (bound - lo) * (target - acc) / c
                break
            acc += c
            if bound != float("inf"):
                lo = bound
        out[q] = val
    return out


def quantiles(name, qs=_QUANTILES, **labels):
    """Estimated quantiles of one recorded histogram as
    {"p50": v, "p95": v, ...} (None when nothing was recorded).  Serving
    SLOs (serve.ttft/tpot) and the latency histograms (dataloader.
    batch_wait, kvstore.*) read their percentiles through this."""
    key = (name, _labels_key(labels))
    with _lock:
        h = _hists.get(key)
        if h is None or h.count == 0:
            return None
        est = _hist_quantiles(h, qs)
    return {f"p{('%g' % (100 * q)).replace('.', '_')}": v
            for q, v in est.items()}


def counters(prefix=None, aggregate=False):
    """Flat dict of counters.  ``aggregate=True`` sums away labels (one
    value per metric name) — what LoggingHandler's epoch summary pulls."""
    out = {}
    with _lock:
        for (name, labels), v in _counters.items():
            if prefix and not name.startswith(prefix):
                continue
            if aggregate:
                out[name] = out.get(name, 0) + v
            else:
                out[_render(name, labels)] = v
    return dict(sorted(out.items()))


def summary_line():
    """One-line 'k=v k=v' digest of every counter (labels aggregated) for
    log lines; '' when nothing was recorded."""
    snap = counters(aggregate=True)
    if not snap:
        return ""
    return " ".join(f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
                    for k, v in snap.items())


def snapshot():
    """JSON-safe snapshot of every metric: counters/gauges as rendered
    name -> value, histograms as {buckets(le->cumulative), sum, count}."""
    with _lock:
        counter_snap = {_render(n, ls): v for (n, ls), v in _counters.items()}
        gauge_snap = {_render(n, ls): v for (n, ls), v in _gauges.items()}
        hist_snap = {}
        for (n, ls), h in _hists.items():
            cum, acc = {}, 0
            for bound, c in zip(h.buckets, h.counts):
                acc += c
                cum[_le(bound)] = acc
            hist_snap[_render(n, ls)] = {
                "buckets": cum, "sum": h.sum, "count": h.count,
                "quantiles": {("%g" % (100 * q)): v for q, v in
                              _hist_quantiles(h).items()}}
    from . import pipeline as _pipeline   # lazy: pipeline imports us
    return {"counters": dict(sorted(counter_snap.items())),
            "gauges": dict(sorted(gauge_snap.items())),
            "histograms": dict(sorted(hist_snap.items())),
            "sync_sites": _pipeline.sync_site_counts()}


def _sanitize(name):
    return "mxnet_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def exposition():
    """Prometheus-style text exposition of every recorded metric (HELP/
    TYPE from the catalog)."""
    with _lock:
        by_name: dict[str, list] = {}
        for (n, ls), v in _counters.items():
            by_name.setdefault(n, []).append((ls, v))
        for (n, ls), v in _gauges.items():
            by_name.setdefault(n, []).append((ls, v))
        for (n, ls), h in _hists.items():
            by_name.setdefault(n, []).append((ls, h))
        catalog = dict(CATALOG)
    lines = []
    order = [n for n in catalog if n in by_name] + \
        sorted(n for n in by_name if n not in catalog)
    for name in order:
        kind, doc, _ = catalog.get(name, ("counter", "(auto)", None))
        full = _sanitize(name)
        lines.append(f"# HELP {full} {doc}")
        lines.append(f"# TYPE {full} {kind}")
        for labels, v in sorted(by_name[name]):
            if isinstance(v, _Hist):
                acc = 0
                for bound, c in zip(v.buckets, v.counts):
                    acc += c
                    le = _render("", labels, (("le", _le(bound)),))
                    lines.append(f"{full}_bucket{le} {acc}")
                lines.append(f"{full}_sum{_render('', labels)} {v.sum:g}")
                lines.append(f"{full}_count{_render('', labels)} {v.count}")
                for q, qv in _hist_quantiles(v).items():
                    ql = _render("", labels, (("quantile", "%g" % q),))
                    lines.append(f"{full}{ql} {qv:g}")
            else:
                vv = f"{v:g}" if isinstance(v, float) else str(v)
                lines.append(f"{full}{_render('', labels)} {vv}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- stdlib ops endpoint ----------------------------------------------------

#: the Prometheus text-format content type scrapers key parsing on
EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_http_server = None

#: liveness providers consulted by /healthz: name -> zero-arg callable
#: returning a bool or a dict with an "ok" key. The fleet health plane
#: and the serve engine register here so the endpoint reflects step-loop
#: and lease liveness instead of a static OK.
_health_providers: dict[str, object] = {}


def register_health(name, provider):
    """Register a liveness check under ``name`` (replaces a previous
    one).  ``provider()`` -> bool or {"ok": bool, ...detail}; any check
    that is falsy (or raises) turns /healthz red (HTTP 503)."""
    with _lock:
        _health_providers[name] = provider
    return name


def unregister_health(name):
    with _lock:
        _health_providers.pop(name, None)


def health():
    """Aggregate every registered liveness check.  Returns
    ``(ok, checks)`` where checks is {name: {"ok": bool, ...}}."""
    with _lock:
        providers = dict(_health_providers)
    ok, checks = True, {}
    for name, fn in sorted(providers.items()):
        try:
            res = fn()
        except Exception as e:   # noqa: BLE001 - a dead check is a red check
            res = {"ok": False, "error": str(e)}
        if not isinstance(res, dict):
            res = {"ok": bool(res)}
        res.setdefault("ok", True)
        checks[name] = res
        ok = ok and bool(res["ok"])
    return ok, checks


def serve_http(port=None):
    """Start the in-process ops endpoint (stdlib ``http.server``, daemon
    thread) — the surface a fleet scrapes:

    - ``GET /metrics``  — :func:`exposition` with the proper
      ``Content-Type: text/plain; version=0.0.4`` header; each scrape
      sets the ``telemetry.scrape_duration_seconds`` gauge.
    - ``GET /healthz``  — liveness JSON (pid, telemetry/trace state).
    - ``GET /trace?last=N&category=C`` — the newest N ``mx.trace``
      spans as JSON, optionally filtered to one category.
    - ``GET /insight``  — the mx.insight attribution report (local +
      merged fleet view) as JSON.
    - ``GET /goodput``  — the mx.goodput ledger (local bucket waterfall
      + capacity-weighted fleet device-second merge) as JSON.
    - ``GET /servefleet`` — the mx.servefleet control-plane view (per-
      replica states, generations, ledger counters) as JSON.
    - ``GET /postmortem?last=N`` — metadata of the newest N mx.blackbox
      postmortem bundles in the resolved bundle directory.

    ``port=None`` reads the ``telemetry.http_port`` knob
    (``MXNET_TELEMETRY_PORT``); 0 binds an ephemeral port — read it back
    from ``server.server_address[1]``.  Idempotent: a running server is
    returned as-is; ``stop_http()`` shuts it down."""
    global _http_server
    if _http_server is not None:
        return _http_server
    import http.server
    import urllib.parse

    class _OpsHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *args):  # keep scrapes out of stderr
            pass

        def _send(self, code, body, ctype):
            data = body.encode("utf-8") if isinstance(body, str) else body
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 - http.server API
            url = urllib.parse.urlsplit(self.path)
            if url.path == "/metrics":
                t0 = time.perf_counter()
                exposition()
                set_gauge("telemetry.scrape_duration_seconds",
                          time.perf_counter() - t0)
                # render again so the gauge is visible in THIS scrape
                body = exposition()
                from . import insight as _insight
                if _insight._active:
                    try:
                        # host-labelled fleet series merged from the
                        # lease-dir snapshots (mx.insight fleet view)
                        body += _insight.fleet_exposition()
                    except Exception:   # noqa: BLE001
                        pass            # a torn snapshot can't 500 a scrape
                self._send(200, body, EXPOSITION_CONTENT_TYPE)
            elif url.path == "/healthz":
                from . import trace as _trace
                ok, checks = health()
                self._send(200 if ok else 503, json.dumps(
                    {"status": "ok" if ok else "unhealthy",
                     "pid": os.getpid(),
                     "telemetry_active": _active,
                     "trace": _trace.stats(),
                     "checks": checks}), "application/json")
            elif url.path == "/trace":
                from . import trace as _trace
                query = urllib.parse.parse_qs(url.query)
                last = None
                if "last" in query:
                    try:
                        last = int(query["last"][0])
                    except ValueError:
                        self._send(400, json.dumps(
                            {"error": "last must be an integer"}),
                            "application/json")
                        return
                category = query["category"][0] \
                    if "category" in query else None
                self._send(200, json.dumps(
                    {"spans": _trace.spans(last, category=category),
                     "dropped": _trace.stats()["dropped"]}),
                    "application/json")
            elif url.path == "/insight":
                from . import insight as _insight
                self._send(200, json.dumps(_insight.endpoint_report()),
                           "application/json")
            elif url.path == "/goodput":
                from . import goodput as _goodput
                self._send(200, json.dumps(_goodput.endpoint_report()),
                           "application/json")
            elif url.path == "/servefleet":
                from . import servefleet as _servefleet
                self._send(200,
                           json.dumps(_servefleet.endpoint_report()),
                           "application/json")
            elif url.path == "/postmortem":
                from . import blackbox as _blackbox
                query = urllib.parse.parse_qs(url.query)
                last = None
                if "last" in query:
                    try:
                        last = int(query["last"][0])
                    except ValueError:
                        self._send(400, json.dumps(
                            {"error": "last must be an integer"}),
                            "application/json")
                        return
                self._send(200, json.dumps(
                    _blackbox.endpoint_report(last)), "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": f"unknown path {url.path!r}",
                     "paths": ["/metrics", "/healthz", "/insight",
                               "/goodput", "/servefleet",
                               "/trace?last=N&category=C",
                               "/postmortem?last=N"]}),
                    "application/json")

    if port is None:
        port = int(_config.get("telemetry.http_port"))
    server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                             _OpsHandler)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever,
                     name="mx-telemetry-http", daemon=True).start()
    _http_server = server
    return server


def stop_http():
    """Shut the ops endpoint down (no-op when not running)."""
    global _http_server
    server, _http_server = _http_server, None
    if server is not None:
        server.shutdown()
        server.server_close()


# -- structured training run reports ---------------------------------------

def _analyze_summary():
    """The static-analysis plane for run reports, or None.

    In-process runs of the analyzer (mx.analyze.run_suite) win; otherwise
    a saved ``tools/mxlint.py --json`` document named by the
    ``analyze.report_path`` knob is folded in, so CI can attach the lint
    stage's findings to the training run report it gates.
    """
    from . import analyze as _analyze   # lazy: keeps import-time cost at 0
    plane = _analyze.last_summary()
    if plane is not None:
        return plane
    path = _config.get("analyze.report_path")
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.loads(f.read().strip().rsplit("\n", 1)[-1])
        return {"total": doc.get("total_new", 0),
                "rules": doc.get("rule_counts", {})}
    except (OSError, ValueError):
        return None


class TrainingTelemetry:
    """Structured training-run reporter over the registry.

    - ``step()`` once per training iteration: observes iteration time and
      every ``interval`` steps emits one JSONL record (cumulative counters
      + caller fields).  When ``mx.profiler`` is running each emitted
      record also lands as a profiler event, so one trace holds spans AND
      run metrics.
    - ``mark()`` emits an ad-hoc record (epoch boundaries etc.).
    - ``close()`` emits and returns the final run report: step count,
      wall time, and the full metric snapshot (histograms included) —
      the machine-readable answer to "what did this run do?".

    ``path=None`` keeps records in memory only (``.records``); a path
    appends JSONL lines (one json object per line; ``read()`` parses them
    back).  Constructing a reporter enables the registry; ``close()``
    restores the previous enabled state.
    """

    def __init__(self, path=None, interval=None, run_id=None):
        self._path = path if path is not None \
            else (_config.get("telemetry.jsonl") or None)
        self._interval = max(1, int(
            interval if interval is not None
            else _config.get("telemetry.step_interval")))
        self.run_id = run_id or f"run-{os.getpid()}"
        self.records = []
        self._file = None
        self._steps = 0
        self._t0 = time.time()
        self._last = time.perf_counter()
        self._closed = False
        self._was_active = _active
        enable()
        self._emit({"type": "run_begin", "run_id": self.run_id,
                    "time": self._t0, "pid": os.getpid()})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _emit(self, record):
        inc("telemetry.records_total")
        self.records.append(record)
        if self._path:
            line = json.dumps(record) + "\n"
            if self._file is None:
                self._file = open(self._path, "a")
            limit = int(_config.get("telemetry.report_max_bytes") or 0)
            if limit > 0 and self._file.tell() \
                    and self._file.tell() + len(line) > limit:
                self._rotate()
            self._file.write(line)
            self._file.flush()
        from . import profiler as _profiler
        if _profiler.is_running():
            _profiler.record_event(
                f"telemetry.{record['type']}", "telemetry",
                time.perf_counter_ns() // 1000, 0,
                {k: v for k, v in record.items()
                 if isinstance(v, (int, float, str))})

    def _rotate(self):
        """Roll the JSONL file to the next free ``<path>.gNNNN``
        generation and reopen fresh.  The size cap is checked before a
        record is written, so rotation never truncates mid-record, and
        rotated generations stay on disk — :meth:`generations` finds
        them (ROADMAP item 5 trains on these files)."""
        self._file.close()
        self._file = None
        n = 0
        while os.path.exists(f"{self._path}.g{n:04d}"):
            n += 1
        os.replace(self._path, f"{self._path}.g{n:04d}")
        inc("telemetry.report_rotations_total")
        self._file = open(self._path, "a")

    def step(self, step=None, **fields):
        """Record one training iteration; emit a JSONL step record every
        ``interval`` calls.  ``fields`` (loss, lr, ...) ride along."""
        self._steps += 1
        now = time.perf_counter()
        iter_s = now - self._last
        self._last = now
        observe("train.iter_seconds", iter_s)
        n = self._steps if step is None else step
        if self._steps % self._interval == 0:
            self._emit({"type": "step", "run_id": self.run_id, "step": n,
                        "time": time.time(), "iter_seconds": iter_s,
                        **fields, "counters": counters()})

    def mark(self, kind, **fields):
        """Emit an ad-hoc record (e.g. ``mark("epoch", epoch=3)``)."""
        self._emit({"type": kind, "run_id": self.run_id,
                    "time": time.time(), **fields})

    def report(self):
        """The final run report dict (also what ``close()`` emits)."""
        out = {"type": "run_report", "run_id": self.run_id,
               "steps": self._steps,
               "wall_seconds": time.time() - self._t0,
               "memory": record_memory(),
               "metrics": snapshot()}
        # lazy import: autotune imports telemetry at module load
        from . import autotune as _autotune
        tuned = _autotune.last_summary()
        if tuned is not None:
            out["autotune"] = tuned
        linted = _analyze_summary()
        if linted is not None:
            out["analyze"] = linted
        from . import insight as _insight
        observed = _insight.last_summary()
        if observed is not None:
            out["insight"] = observed
        from . import goodput as _goodput
        ledger = _goodput.last_summary()
        if ledger is not None:
            out["goodput"] = ledger
        return out

    def close(self):
        """Emit the run report, close the JSONL file, restore the
        registry's previous enabled state; returns the report."""
        if self._closed:
            return self._report
        self._report = self.report()
        self._emit(self._report)
        self._closed = True
        if self._file is not None:
            self._file.close()
            self._file = None
        enable(self._was_active)
        return self._report

    @staticmethod
    def read(path):
        """Parse a JSONL file written by a reporter -> list of records."""
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    @staticmethod
    def generations(path):
        """Every surviving generation of a rotated report, oldest first
        (``<path>.g0000``, ``<path>.g0001``, ..., then the live file).
        Rotation renames, never deletes — this is the discovery surface
        for consumers of the full run history."""
        import glob
        gens = sorted(glob.glob(glob.escape(path) + ".g[0-9]*"))
        if os.path.exists(path):
            gens.append(path)
        return gens


# arm from the environment at import (MXNET_TELEMETRY=1), mirroring
# fault.py, so spawned workers and plain scripts inherit the switch
if _config.get("telemetry.enable"):
    enable()

# MXNET_TELEMETRY_PORT=N arms the ops endpoint at import (best-effort:
# a taken port must not kill the training job it observes)
if _config.get("telemetry.http_port"):
    try:
        serve_http()
    except OSError:
        pass

"""mx.storage — host-memory pool (the storage-manager component).

Reference parity: src/storage/ (Storage::Alloc/Free/DirectFree,
PooledStorageManager with RoundPower2 bucketing selected by
MXNET_CPU_MEM_POOL_TYPE, stats via the storage profiler).  TPU-native
split of responsibilities: device (HBM) allocation belongs to PJRT/XLA —
there is nothing to manage there from python — while HOST staging memory
(batch assembly, IO readahead) benefits from exactly the reference's
pooled recycling.  The pool itself is native C++
(native/mxtpu_pool.cc), loaded on demand; when the toolchain is missing
everything degrades to plain numpy allocation.

    buf = mx.storage.alloc(nbytes)        # pooled aligned host block
    arr = mx.storage.pinned_array((64, 3, 224, 224), "float32")
    mx.storage.pool_stats()               # in_use/cached/hits/misses
    mx.storage.empty_cache()              # DirectFree analog
"""
from __future__ import annotations

import ctypes
import threading

import numpy as onp

from . import config
from .base import MXNetError

config.declare("storage.pool_type", str, "round_power2",
               "MXNET_CPU_MEM_POOL_TYPE",
               "Host staging pool strategy: 'naive' (pass-through) or "
               "'round_power2' (bucketed reuse; reference "
               "pooled_storage_manager.h).")

_lock = threading.Lock()
_pool = None
_lib = None


def _ensure_pool():
    global _pool, _lib
    with _lock:
        if _pool is not None:
            return _pool, _lib
        from . import native
        lib = native.load("mxtpu_pool")
        if lib is None:
            _pool, _lib = 0, None   # sentinel: fallback mode
            return _pool, _lib
        lib.mxtpu_pool_create.restype = ctypes.c_void_p
        lib.mxtpu_pool_create.argtypes = [ctypes.c_int]
        lib.mxtpu_pool_alloc.restype = ctypes.c_void_p
        lib.mxtpu_pool_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.mxtpu_pool_free.restype = ctypes.c_int
        lib.mxtpu_pool_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.mxtpu_pool_empty.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pool_stat.restype = ctypes.c_uint64
        lib.mxtpu_pool_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
        strategy = 0 if config.get("storage.pool_type") == "naive" else 1
        _pool = lib.mxtpu_pool_create(strategy)
        _lib = lib
        return _pool, _lib


class HostBuffer:
    """An aligned pooled host block (Storage::Handle analog)."""

    def __init__(self, ptr, nbytes, pool=None, lib=None):
        self.ptr = ptr
        self.nbytes = nbytes
        # pool/lib captured at alloc time: free() must never touch
        # _ensure_pool's lock (it can run from __del__ mid-allocation)
        self._pool = pool
        self._lib = lib
        self._freed = False

    def as_numpy(self, shape, dtype="uint8"):
        """View the block as a numpy array (no copy)."""
        dt = onp.dtype(dtype)
        count = int(onp.prod(shape)) if shape else 1
        if count * dt.itemsize > self.nbytes:
            raise MXNetError("view exceeds buffer size")
        buf = (ctypes.c_uint8 * self.nbytes).from_address(self.ptr)
        arr = onp.frombuffer(buf, dtype=dt, count=count).reshape(shape)
        arr.flags.writeable = True
        return arr

    def free(self):
        if self._freed:
            return
        if self._lib is not None:
            self._lib.mxtpu_pool_free(self._pool, ctypes.c_void_p(self.ptr))
        self._freed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.free()

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass


def alloc(nbytes):
    """Allocate a pooled host block (Storage::Alloc analog)."""
    if nbytes <= 0:
        raise MXNetError("alloc needs nbytes > 0")
    pool, lib = _ensure_pool()
    if lib is None:   # no toolchain: numpy-backed fallback
        arr = onp.empty(nbytes, onp.uint8)
        hb = HostBuffer(arr.ctypes.data, nbytes)
        hb._keepalive = arr   # the numpy array owns the memory
        hb._freed = True      # nothing to return to a pool
        return hb
    ptr = lib.mxtpu_pool_alloc(pool, nbytes)
    if not ptr:
        raise MemoryError(f"pool alloc of {nbytes} bytes failed")
    return HostBuffer(ptr, nbytes, pool=pool, lib=lib)


def pinned_array(shape, dtype="float32"):
    """numpy array backed by a pooled block; `.base_buffer` keeps it
    alive and returns it to the pool when the array is dropped."""
    dt = onp.dtype(dtype)
    nbytes = int(onp.prod(shape)) * dt.itemsize
    hb = alloc(max(nbytes, 1))
    return _PooledArray(hb.as_numpy(shape, dtype), hb)


class _PooledArray(onp.ndarray):
    """ndarray subclass that returns its block to the pool on collection."""

    def __new__(cls, arr, hb):
        obj = arr.view(cls)
        obj._hb = hb
        return obj

    def __array_finalize__(self, obj):
        if obj is not None:
            self._hb = getattr(obj, "_hb", None)


def pool_stats():
    pool, lib = _ensure_pool()
    if lib is None:
        return {"in_use": 0, "cached": 0, "hits": 0, "misses": 0,
                "native": False}
    return {"in_use": int(lib.mxtpu_pool_stat(pool, 0)),
            "cached": int(lib.mxtpu_pool_stat(pool, 1)),
            "hits": int(lib.mxtpu_pool_stat(pool, 2)),
            "misses": int(lib.mxtpu_pool_stat(pool, 3)),
            "native": True}


def empty_cache():
    """Release cached (free-listed) blocks back to the OS
    (Storage::DirectFree analog)."""
    pool, lib = _ensure_pool()
    if lib is not None:
        lib.mxtpu_pool_empty(pool)

"""Python side of the C ABI (native/mxtpu_capi.cc <-> this module).

Reference parity: the reference's C API (src/c_api/c_api.cc) fronts its
C++ engine; here the runtime IS Python/JAX, so the C library forwards
each ABI call to one of these small, primitive-typed functions. Keeping
the conversion logic in Python (bytes/tuples/ints only at the boundary)
keeps the C++ layer free of numpy/jax internals and the ABI stable.

dtype codes follow the reference's mshadow enum (base.py mirrors it):
0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64.
"""
from __future__ import annotations

import ast

import numpy as onp

_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
           4: "int32", 5: "int8", 6: "int64"}
_CODES = {v: k for k, v in _DTYPES.items()}


def _np():
    from . import numpy as np
    return np


def runtime_info():
    import jax
    devs = jax.devices()
    return f"platform={devs[0].platform};devices={len(devs)}"


def seed(n):
    from . import random
    random.seed(int(n))
    return True


def wait_all():
    from . import engine
    engine.wait_all()
    return True


def ndarray_from_bytes(payload, shape, dtype_code):
    """bytes (or None for zeros) + shape tuple + mshadow dtype code."""
    dt = _DTYPES[int(dtype_code)]
    if payload is None:
        return _np().zeros(tuple(shape), dtype=dt)
    host = onp.frombuffer(payload, dtype=dt).reshape(tuple(shape))
    return _np().array(host, dtype=dt)


def ndarray_shape(nd):
    return tuple(int(d) for d in nd.shape)


def ndarray_dtype_code(nd):
    return _CODES[str(nd.dtype)]


def ndarray_to_bytes(nd):
    return nd.asnumpy().tobytes()


def _parse_kwargs(kw):
    """ABI kwargs arrive as strings (reference C API convention); parse
    python literals where possible, pass raw strings through otherwise."""
    out = {}
    for k, v in kw.items():
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def _resolve(op_name):
    """npx -> mx.np -> legacy CamelCase, the same order python users see."""
    from . import ndarray as legacy_nd
    from . import numpy as np
    from . import numpy_extension as npx
    for mod in (npx, np):
        fn = getattr(mod, op_name, None)
        if callable(fn):
            return fn
    fn = getattr(legacy_nd, op_name, None)
    if callable(fn):
        return fn
    raise ValueError(f"unknown operator '{op_name}' "
                     "(searched npx, np, legacy nd)")


def invoke(op_name, inputs, kwargs):
    fn = _resolve(op_name)
    out = fn(*inputs, **_parse_kwargs(kwargs))
    if out is None:
        return []
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]

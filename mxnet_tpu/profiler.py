"""mx.profiler.

Reference parity: python/mxnet/profiler.py (:30-360 — set_config/set_state/
dump, Domain/Task/Counter/Marker/Frame objects) over src/profiler/profiler.h
(engine-integrated per-op spans, chrome://tracing JSON dump).

TPU-native design: two layers —
1. Device profiling: jax.profiler start/stop trace (Xprof/libtpu; the
   TensorBoard-compatible trace the TPU stack provides natively).
2. Host-side op spans: the eager dispatcher and cached-graph calls can be
   timed here; dump() writes chrome://tracing JSON like the reference.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

import jax

from .base import MXNetError

_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": False, "profile_api": False,
           "aggregate_stats": False}
_state = {"running": False, "device_trace_dir": None}
_events = []
_lock = threading.Lock()


def now_us():
    """Monotonic microseconds — THE clock of the host observability
    plane: profiler events, ``mx.trace`` spans and DataLoader-worker
    spans all stamp from here (CLOCK_MONOTONIC, system-wide on Linux),
    so aggregate tables and trace exports line up."""
    return time.perf_counter_ns() // 1000


def set_config(**kwargs):
    """Reference: profiler.py set_config (filename, profile_all, ...)."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """'run' | 'stop' (reference: profiler.py set_state).

    'run' also arms ``mx.telemetry`` when it is off, so one call captures
    host spans, the device trace AND the metrics registry; 'stop' disarms
    telemetry only if this bridge armed it (an explicit
    ``telemetry.enable()`` survives profiler stop/start cycles)."""
    from . import telemetry as _telemetry
    if state == "run":
        _state["running"] = True
        if not _telemetry.active():
            _telemetry.enable()
            _state["telemetry_autostart"] = True
        tracedir = _config.get("tensorboard_dir")
        if tracedir:
            jax.profiler.start_trace(tracedir)
            _state["device_trace_dir"] = tracedir
    elif state == "stop":
        if _state.get("device_trace_dir"):
            jax.profiler.stop_trace()
            _state["device_trace_dir"] = None
        _state["running"] = False
        if _state.pop("telemetry_autostart", False):
            _telemetry.disable()
    else:
        raise MXNetError(f"unknown profiler state {state!r}")


def is_running():
    return _state["running"]


def record_event(name, category, start_us, dur_us, args=None):
    """Internal hook used by dispatch layers and the Task/Counter/Marker/
    Event objects. Gated on the running state: instrumentation left in
    place while the profiler is stopped must not accumulate events
    (the reference's objects no-op the same way when unconfigured)."""
    if not _state["running"]:
        return
    enclosing = current_scope()
    if enclosing:
        args = dict(args or {})
        args.setdefault("scope", enclosing)
    with _lock:
        _events.append({"name": name, "cat": category, "ph": "X",
                        "ts": start_us, "dur": dur_us, "pid": os.getpid(),
                        "tid": threading.get_ident(), "args": args or {}})


class _Span:
    def __init__(self, name, category="op"):
        self.name, self.category = name, category

    def __enter__(self):
        self._t0 = now_us()
        self._jax = jax.profiler.TraceAnnotation(self.name)
        self._jax.__enter__()
        return self

    def __exit__(self, *exc):
        self._jax.__exit__(*exc)
        if _state["running"]:
            record_event(self.name, self.category, self._t0,
                         now_us() - self._t0)


def span(name, category="op"):
    return _Span(name, category)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (reference: profiler.py dump /
    Profiler::DumpProfile profiler.h:304)."""
    with _lock:
        events = list(_events)
        if finished:
            _events.clear()
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return _config["filename"]


#: dumps() sort keys -> aggregate-row field
_SORT_KEYS = {"total": "total_ms", "avg": "avg_ms", "max": "max_ms",
              "calls": "calls", "name": "name"}


def dumps(reset=False, format="table", sort_by="total", ascending=False):  # noqa: A002
    """Aggregate stats (reference: profiler.py dumps, which honored the
    same format/sort_by/ascending knobs).  ``format='table'`` renders the
    human-readable text; ``format='json'`` returns machine-readable
    aggregate rows (name/calls/total_ms/avg_ms/max_ms) so dashboards and
    tests stop re-parsing the table."""
    if sort_by not in _SORT_KEYS:
        raise MXNetError(f"dumps(sort_by={sort_by!r}): expected one of "
                         f"{sorted(_SORT_KEYS)}")
    if format not in ("table", "json"):
        raise MXNetError(f"dumps(format={format!r}): expected 'table' "
                         "or 'json'")
    with _lock:
        events = list(_events)
        if reset:
            _events.clear()
    agg = {}
    for e in events:
        a = agg.setdefault(e["name"], [0, 0.0, 0.0])
        a[0] += 1
        a[1] += e["dur"] / 1000.0
        a[2] = max(a[2], e["dur"] / 1000.0)
    rows = [{"name": name, "calls": calls,
             "total_ms": round(total, 6),
             "avg_ms": round(total / calls, 6) if calls else 0.0,
             "max_ms": round(mx, 6)}
            for name, (calls, total, mx) in agg.items()]
    rows.sort(key=lambda r: r[_SORT_KEYS[sort_by]], reverse=not ascending)
    if format == "json":
        return json.dumps({"aggregates": rows})
    lines = [f"{'Name':40s} {'Calls':>8s} {'Total(ms)':>12s} "
             f"{'Avg(ms)':>10s} {'Max(ms)':>10s}"]
    for r in rows:
        lines.append(f"{r['name']:40.40s} {r['calls']:8d} "
                     f"{r['total_ms']:12.3f} {r['avg_ms']:10.3f} "
                     f"{r['max_ms']:10.3f}")
    return "\n".join(lines)


def pause(profile_process="worker"):
    _state["running"] = False


def resume(profile_process="worker"):
    _state["running"] = True


# -- structured objects (reference: profiler.py Domain/Task/Counter/Marker) --

class Domain:
    def __init__(self, name):
        self.name = name


class Task:
    def __init__(self, domain, name):
        self.domain, self.name = domain, name
        self._t0 = None

    def start(self):
        self._t0 = now_us()

    def stop(self):
        if self._t0 is not None:
            record_event(self.name, f"task:{self.domain.name}",
                         self._t0, now_us() - self._t0)


Frame = Task


class Counter:
    def __init__(self, domain, name, value=0):
        self.domain, self.name, self.value = domain, name, value

    def set_value(self, value):
        self.value = value
        record_event(self.name, f"counter:{self.domain.name}",
                     now_us(), 0, {"value": value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


class Marker:
    def __init__(self, domain, name):
        self.domain, self.name = domain, name

    def mark(self, scope="process"):
        record_event(self.name, f"marker:{self.domain.name}",
                     now_us(), 0)


class Event:
    """Standalone timed event (reference profiler.py Event over
    ProfileEvent): start()/stop() records one span."""

    def __init__(self, name):
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = now_us()

    def stop(self):
        if self._t0 is not None:
            record_event(self.name, "event", self._t0,
                         now_us() - self._t0)
            self._t0 = None


_scope_tls = threading.local()


def current_scope():
    """Innermost active ``scope()`` name on this thread ('' outside any)."""
    stack = getattr(_scope_tls, "stack", None)
    return stack[-1] if stack else ""


@contextlib.contextmanager
def scope(name="<unk>:", append_mode=False):
    """Profiler scope naming everything recorded inside it (reference
    profiler.py scope — the GPU memory profiler used it to tag
    allocations).  Events recorded inside carry the scope in their args;
    ``append_mode=True`` nests under the enclosing scope
    (``outer:inner``) instead of replacing it, matching the reference's
    append semantics."""
    base = name.rstrip(":")
    stack = getattr(_scope_tls, "stack", None)
    if stack is None:
        stack = _scope_tls.stack = []
    if append_mode and stack:
        base = stack[-1] + ":" + base
    stack.append(base)
    try:
        with span(base, "scope"):
            yield
    finally:
        stack.pop()


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Deprecated 1.x spelling of set_config (reference profiler.py:73)."""
    import warnings
    warnings.warn("profiler.profiler_set_config() is deprecated; use "
                  "profiler.set_config()", DeprecationWarning, stacklevel=2)
    set_config(profile_symbolic=(mode in ("symbolic", "all")),
               profile_all=(mode == "all"), filename=filename)


def profiler_set_state(state="stop"):
    """Deprecated 1.x spelling of set_state (reference profiler.py:112)."""
    import warnings
    warnings.warn("profiler.profiler_set_state() is deprecated; use "
                  "profiler.set_state()", DeprecationWarning, stacklevel=2)
    set_state(state)


def dump_profile():
    """Deprecated spelling of dump (reference profiler.py:146)."""
    import warnings
    warnings.warn("profiler.dump_profile() is deprecated; use "
                  "profiler.dump()", DeprecationWarning, stacklevel=2)
    dump(True)

"""Shared machinery for the ``mx.analyze`` static-analysis suite.

Everything here is pure stdlib (``ast`` + ``re`` + ``json``) on purpose:
the linter must be runnable in the ``sanity`` tier of CI without paying a
jax import, and must never execute the code it inspects.

The pieces:

``Finding``
    one diagnostic: rule id, file:line, message, fix hint, and the
    stripped source line (``snippet``).  The baseline keys findings on
    ``(rule, path, snippet)`` rather than the line *number*, so unrelated
    edits that shift a file down do not invalidate the baseline.

``ModuleInfo``
    a parsed source file: AST, source lines, the import-alias map, the
    parent map (``ast`` has no uplinks), and the inline-waiver table.

``ImportMap``
    resolves names/attribute chains back to canonical dotted module
    paths (``jnp.asarray`` -> ``jax.numpy.asarray``,
    ``_config.get`` -> ``mxnet_tpu.config.get``) including relative
    imports (``from . import config as _config``).  Rules match against
    canonical paths so aliasing cannot hide a violation — and so a
    module-local dict that happens to be called ``_config`` (see
    ``profiler.py``) is *not* mistaken for the knob registry.

``run_suite``
    the driver: discover files, parse, run every rule module, apply
    inline waivers, and remember a rule->count summary for the
    telemetry ``analyze`` plane.
"""

import ast
import json
import os
import re
from dataclasses import dataclass, field

# rule modules are imported lazily in run_suite to avoid a cycle
# (trc/don/lck/reg each import core for Finding/helpers)

__all__ = [
    "Finding", "ModuleInfo", "ImportMap", "Context",
    "run_suite", "load_baseline", "write_baseline", "apply_baseline",
    "DEFAULT_ROOTS", "RULES",
]

# every rule id -> one-line description (drives --list-rules and docs)
RULES = {
    "TRC001": "host sync (asnumpy/.item()/np.asarray/float()) inside a "
              "traced scope",
    "TRC002": "impure call (time.*/random.*/np.random.*) inside a traced "
              "scope",
    "TRC003": "Python if/while branching on a traced value",
    "TRC004": "traced closure captures a step-varying Python scalar",
    "TRC005": "unconditional host sync in a per-batch hot path",
    "DON001": "buffer read after being donated through donate_argnums",
    "LCK001": "lock-acquisition cycle (potential deadlock)",
    "LCK002": "blocking call (queue get/put, join, sleep, collective) "
              "while holding a lock",
    "REG001": "config knob read that is not declared in config.py",
    "REG002": "declared config knob with no doc string",
    "REG003": "metric recorded without a declare_metric declaration",
    "REG004": "fault point not exercised by any test",
    "REG005": "fault fire/armed on an unknown point name",
    "REG006": "CI stage drift between ci/matrix.yaml and ci/run.sh",
    "REG007": "declared metric missing from docs/OBSERVABILITY.md",
    "REG008": "fault point missing from docs/FAULT_TOLERANCE.md",
    "WVR001": "inline waiver without a reason string",
}

# directories scanned when the CLI is given no paths
DEFAULT_ROOTS = ("mxnet_tpu", "tests", "benchmark", "tools", "example",
                 "bench.py")

_SKIP_DIRS = {"__pycache__", ".git", "build", "node_modules"}


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""
    snippet: str = ""    # stripped source line (baseline key component)
    col: int = 0

    def key(self):
        return (self.rule, self.path, self.snippet)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "hint": self.hint, "snippet": self.snippet}

    def render(self):
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class ImportMap:
    """Alias -> canonical dotted path, built from a module's imports."""

    def __init__(self, tree, package):
        # package: dotted package of the module itself ("" for scripts),
        # used to resolve relative imports
        self.map = {}
        self.package = package
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.map[a.asname] = a.name
                    else:
                        # "import jax.numpy" binds "jax"
                        head = a.name.split(".")[0]
                        self.map.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    self.map[bound] = (base + "." + a.name) if base \
                        else a.name

    def _from_base(self, node):
        if node.level == 0:
            return node.module or ""
        # relative: walk up from this module's package
        parts = self.package.split(".") if self.package else []
        up = node.level - 1
        if up > len(parts):
            return None
        base_parts = parts[:len(parts) - up] if up else parts
        if node.module:
            base_parts = base_parts + node.module.split(".")
        return ".".join(base_parts)

    def resolve(self, node):
        """Dotted canonical path for a Name/Attribute chain rooted at an
        import, or None (locals, self.*, un-imported names)."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.map.get(node.id)
        if head is None:
            return None
        parts.append(head)
        return ".".join(reversed(parts))


# --- inline waivers ------------------------------------------------------
# syntax:  # mxlint: disable=TRC001(reason),LCK002(another reason)
# a waiver with no reason does NOT suppress and raises WVR001 instead.

_WAIVER_RE = re.compile(r"#\s*mxlint:\s*disable=(.*)$")
_WAIVER_ITEM_RE = re.compile(r"([A-Z]{3}\d{3})(?:\(([^()]*)\))?")


def parse_waivers(lines):
    """-> {lineno: {rule: reason_or_None}}; a comment-only line applies
    to the next line as well (block style)."""
    waivers = {}
    for i, raw in enumerate(lines, start=1):
        m = _WAIVER_RE.search(raw)
        if not m:
            continue
        items = {}
        for rule, reason in _WAIVER_ITEM_RE.findall(m.group(1)):
            reason = reason.strip()
            items[rule] = reason or None
        if not items:
            continue
        waivers.setdefault(i, {}).update(items)
        if raw[:m.start()].strip() == "":
            # standalone comment line: waive the following line too
            waivers.setdefault(i + 1, {}).update(items)
    return waivers


class ModuleInfo:
    """One parsed python source file plus derived lookup tables."""

    def __init__(self, path, root):
        self.abspath = os.path.abspath(path)
        self.path = os.path.relpath(self.abspath, root).replace(os.sep, "/")
        with open(self.abspath, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)
        self.package = self._package_of(self.path)
        self.imports = ImportMap(self.tree, self.package)
        self.waivers = parse_waivers(self.lines)
        self.parents = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    @staticmethod
    def _package_of(relpath):
        parts = relpath.split("/")
        parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else \
            parts[-1]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        else:
            parts = parts[:-1]
        return ".".join(parts)

    def snippet(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule, node_or_line, message, hint=""):
        line = node_or_line if isinstance(node_or_line, int) \
            else getattr(node_or_line, "lineno", 1)
        col = 0 if isinstance(node_or_line, int) \
            else getattr(node_or_line, "col_offset", 0)
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, hint=hint,
                       snippet=self.snippet(line))

    def enclosing(self, node, kinds):
        """Nearest ancestor of the given AST node types, or None."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self.parents.get(cur)
        return None


@dataclass
class Context:
    """Cross-file state shared by the rule modules."""
    root: str
    modules: list
    # populated by reg.collect():
    knobs: dict = field(default_factory=dict)      # name -> (mod, line, doc)
    metrics: dict = field(default_factory=dict)    # name -> (mod, line)
    fault_points: dict = field(default_factory=dict)  # name -> (mod, line)
    test_strings: set = field(default_factory=set)

    def module(self, relpath):
        for m in self.modules:
            if m.path == relpath or m.path.endswith("/" + relpath):
                return m
        return None


def dotted_path(node):
    """'self._step' / 'ws' for a Name/Attribute chain, else None.
    Unlike ImportMap.resolve this keeps local roots — it names *objects*
    in the current scope, not imported modules."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def iter_files(paths, root):
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            if ap not in seen:
                seen.add(ap)
                yield ap
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        if fp not in seen:
                            seen.add(fp)
                            yield fp


def find_repo_root(start=None):
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(cur, ".git")) or \
                os.path.isfile(os.path.join(cur, "ci", "run.sh")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start or os.getcwd())
        cur = parent


# --- baseline ------------------------------------------------------------

def load_baseline(path):
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    counts = {}
    for e in doc.get("findings", []):
        k = (e["rule"], e["path"], e.get("snippet", ""))
        counts[k] = counts.get(k, 0) + 1
    return counts


def write_baseline(path, findings):
    doc = {"version": 1,
           "comment": "pre-existing mxlint findings waived for CI; "
                      "regenerate with tools/mxlint.py --write-baseline",
           "findings": [{"rule": f.rule, "path": f.path,
                         "snippet": f.snippet}
                        for f in sorted(findings,
                                        key=lambda f: (f.path, f.line,
                                                       f.rule))]}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")


def apply_baseline(findings, baseline_counts):
    """-> (new, waived): each baseline entry absorbs that many matching
    findings (earliest lines first)."""
    remaining = dict(baseline_counts)
    new, waived = [], []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            waived.append(f)
        else:
            new.append(f)
    return new, waived


# --- driver --------------------------------------------------------------

_last_summary = None


def last_summary():
    """Rule->count summary of the most recent run_suite() in this
    process (the telemetry ``analyze`` plane), or None."""
    return _last_summary


def _apply_waivers(findings, modules):
    by_path = {m.path: m for m in modules}
    kept = []
    for f in findings:
        m = by_path.get(f.path)
        if m is None:
            kept.append(f)
            continue
        w = m.waivers.get(f.line, {})
        if f.rule in w:
            if w[f.rule] is None:
                kept.append(m.finding(
                    "WVR001", f.line,
                    f"waiver for {f.rule} has no reason string",
                    hint="write # mxlint: disable="
                         f"{f.rule}(why this is safe)"))
            # waived with a reason: suppressed
        else:
            kept.append(f)
    return kept


def run_suite(paths=None, root=None, rules=None):
    """Run every rule over the given paths (default: the repo's own
    source roots).  Returns raw findings with inline waivers already
    applied; baseline subtraction is the caller's business."""
    global _last_summary
    from . import trc, don, lck, reg

    root = os.path.abspath(root or find_repo_root())
    paths = list(paths) if paths else [p for p in DEFAULT_ROOTS
                                       if os.path.exists(
                                           os.path.join(root, p))]
    modules = []
    findings = []
    for fp in iter_files(paths, root):
        try:
            modules.append(ModuleInfo(fp, root))
        except (SyntaxError, UnicodeDecodeError) as e:
            rel = os.path.relpath(fp, root).replace(os.sep, "/")
            findings.append(Finding(
                rule="WVR001", path=rel,
                line=getattr(e, "lineno", 1) or 1,
                message=f"file does not parse: {e}",
                hint="fix the syntax error", snippet=""))
    ctx = Context(root=root, modules=modules)
    reg.collect(ctx)
    for m in modules:
        findings += trc.check(m, ctx)
        findings += don.check(m, ctx)
        findings += lck.check(m, ctx)
        findings += reg.check(m, ctx)
    findings += lck.check_global(ctx)
    findings += reg.check_global(ctx)
    findings = _apply_waivers(findings, modules)
    if rules:
        findings = [f for f in findings
                    if any(f.rule.startswith(r) for r in rules)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    _last_summary = {"total": len(findings), "files": len(modules),
                     "rules": counts}
    return findings

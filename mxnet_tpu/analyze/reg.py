"""REG — string-keyed registry drift rules.

The framework's registries are stringly typed on purpose (env-var
configuration, Prometheus names, fault-spec strings survive process
boundaries), which means nothing but convention keeps a call site and
its declaration in sync.  ``mx.config.get`` raises on an unknown knob
and ``mx.fault.fire`` *silently returns False* on an unknown point —
the first fails loudly at runtime, the second never fails at all.
These rules close the loop statically:

* **REG001** — every ``config.get("k")`` names a knob declared in
  ``config.py``/``storage.py``.  The receiver is resolved through the
  import map, so a module-local dict named ``_config`` (profiler.py)
  is not confused with the registry.
* **REG002** — every declared knob carries a non-empty ``doc=``.
* **REG003** — every literally-named metric record (``inc``/
  ``observe``/``set_gauge``/``timed`` on the telemetry module) is
  declared via ``declare_metric`` somewhere in the tree.  Dynamic
  names are skipped; an ``IfExp`` of two literals checks both arms.
* **REG004** — every ``mx.fault`` point appears in at least one test.
* **REG005** — ``fire``/``armed`` with a literal name not in POINTS.
* **REG006** — ci/matrix.yaml stages, ci/run.sh case labels, and the
  ``all`` chain agree (scheduled stages are exempt from ``all``).
* **REG007** — every declared metric appears in
  docs/OBSERVABILITY.md (whose metric table the telemetry module
  documents as authoritative).
* **REG008** — every fault point appears in docs/FAULT_TOLERANCE.md's
  injection-point table (it is how users learn what MXNET_FAULT_SPEC
  can arm).
"""

import ast
import os
import re

_METRIC_FUNCS = {"inc", "observe", "set_gauge", "timed"}
_TELEMETRY_MODULES = ("mxnet_tpu.telemetry",)
_CONFIG_MODULES = ("mxnet_tpu.config",)
_FAULT_MODULES = ("mxnet_tpu.fault",)


def _literal_names(node):
    """String constants named by an expression: a literal, or both arms
    of a conditional expression.  Dynamic expressions -> []."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        return _literal_names(node.body) + _literal_names(node.orelse)
    return []


def _is_module_ref(module, node, canonical_modules):
    """True when `node` (the receiver of an attribute call) resolves to
    one of the canonical module paths."""
    return module.imports.resolve(node) in canonical_modules


def collect(ctx):
    """First pass: build the declared-name tables off the parsed
    modules (no file re-reads, no imports executed)."""
    for m in ctx.modules:
        base = os.path.basename(m.path)
        # knob declarations: declare("name", ..., doc=...) inside
        # config.py/storage.py, or config.declare(...) anywhere
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
                in_registry_file = base in ("config.py", "storage.py")
                is_decl = fname == "declare" and in_registry_file
            elif isinstance(node.func, ast.Attribute):
                fname = node.func.attr
                is_decl = fname == "declare" and \
                    m.imports.resolve(node.func.value) in _CONFIG_MODULES
            else:
                continue
            if is_decl and node.args:
                for name in _literal_names(node.args[0]):
                    # declare(name, typ, default, env, doc) — doc is the
                    # 5th positional in config.py's own style, or doc=
                    doc = ""
                    if len(node.args) >= 5 and isinstance(
                            node.args[4], ast.Constant):
                        doc = node.args[4].value or ""
                    for kw in node.keywords:
                        if kw.arg == "doc" and isinstance(
                                kw.value, ast.Constant):
                            doc = kw.value.value or ""
                    ctx.knobs[name] = (m, node.lineno, doc)
            if fname == "declare_metric" and node.args:
                for name in _literal_names(node.args[0]):
                    ctx.metrics.setdefault(name, (m, node.lineno))
        # fault points: the POINTS = {...} dict in fault.py
        if base == "fault.py":
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "POINTS"
                        for t in node.targets) and \
                        isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            ctx.fault_points[k.value] = (m, k.lineno)
        # strings appearing in tests (for REG004); f-string literal
        # fragments count too — specs like f"{point}:at=2" do not,
        # which is the conservative direction
        if "/tests/" in "/" + m.path or m.path.startswith("tests/"):
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    ctx.test_strings.add(node.value)


def check(module, ctx):
    findings = []
    base = os.path.basename(module.path)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        attr = node.func.attr
        recv = node.func.value
        # REG001: config knob reads
        if attr == "get" and node.args and base not in (
                "config.py",) and _is_module_ref(
                    module, recv, _CONFIG_MODULES):
            for name in _literal_names(node.args[0]):
                if name not in ctx.knobs:
                    findings.append(module.finding(
                        "REG001", node,
                        f"config knob {name!r} is read but never "
                        "declared in config.py",
                        hint="add config.declare(...) with a doc "
                             "string, or fix the knob name"))
        # REG003: metric records against the telemetry registry
        elif attr in _METRIC_FUNCS and node.args and _is_module_ref(
                module, recv, _TELEMETRY_MODULES):
            for name in _literal_names(node.args[0]):
                if name not in ctx.metrics:
                    findings.append(module.finding(
                        "REG003", node,
                        f"metric {name!r} is recorded but never "
                        "declared via declare_metric",
                        hint="declare it (name, kind, doc) next to "
                             "the subsystem's other metrics"))
        # REG005: fault points
        elif attr in ("fire", "armed") and node.args and \
                base != "fault.py" and _is_module_ref(
                    module, recv, _FAULT_MODULES):
            for name in _literal_names(node.args[0]):
                if name not in ctx.fault_points:
                    findings.append(module.finding(
                        "REG005", node,
                        f"fault point {name!r} is not in fault.POINTS "
                        "— fire() on it silently never fires",
                        hint="add the point to fault.POINTS or fix "
                             "the name"))
    # bare inc("x")/observe("x") inside telemetry.py itself
    if base == "telemetry.py":
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in _METRIC_FUNCS and node.args:
                for name in _literal_names(node.args[0]):
                    if name not in ctx.metrics:
                        findings.append(module.finding(
                            "REG003", node,
                            f"metric {name!r} is recorded but never "
                            "declared via declare_metric",
                            hint="declare it in the catalog"))
    return findings


# --- global checks -------------------------------------------------------

_STAGE_RE = re.compile(r"^\s*-\s*stage:\s*(\S+)")
_SCHED_RE = re.compile(r"^\s*schedule:")
# [a-z0-9_]: stage names may carry digits (e.g. the fp8 stage)
_CASE_RE = re.compile(r"^\s*([a-z][a-z0-9_]*)\)")


def _parse_matrix(path):
    """-> [(stage, lineno, scheduled)] from ci/matrix.yaml (regex — the
    file is ours and flat; no yaml dependency in the linter)."""
    out = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    current = None
    for i, line in enumerate(lines, start=1):
        m = _STAGE_RE.match(line)
        if m:
            current = [m.group(1), i, False]
            out.append(current)
        elif current is not None and _SCHED_RE.match(line):
            current[2] = True
    return [(s, ln, sched) for s, ln, sched in out]


def _parse_run_sh(path):
    """-> (case_labels {stage: lineno}, all_chain [stages])."""
    cases, all_chain = {}, []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    in_case = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if stripped.startswith("case "):
            in_case = True
        if not in_case:
            continue
        m = _CASE_RE.match(line)
        if m and m.group(1) != "all":
            cases[m.group(1)] = i
        if stripped.startswith("all)"):
            body = stripped[len("all)"):].split(";;")[0]
            all_chain = [p.strip() for p in body.split(";")
                        if p.strip()]
    return cases, all_chain


def check_global(ctx):
    findings = []

    # REG002: undocumented knobs (framework declarations only — tests
    # may declare scratch knobs)
    for name, (m, line, doc) in sorted(ctx.knobs.items()):
        if not doc.strip() and m.path.startswith("mxnet_tpu/"):
            findings.append(m.finding(
                "REG002", line,
                f"config knob {name!r} is declared without a doc "
                "string",
                hint="knobs are user API: say what it does and which "
                     "env var sets it"))

    # REG004: fault points no test exercises.  Substring match: fault
    # specs in tests look like "resilience.preempt:at=3", which counts.
    for name, (m, line) in sorted(ctx.fault_points.items()):
        if ctx.test_strings and not any(
                name in s for s in ctx.test_strings):
            findings.append(m.finding(
                "REG004", line,
                f"fault point {name!r} is not referenced by any test",
                hint="add a chaos test that arms and fires it (see "
                     "tests/test_fault_injection.py)"))

    # REG006: CI stage drift
    matrix_path = os.path.join(ctx.root, "ci", "matrix.yaml")
    run_path = os.path.join(ctx.root, "ci", "run.sh")
    if os.path.isfile(matrix_path) and os.path.isfile(run_path):
        matrix = _parse_matrix(matrix_path)
        cases, all_chain = _parse_run_sh(run_path)
        rel_matrix = os.path.relpath(matrix_path, ctx.root)
        rel_run = os.path.relpath(run_path, ctx.root)
        for stage, line, scheduled in matrix:
            if stage not in cases:
                findings.append(_file_finding(
                    rel_matrix, line, "REG006",
                    f"stage {stage!r} is in ci/matrix.yaml but has no "
                    "case in ci/run.sh",
                    "add the stage function and case arm to ci/run.sh",
                    matrix_path))
            elif not scheduled and stage not in all_chain:
                findings.append(_file_finding(
                    rel_matrix, line, "REG006",
                    f"PR-blocking stage {stage!r} is missing from the "
                    "'all' chain in ci/run.sh",
                    "append it to the all) arm (scheduled stages are "
                    "exempt)", matrix_path))
        matrix_names = {s for s, _, _ in matrix}
        for stage, line in sorted(cases.items()):
            if stage not in matrix_names:
                findings.append(_file_finding(
                    rel_run, line, "REG006",
                    f"stage {stage!r} is in ci/run.sh but absent from "
                    "ci/matrix.yaml",
                    "add a matrix row (platform + env) for it",
                    run_path))

    # REG007: declared metrics missing from the observability doc
    # (framework declarations only — tests declare scratch metrics)
    doc_path = os.path.join(ctx.root, "docs", "OBSERVABILITY.md")
    if os.path.isfile(doc_path) and ctx.metrics:
        with open(doc_path, encoding="utf-8") as f:
            doc_text = f.read()
        for name, (m, line) in sorted(ctx.metrics.items()):
            if name not in doc_text and m.path.startswith("mxnet_tpu/"):
                findings.append(m.finding(
                    "REG007", line,
                    f"declared metric {name!r} is missing from "
                    "docs/OBSERVABILITY.md",
                    hint="add a row to the metrics table (the "
                         "catalog docstring promises the doc tracks "
                         "it)"))

    # REG008: fault points missing from the fault-tolerance doc — the
    # injection-point table is how users learn what MXNET_FAULT_SPEC
    # can arm
    ft_path = os.path.join(ctx.root, "docs", "FAULT_TOLERANCE.md")
    if os.path.isfile(ft_path) and ctx.fault_points:
        with open(ft_path, encoding="utf-8") as f:
            ft_text = f.read()
        for name, (m, line) in sorted(ctx.fault_points.items()):
            if name not in ft_text:
                findings.append(m.finding(
                    "REG008", line,
                    f"fault point {name!r} is missing from "
                    "docs/FAULT_TOLERANCE.md",
                    hint="document it in the injection-point list "
                         "(what it simulates, which knob arms it)"))
    return findings


def _file_finding(relpath, line, rule, message, hint, abspath):
    from .core import Finding
    snippet = ""
    try:
        with open(abspath, encoding="utf-8") as f:
            lines = f.read().splitlines()
        if 1 <= line <= len(lines):
            snippet = lines[line - 1].strip()
    except OSError:
        pass
    return Finding(rule=rule, path=relpath.replace(os.sep, "/"),
                   line=line, message=message, hint=hint,
                   snippet=snippet)

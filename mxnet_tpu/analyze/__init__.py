"""``mx.analyze`` — framework-aware static analysis.

AST-level enforcement of the invariants the runtime can only sample:
trace purity (TRC), buffer-donation discipline (DON), lock ordering
(LCK), and string-keyed registry coherence (REG).  See
docs/STATIC_ANALYSIS.md for the rule catalog, baseline workflow, and
waiver syntax; ``tools/mxlint.py`` is the CLI and the CI ``lint``
stage gates on it.

Stdlib-only by design: importing or running this package never
imports jax and never executes the code under analysis.
"""

from .core import (  # noqa: F401
    DEFAULT_ROOTS, Finding, RULES, apply_baseline, last_summary,
    load_baseline, run_suite, write_baseline,
)

__all__ = ["run_suite", "Finding", "RULES", "DEFAULT_ROOTS",
           "load_baseline", "write_baseline", "apply_baseline",
           "last_summary"]

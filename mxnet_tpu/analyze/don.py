"""DON — buffer-donation rules.

``jax.jit(..., donate_argnums=...)`` hands the argument's device buffer
to XLA for reuse: after the call, the Python object still exists but
its buffer is dead, and touching it raises (or worse, on some backends,
silently reads garbage).  The serve engine's KV cache and the fused
trainer update both rely on donation, and both follow the one safe
idiom: *rebind the donated name from the call's results on the same
statement* (``self.trainable, ... = self._step(self.trainable, ...)``).

DON001 flags the unsafe shape: an argument passed at a donated position
whose name is read again later in the same function without having been
rebound by the donating call itself.  The analysis is function-local and
straight-line (lineno order); a re-assignment before the next read
clears the taint.
"""

import ast

from .core import dotted_path


def _donated_indices(call, imports):
    """Indices from donate_argnums when `call` is jax.jit/pjit, else
    None."""
    target = imports.resolve(call.func)
    if target in ("functools.partial", "partial") and call.args:
        target = imports.resolve(call.args[0])
    if target not in ("jax.jit", "jax.pjit",
                      "jax.experimental.pjit.pjit", "jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, int):
                        out.append(elt.value)
                return tuple(out)
    return None


def _collect_donating_callables(module):
    """Paths ('self._step', 'step_fn') bound to a donating jit, plus
    direct-call sites jax.jit(f, donate_argnums=...)(args).
    -> ({path: indices}, {call_node: indices})"""
    bound, direct = {}, {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        idx = _donated_indices(node, module.imports)
        if idx is None:
            continue
        parent = module.parents.get(node)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                p = dotted_path(t)
                if p:
                    bound[p] = idx
        elif isinstance(parent, ast.Call) and parent.func is node:
            direct[parent] = idx
    return bound, direct


def _stmt_of(module, node):
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = module.parents.get(cur)
    return cur


def _target_paths(stmt):
    out = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                p = dotted_path(el)
                if p:
                    out.add(p)
    elif isinstance(stmt, ast.AugAssign):
        p = dotted_path(stmt.target)
        if p:
            out.add(p)
    return out


def check(module, ctx):
    findings = []
    bound, direct = _collect_donating_callables(module)
    if not bound and not direct:
        return findings

    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            if call in direct:
                idx = direct[call]
            else:
                p = dotted_path(call.func)
                idx = bound.get(p) if p else None
            if idx is None:
                continue
            stmt = _stmt_of(module, call)
            if stmt is None:
                continue
            rebound = _target_paths(stmt)
            donated = []
            for i in idx:
                if i < len(call.args) and not isinstance(
                        call.args[i], ast.Starred):
                    path = dotted_path(call.args[i])
                    if path and path not in rebound:
                        donated.append((i, path))
            if not donated:
                continue
            # straight-line scan: first later event per donated path
            for i, path in donated:
                event = None  # ("load"|"store", node)
                for node in ast.walk(fn):
                    ln = getattr(node, "lineno", None)
                    if ln is None or ln <= stmt.lineno:
                        continue
                    np_ = dotted_path(node) if isinstance(
                        node, (ast.Name, ast.Attribute)) else None
                    if np_ != path:
                        continue
                    # only top-level matches: skip when this node is a
                    # sub-chain of a longer attribute path
                    par = module.parents.get(node)
                    if isinstance(par, ast.Attribute) and \
                            par.value is node:
                        continue
                    kind = "store" if isinstance(
                        getattr(node, "ctx", None), ast.Store) else "load"
                    if event is None or ln < event[1]:
                        event = (kind, ln, node)
                if event and event[0] == "load":
                    findings.append(module.finding(
                        "DON001", event[2],
                        f"{path!r} is read after being donated at "
                        f"line {stmt.lineno} (argument {i} of a "
                        "donate_argnums call) — its buffer is dead",
                        hint="rebind the name from the call's results "
                             "on the same statement, or drop it from "
                             "donate_argnums"))
    return findings

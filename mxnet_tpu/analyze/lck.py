"""LCK — lock-order and blocking-under-lock rules.

``mx.pipeline``, the DataLoader, telemetry and trace all guard shared
rings with plain ``threading.Lock``s.  Two hazards repeat across that
code:

* **LCK001** — two code paths acquiring the same pair of locks in
  opposite orders (classic deadlock).  The rule extracts every ``with
  <lock>:`` nesting (lexically, plus one level of same-module call
  resolution so ``with self._lock: self._flush()`` sees the locks
  ``_flush`` takes) into a global acquisition graph and fails on
  cycles.
* **LCK002** — a call that can block indefinitely (queue ``get``/
  ``put``, ``join``, ``sleep``, a collective) while a lock is held:
  every other thread touching that lock now waits on the slow path.
  The fault-telemetry deadlock fixed in PR 2 (``record()`` calling
  ``inc()`` under ``_lock``) is the house example.

Lock objects are recognised by name (a ``with`` target whose final
path segment contains ``lock`` or ``mutex``) and identified as
``module.Class.attr`` so distinct classes' ``self._lock`` stay
distinct nodes in the graph.
"""

import ast

from .core import dotted_path

_BLOCKING_RESOLVED = {"time.sleep"}
_BLOCKING_PREFIXES = ("jax.lax.p",)           # psum/pmean/pmax/...
_BLOCKING_RESOLVED_SUFFIX = (".all_gather", ".all_reduce", ".barrier")
_QUEUEISH = ("queue",)


def _is_lock_path(path):
    if not path:
        return False
    last = path.split(".")[-1].lower()
    return "lock" in last or "mutex" in last


def _queueish(seg):
    seg = seg.lower()
    return seg == "q" or seg.endswith("_q") or any(
        s in seg for s in _QUEUEISH)


def _lock_id(module, fn, path):
    cls = module.enclosing(fn, (ast.ClassDef,))
    scope = cls.name if cls is not None else fn.name
    # 'self._lock' and bare '_lock' (module global) normalise so the
    # same lock referenced both ways is one graph node
    norm = path[5:] if path.startswith("self.") else path
    if path.startswith("self."):
        return f"{module.path}:{scope}.{norm}"
    return f"{module.path}:{norm}"


def _blocking_reason(module, call):
    """Short description when `call` can block indefinitely, else
    None."""
    resolved = module.imports.resolve(call.func)
    if resolved:
        if resolved in _BLOCKING_RESOLVED:
            return resolved
        if resolved.startswith(_BLOCKING_PREFIXES) or \
                resolved.endswith(_BLOCKING_RESOLVED_SUFFIX):
            return f"collective {resolved}"
        return None
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = dotted_path(call.func.value)
    recv_seg = recv.split(".")[-1] if recv else ""
    kwargs = {kw.arg for kw in call.keywords}
    if attr in ("get", "put"):
        if _queueish(recv_seg) or {"timeout", "block"} & kwargs:
            return f"{recv or '?'}.{attr}()"
    elif attr == "join" and not call.args:
        # str.join takes a positional arg, thread/queue join takes none
        return f"{recv or '?'}.join()"
    return None


class _FuncSummary:
    """Per-function lock behaviour, lexical only."""

    def __init__(self, module, fn):
        self.module = module
        self.fn = fn
        self.acquires = []      # (lock_id, node, held_stack_at_entry)
        self.calls_under = []   # (held_stack, call_node)
        self.blocking = []      # (held_stack, call_node, reason)
        for child in ast.iter_child_nodes(fn):
            self._walk(child, [])

    def _locks_of(self, with_node):
        out = []
        for item in with_node.items:
            path = dotted_path(item.context_expr)
            if path is None and isinstance(item.context_expr, ast.Call):
                path = dotted_path(item.context_expr.func)
            if _is_lock_path(path):
                out.append(_lock_id(self.module, self.fn, path))
        return out

    def _walk(self, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs summarised separately
        if isinstance(node, ast.With):
            new = list(held)
            for lid in self._locks_of(node):
                self.acquires.append((lid, node, tuple(new)))
                new.append(lid)
            for b in node.body:
                self._walk(b, new)
            return
        if isinstance(node, ast.Call):
            if held:
                self.calls_under.append((tuple(held), node))
            reason = _blocking_reason(self.module, node)
            if reason:
                # recorded even with no lock held so that a caller
                # holding one can see this callee blocks
                self.blocking.append((tuple(held), node, reason))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


def _summaries(module):
    out = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = module.enclosing(node, (ast.ClassDef,))
            qual = f"{cls.name}.{node.name}" if cls else node.name
            out[(module.path, qual)] = _FuncSummary(module, node)
    return out


def _resolve_callee(module, fn_summary, call):
    """'self.foo(...)' -> same-class method foo; 'bar(...)' -> module
    function bar.  One level only, same module only."""
    path = dotted_path(call.func)
    if not path:
        return None
    cls = module.enclosing(fn_summary.fn, (ast.ClassDef,))
    if path.startswith("self.") and "." not in path[5:] and cls:
        return (module.path, f"{cls.name}.{path[5:]}")
    if "." not in path:
        return (module.path, path)
    return None


def check(module, ctx):
    """LCK002 per module (lexical + one call level)."""
    findings = []
    sums = _summaries(module)
    module._lck_summaries = sums  # stashed for check_global
    for key, s in sums.items():
        for held, node, reason in s.blocking:
            if not held:
                continue  # blocking with no lock held is fine
            findings.append(module.finding(
                "LCK002", node,
                f"blocking call {reason} while holding "
                f"{_short(held[-1])}",
                hint="release the lock before blocking, or bound the "
                     "wait and handle timeout"))
        # one level of call resolution: callee's top-level blocking
        # calls and acquisitions count as happening under our lock
        for held, call in s.calls_under:
            callee_key = _resolve_callee(module, s, call)
            if callee_key is None or callee_key == key:
                continue
            callee = sums.get(callee_key)
            if callee is None:
                continue
            for cheld, cnode, reason in callee.blocking:
                if cheld:
                    continue  # counted at its own site
                findings.append(module.finding(
                    "LCK002", call,
                    f"call to {callee_key[1]}() blocks ({reason}) "
                    f"while holding {_short(held[-1])}",
                    hint="release the lock before calling into a "
                         "blocking helper, or bound the wait"))
    return findings


def _short(lock_id):
    return lock_id.split(":", 1)[-1]


def check_global(ctx):
    """LCK001: cycle detection over the cross-module acquisition
    graph."""
    edges = {}   # (a, b) -> (module, node) first witness
    for m in ctx.modules:
        sums = getattr(m, "_lck_summaries", None)
        if not sums:
            continue
        for key, s in sums.items():
            for lid, node, held in s.acquires:
                for h in held:
                    if h != lid:
                        edges.setdefault((h, lid), (m, node))
            # call-level edges: lock held here -> locks callee takes
            for held, call in s.calls_under:
                callee_key = _resolve_callee(m, s, call)
                if callee_key is None or callee_key == key:
                    continue
                callee = sums.get(callee_key)
                if callee is None:
                    continue
                for clid, cnode, cheld in callee.acquires:
                    if not cheld:
                        for h in held:
                            if h != clid:
                                edges.setdefault((h, clid), (m, call))
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    findings = []
    seen_cycles = set()
    for start in sorted(graph):
        path, on_path = [], set()

        def dfs(node):
            if node in on_path:
                cyc = tuple(path[path.index(node):] + [node])
                canon = frozenset(cyc)
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    a, b = cyc[0], cyc[1]
                    m, witness = edges[(a, b)]
                    findings.append(m.finding(
                        "LCK001", witness,
                        "lock-order cycle: " + " -> ".join(
                            _short(x) for x in cyc),
                        hint="pick one global acquisition order for "
                             "these locks and stick to it"))
                return
            if node in graph:
                path.append(node)
                on_path.add(node)
                for nxt in sorted(graph[node]):
                    dfs(nxt)
                path.pop()
                on_path.remove(node)

        dfs(start)
    return findings

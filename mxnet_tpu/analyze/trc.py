"""TRC — trace-safety rules.

The hybridize/CachedOp contract (PAPER.md §2) says a traced graph must
be pure and replayable: no host syncs, no wall-clock or host-RNG reads,
no Python control flow on traced values.  The runtime half of that
contract is the PR 2 ``RecompileWarning`` detector and the PR 4
``sync_guard``; these rules are the static half, catching violations in
code paths the sampled runtime probes never execute.

Traced scopes are found, not annotated: any ``hybrid_forward``, any
function decorated with or passed to ``jax.jit`` / ``shard_map`` /
``lax.scan`` / ``jax.checkpoint`` (and friends), and anything nested
inside one.

TRC005 is the odd one out — it covers *host* code that runs once per
batch (estimator ``batch_end`` handlers and the serve/train step
methods): a host sync there is legal but stalls the device pipeline
every single step, which is exactly the bug class sync_guard exists
for.  Syncs under an emit-interval gate (an ``if`` whose condition
computes ``step % interval``) pass; a bare None-check does not.
"""

import ast

from .core import dotted_path

# canonical dotted paths whose function argument becomes a traced scope
TRACED_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.checkpoint", "jax.remat", "jax.ad_checkpoint.checkpoint",
    "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.maps.xmap",
}

# .attr() calls that force a device->host transfer
SYNC_METHODS = {"asnumpy", "item", "tolist", "to_py", "block_until_ready"}

# canonical call targets that materialise a traced value on host
SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.asanyarray",
              "numpy.copyto"}

# canonical prefixes that are impure inside a trace
IMPURE_PREFIXES = ("time.", "random.", "numpy.random.")

# attribute reads on a traced value that stay static under tracing
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}

# builtins whose result on a traced value is host-static (shape-level)
STATIC_FUNCS = {"len", "isinstance", "hasattr", "callable", "getattr",
                "type", "id"}

# host builtins that force a concrete value out of a traced array
COERCE_FUNCS = {"float", "int", "bool", "complex"}

# per-batch host hot paths checked by TRC005 (Class.method); estimator
# BatchEnd handlers are detected structurally on top of this list
HOT_PATHS = {
    ("ServeEngine", "step"),
    ("ShardedTrainStep", "__call__"),
    ("DevicePrefetcher", "__next__"),
}


def _unwrap_partial(call, imports):
    """functools.partial(jax.jit, ...) -> jax.jit (canonical path)."""
    target = imports.resolve(call.func)
    if target in ("functools.partial", "partial"):
        if call.args:
            return imports.resolve(call.args[0])
        return None
    return target


def _param_names(fn):
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _static_param_names(call, fn, target=None):
    """Parameters of `fn` the wrapper treats as static python values:
    jit static_argnames/static_argnums, vmap/pmap in_axes=None
    positions."""
    out = set()
    names = _param_names(fn) if fn is not None else []
    kw = {k.arg: k.value for k in call.keywords}
    v = kw.get("static_argnames")
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        out.add(v.value)
    elif isinstance(v, (ast.Tuple, ast.List)):
        out |= {e.value for e in v.elts
                if isinstance(e, ast.Constant) and
                isinstance(e.value, str)}
    v = kw.get("static_argnums")
    nums = []
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        nums = [v.value]
    elif isinstance(v, (ast.Tuple, ast.List)):
        nums = [e.value for e in v.elts
                if isinstance(e, ast.Constant) and
                isinstance(e.value, int)]
    for i in nums:
        if 0 <= i < len(names):
            out.add(names[i])
    # vmap/pmap: in_axes=None (or a None element) means the argument is
    # broadcast as-is — a python scalar there stays concrete
    v = kw.get("in_axes")
    if v is None and len(call.args) >= 2 and target is not None and \
            target.split(".")[-1] in ("vmap", "pmap"):
        v = call.args[1]
    if isinstance(v, (ast.Tuple, ast.List)):
        for i, e in enumerate(v.elts):
            if isinstance(e, ast.Constant) and e.value is None and \
                    i < len(names):
                out.add(names[i])
    return out


def _scope_of(module, node):
    """The function/module that lexically owns a def (for scope-aware
    name resolution)."""
    return module.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda, ast.Module)) or module.tree


def _in_scope(module, defnode, call):
    """True when the def's name is visible at the call site: the def's
    owning scope is the call's own function or one of its ancestors
    (module-level defs are visible everywhere in the module)."""
    owner = _scope_of(module, defnode)
    cur = call
    while cur is not None:
        if cur is owner:
            return True
        cur = module.parents.get(cur)
    return owner is module.tree


def _traced_functions(module):
    """-> (traced set of FunctionDef/Lambda, {fn: static param names})."""
    defs = {}  # name -> [FunctionDef]
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    traced = set()
    statics = {}

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == "hybrid_forward":
                traced.add(node)
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    target = _unwrap_partial(dec, module.imports)
                    if target in TRACED_WRAPPERS:
                        traced.add(node)
                        statics.setdefault(node, set()).update(
                            _static_param_names(dec, node, target))
                else:
                    if module.imports.resolve(dec) in TRACED_WRAPPERS:
                        traced.add(node)
        elif isinstance(node, ast.Call):
            target = _unwrap_partial(node, module.imports)
            if target not in TRACED_WRAPPERS:
                continue
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    # passed by name: only defs whose scope encloses
                    # this call (or module level) — same-named defs in
                    # sibling functions are different objects
                    for d in defs[arg.id]:
                        if _in_scope(module, d, node):
                            traced.add(d)
                            statics.setdefault(d, set()).update(
                                _static_param_names(node, d, target))

    # everything nested inside a traced function is traced too
    out = set(traced)
    for fn in traced:
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and sub is not fn:
                out.add(sub)
    return out, statics


def _params_of(fn):
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    names = [a.arg for a in args.posonlyargs + args.args +
             args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    # self/cls carry the module, not traced data
    return {n for n in names if n not in ("self", "cls", "F")}


def _taint(fn, static_params=()):
    """Names in fn plausibly bound to traced values: the parameters
    (minus declared-static ones), plus anything assigned from an
    expression reaching one through a dynamic channel (iterated to a
    fixpoint).  ``c, h, w = img.shape`` does NOT taint c/h/w — shape,
    dtype, len() etc. are static under tracing."""
    tainted = _params_of(fn) - set(static_params)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AugAssign):
                value, targets = node.value, [node.target]
            elif isinstance(node, (ast.For, ast.comprehension)):
                value = node.iter
                targets = [node.target]
            else:
                continue
            if _dynamic_taint_in(value, tainted) is not None:
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and \
                                n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
    return tainted


def _is_static_expr(node, tainted):
    """True when the expression only touches traced values through
    static channels (shape/dtype/len/isinstance/`is None`)."""
    if isinstance(node, ast.Call):
        fname = node.func.id if isinstance(node.func, ast.Name) else None
        if fname in STATIC_FUNCS:
            return True
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in node.ops):
            return True
    if isinstance(node, ast.Attribute):
        # x.shape, x.ndim — and anything hanging off them
        cur = node
        while isinstance(cur, ast.Attribute):
            if cur.attr in STATIC_ATTRS:
                return True
            cur = cur.value
    return False


def _dynamic_taint_in(test, tainted):
    """The first tainted Name reached through a non-static channel in a
    branch condition, or None."""
    skip = set()
    for node in ast.walk(test):
        if node in skip:
            continue
        if _is_static_expr(node, tainted):
            for sub in ast.walk(node):
                skip.add(sub)
            continue
        if isinstance(node, ast.Name) and node.id in tainted:
            return node
    return None


def _check_traced_body(module, fn, findings, static_params=()):
    tainted = _taint(fn, static_params)
    own_nested = {sub for sub in ast.walk(fn)
                  if isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                  and sub is not fn}
    fname = getattr(fn, "name", "<lambda>")

    for node in ast.walk(fn):
        # nodes belonging to a nested def get their own pass with their
        # own taint set — skip them here to avoid duplicate findings
        owner = module.enclosing(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
        if owner is not fn and owner in own_nested:
            continue
        if isinstance(node, ast.Call):
            # host-sync methods: x.asnumpy(), loss.item()
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in SYNC_METHODS and \
                    module.imports.resolve(node.func) is None:
                findings.append(module.finding(
                    "TRC001", node,
                    f".{node.func.attr}() forces a host sync inside "
                    f"traced scope {fname!r}",
                    hint="keep device values on device; move the sync "
                         "outside the traced function"))
                continue
            target = module.imports.resolve(node.func)
            if target in SYNC_CALLS:
                findings.append(module.finding(
                    "TRC001", node,
                    f"{target}() materialises a traced value on host "
                    f"inside traced scope {fname!r}",
                    hint="use jax.numpy inside traced code"))
            elif target and target.startswith(IMPURE_PREFIXES):
                findings.append(module.finding(
                    "TRC002", node,
                    f"impure call {target}() inside traced scope "
                    f"{fname!r} bakes one sample into the compiled "
                    "graph",
                    hint="thread a jax.random key (or pass the value "
                         "in as an argument)"))
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in COERCE_FUNCS and node.args:
                if _dynamic_taint_in(node.args[0], tainted) is not None:
                    findings.append(module.finding(
                        "TRC001", node,
                        f"{node.func.id}() on a traced value inside "
                        f"traced scope {fname!r} forces a host sync",
                        hint="return the value and coerce it outside "
                             "the trace"))
        elif isinstance(node, (ast.If, ast.While)):
            hit = _dynamic_taint_in(node.test, tainted)
            if hit is not None:
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(module.finding(
                    "TRC003", node,
                    f"Python `{kind}` on traced value {hit.id!r} in "
                    f"traced scope {fname!r} (concretisation error or "
                    "silent recompile per branch)",
                    hint="use jax.lax.cond/select, or branch on "
                         "x.shape/x.ndim if the decision is static"))


def _check_closure_capture(module, fn, traced, findings):
    """TRC004: a traced nested def reading a variable the enclosing
    function mutates (step counters and friends) — each new value is a
    fresh compile-time constant, i.e. one recompile per step."""
    nested = [sub for sub in ast.walk(fn)
              if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
              and sub is not fn and sub in traced]
    if not nested:
        return
    varying = set()
    for node in ast.walk(fn):
        owner = module.enclosing(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
        if owner is not fn:
            continue
        if isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name):
            varying.add(node.target.id)
        elif isinstance(node, ast.Assign) and \
                module.enclosing(node, (ast.For, ast.While)) is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    varying.add(t.id)
    if not varying:
        return
    for sub in nested:
        local = _params_of(sub) | {
            n.id for n in ast.walk(sub)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}
        for node in ast.walk(sub):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in varying and node.id not in local:
                findings.append(module.finding(
                    "TRC004", node,
                    f"traced function {sub.name!r} closes over "
                    f"{node.id!r}, which {getattr(fn, 'name', '?')!r} "
                    "mutates per step — every new value recompiles",
                    hint="pass it as a traced argument, or mark it "
                         "static on purpose"))


def _is_batch_end_handler(module, fn):
    if fn.name != "batch_end":
        return False
    cls = module.enclosing(fn, (ast.ClassDef,))
    if cls is None:
        return False
    bases = {b.id if isinstance(b, ast.Name) else
             (b.attr if isinstance(b, ast.Attribute) else "")
             for b in cls.bases}
    return "BatchEnd" in bases or "EventHandler" in bases


def _check_hot_path(module, fn, findings):
    """TRC005: unconditional per-batch host syncs in host hot paths."""
    cls = module.enclosing(fn, (ast.ClassDef,))
    clsname = cls.name if cls is not None else None
    if not (_is_batch_end_handler(module, fn) or
            (clsname, fn.name) in HOT_PATHS):
        return
    for node in ast.walk(fn):
        # only the unambiguous sync signals here: in host code there is
        # no traced-parameter anchor, so float()/int() of an arbitrary
        # expression is usually a plain host coercion, not a transfer
        sync = None
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in SYNC_METHODS:
                sync = f".{node.func.attr}()"
            elif module.imports.resolve(node.func) in SYNC_CALLS:
                sync = module.imports.resolve(node.func) + "()"
        if sync is None:
            continue
        # exempt syncs under an emit-interval gate — an ancestor `if`
        # whose condition computes a modulo (`step % interval == 0`);
        # a bare None-check does not make a per-batch sync cheaper
        guard = node
        gated = False
        while True:
            guard = module.enclosing(guard, (ast.If,))
            if guard is None or module.enclosing(
                    guard, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    is not fn:
                break
            if any(isinstance(sub, ast.BinOp) and
                   isinstance(sub.op, ast.Mod)
                   for sub in ast.walk(guard.test)):
                gated = True
                break
        if gated:
            continue
        where = f"{clsname}.{fn.name}" if clsname else fn.name
        findings.append(module.finding(
            "TRC005", node,
            f"unconditional host sync {sync} in per-batch hot path "
            f"{where} stalls the device pipeline every step",
            hint="gate the sync on the emit/log interval so most "
                 "steps stay sync-free"))


def check(module, ctx):
    findings = []
    traced, statics = _traced_functions(module)
    for fn in traced:
        if isinstance(fn, ast.Lambda):
            continue  # lambdas: too small to taint-track usefully
        _check_traced_body(module, fn, findings,
                           static_params=statics.get(fn, ()))
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node not in traced:
                _check_closure_capture(module, node, traced, findings)
                _check_hot_path(module, node, findings)
    return findings

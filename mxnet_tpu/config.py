"""mx.config — unified typed configuration.

Reference parity: the reference configures itself through three mechanisms
(SURVEY §5 "Config / flag system"):

1. ~72 environment variables read ad hoc via ``dmlc::GetEnv`` at use sites
   (docs/static_site/src/pages/api/faq/env_var.md:43-238);
2. ``dmlc::Parameter`` reflection structs declaring typed fields with
   defaults, ranges and docs (pattern: src/imperative/cached_op.h:412-459
   ``CachedOpConfig``);
3. cmake feature flags surfaced at runtime via libinfo
   (``mx.runtime.feature_list()`` — kept in runtime.py).

This module unifies (1)+(2): every knob is declared once with type,
default, doc and an env-var override; values are introspectable
(``mx.config.describe()``) and settable at runtime (``mx.config.set``).
``Params`` is the ``dmlc::Parameter`` analog for op/block config structs.
"""
from __future__ import annotations

import os
import threading

from .base import MXNetError

__all__ = ["declare", "get", "set", "reset", "describe", "knobs",
           "Field", "Params"]

_lock = threading.Lock()
_registry: dict[str, "_Knob"] = {}


class _Knob:
    __slots__ = ("name", "typ", "default", "env", "doc", "_value", "_set")

    def __init__(self, name, typ, default, env, doc):
        self.name = name
        self.typ = typ
        self.default = default
        self.env = env
        self.doc = doc
        self._value = None
        self._set = False

    def _coerce(self, val):
        if self.typ is bool and isinstance(val, str):
            return val not in ("0", "false", "False", "")
        return self.typ(val)

    def value(self):
        if self._set:
            return self._value
        if self.env:
            raw = os.environ.get(self.env)
            if raw is not None:
                return self._coerce(raw)
        return self.default


def declare(name, typ=str, default=None, env=None, doc=""):
    """Register a configuration knob (once, at module import)."""
    with _lock:
        if name in _registry:
            return _registry[name]
        knob = _Knob(name, typ, default, env, doc)
        _registry[name] = knob
        return knob


def get(name):
    knob = _registry.get(name)
    if knob is None:
        raise MXNetError(f"unknown config knob {name!r}; see "
                         "mx.config.describe()")
    return knob.value()


def set(name, value):  # noqa: A001 - mirrors the reference's setter name
    knob = _registry.get(name)
    if knob is None:
        raise MXNetError(f"unknown config knob {name!r}")
    with _lock:
        prev = knob.value()
        knob._value = knob._coerce(value)
        knob._set = True
    return prev


def reset(name=None):
    """Drop runtime overrides (env/defaults apply again)."""
    if name is not None and name not in _registry:
        raise MXNetError(f"unknown config knob {name!r}; see "
                         "mx.config.describe()")
    with _lock:
        for knob in ([_registry[name]] if name else _registry.values()):
            knob._set = False
            knob._value = None


def knobs():
    return dict(_registry)


def describe():
    """Human-readable table of every knob (env_var.md analog)."""
    lines = []
    for name in sorted(_registry):
        k = _registry[name]
        env = f" [env {k.env}]" if k.env else ""
        lines.append(f"{name} ({k.typ.__name__}, default={k.default!r})"
                     f"{env}: {k.doc}")
    return "\n".join(lines)


# -- the built-in knob set (the env_var.md surface that applies on TPU) ----

declare("seed", int, 0, "MXNET_SEED",
        "Global RNG seed (reference: mx.random.seed / MXNET_SEED).")
declare("engine.type", str, "PJRT", "MXNET_ENGINE_TYPE",
        "Engine selector; informational — PJRT async dispatch is the only "
        "engine (reference: NaiveEngine/ThreadedEngine/PerDevice).")
declare("engine.bulk_size", int, 15, "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN",
        "Default op-bulking window for engine.bulk() scopes (reference: "
        "threaded_engine.h:433 op bulking; XLA fuses under jit here).")
declare("update_on_kvstore", bool, None, "MXNET_UPDATE_ON_KVSTORE",
        "Force Trainer's update_on_kvstore choice (reference: trainer.py).")
declare("profiler.autostart", bool, False, "MXNET_PROFILER_AUTOSTART",
        "Start the profiler at import (reference: profiler env knob).")
declare("native.build_dir", str, "", "MXNET_TPU_NATIVE_BUILD",
        "Build/cache dir for native (C++) helper libraries "
        "('' = <repo>/native/build).")
declare("fused_conv_bn", str, "auto", "MXNET_FUSED_CONV_BN",
        "Pallas fused conv3x3+BN+ReLU backward on eligible blocks: "
        "'auto' (TPU only), 'on', 'off'.")
declare("cached_graph.max_signatures", int, 512,
        "MXNET_CACHED_GRAPH_MAX_SIGNATURES",
        "Max distinct call signatures one compiled block keeps before its "
        "trace caches are flushed (bounds the recompile/memory blowup from "
        "varying python scalars; reference analog: CachedOpConfig limits, "
        "src/imperative/cached_op.h:412-459)")
declare("fused_ln_residual", str, "auto", "MXNET_FUSED_LN_RESIDUAL",
        "Pallas fused dropout+residual+LayerNorm in transformer encoder "
        "cells: 'auto' (TPU only), 'on', 'off'.")
declare("kvstore.async_timeout", float, 120.0,
        "MXNET_KVSTORE_ASYNC_TIMEOUT",
        "Seconds a dist_async reconciling pull may wait on its collective "
        "before failing loudly (mismatched pull schedules deadlock the "
        "SPMD psum; the reference's ZMQ server has no such constraint)")
declare("home", str, os.path.join("~", ".mxnet"), "MXNET_HOME",
        "Cache root for datasets/pretrained weights (reference: base.py "
        "data_dir).")
declare("fault.spec", str, "", "MXNET_FAULT_SPEC",
        "Fault-injection spec, 'point:at=N[,prob=P,times=K,seed=S];...' "
        "('' = all injection points disabled; see mx.fault.POINTS).")
declare("telemetry.enable", bool, False, "MXNET_TELEMETRY",
        "Enable the mx.telemetry metrics registry (counters/gauges/"
        "histograms wired through cached-graph compile, dataloader, "
        "trainer, kvstore and fault paths); disabled, every hook costs "
        "one module-attribute read.")
declare("telemetry.recompile_limit", int, 8, "MXNET_TELEMETRY_RECOMPILE_LIMIT",
        "Per-block XLA trace+compile count above which the recompilation "
        "detector emits a structured RecompileWarning (the TPU shape-"
        "polymorphism pitfall); fires once per block.")
declare("telemetry.jsonl", str, "", "MXNET_TELEMETRY_JSONL",
        "Default JSONL path for TrainingTelemetry step records and the "
        "final run report ('' = keep records in memory only).")
declare("telemetry.step_interval", int, 1, "MXNET_TELEMETRY_STEP_INTERVAL",
        "TrainingTelemetry emits a JSONL step record every N step() calls.")
declare("dataloader.worker_mode", str, "auto", "MXNET_DATALOADER_WORKER_MODE",
        "num_workers>0 execution mode: 'threads', 'processes', or 'auto' "
        "(first-batch cost probe picks processes only for GIL-bound "
        "python transforms — BENCH_r05 shows IPC makes processes 4x "
        "slower for everything else).")
declare("dataloader.mp_threshold_ms", float, 2.0,
        "MXNET_DATALOADER_MP_THRESHOLD_MS",
        "auto worker mode: per-sample python cost (ms) above which the "
        "GIL dominates and process workers beat threads.")
declare("dataloader.max_respawns", int, 2, "MXNET_DATALOADER_MAX_RESPAWNS",
        "Crashed/hung worker-pool respawns tolerated per epoch before the "
        "loader degrades to threaded workers.")
declare("dataloader.respawn_backoff", float, 0.1,
        "MXNET_DATALOADER_RESPAWN_BACKOFF",
        "Base seconds slept before respawning a crashed worker pool "
        "(doubles per retry).")
declare("dataloader.shm_ring", bool, True, "MXNET_DATALOADER_SHM_RING",
        "Process-worker loaders reuse a pool of SharedMemory segments "
        "across batches instead of create/unlink per leaf (BENCH_r05: the "
        "churn made process workers 0.25x thread throughput); off restores "
        "the historical one-shot segments.")
declare("dataloader.shm_ring_max", int, 32, "MXNET_DATALOADER_SHM_RING_MAX",
        "Max idle SharedMemory segments the reuse pool keeps per loader; "
        "overflow segments are unlinked oldest-first.")
declare("pipeline.prefetch_depth", int, 2, "MXNET_PIPELINE_PREFETCH_DEPTH",
        "In-flight batch window of a mx.pipeline.DevicePrefetcher (2 = "
        "double buffering, 3 = triple); bounds host+device memory pinned "
        "by prefetched batches.")
declare("pipeline.stall_timeout", float, 30.0, "MXNET_PIPELINE_STALL_TIMEOUT",
        "Seconds a DevicePrefetcher consumer waits on an empty queue "
        "before declaring the background thread stalled and handing its "
        "source iterator to a replacement thread (counted in "
        "mx.fault.stats()).")
declare("pipeline.deferred_window", int, 32, "MXNET_PIPELINE_DEFERRED_WINDOW",
        "Default mx.pipeline.DeferredWindow capacity: device scalars "
        "(grad norms, metric accumulators) pending host fetch; overflow "
        "drains oldest-first and counts as a host sync.")
declare("compilation_cache_dir", str, "", "MXNET_COMPILE_CACHE",
        "Directory for JAX's persistent XLA compilation cache ('' = off); "
        "repeated runs reuse compiled executables instead of recompiling. "
        "Armed at import when set; mx._compile_cache.configure() applies "
        "a runtime change.")
declare("trainer.skip_nonfinite", bool, False, "MXNET_TRAINER_SKIP_NONFINITE",
        "Trainer.step skips (and counts) updates whose global grad norm "
        "is non-finite instead of poisoning the weights; automatic when "
        "an AMP loss scaler is attached.")
declare("kvstore.retry_max", int, 2, "MXNET_KVSTORE_RETRY_MAX",
        "Transient-failure retries per blocking dist collective "
        "(CollectiveTimeout / coordination-service hiccups): each retry "
        "re-barriers via jax.distributed and re-issues the collective; "
        "0 disables retry (a timeout raises immediately); exhausting the "
        "budget escalates a structured resilience.WorkerLost.")
declare("kvstore.retry_backoff", float, 0.5, "MXNET_KVSTORE_RETRY_BACKOFF",
        "Base seconds slept before a collective retry (doubles per "
        "attempt, +25% jitter so rejoining workers don't stampede the "
        "coordination service).")
declare("kvstore.rejoin_timeout", float, 10.0, "MXNET_KVSTORE_REJOIN_TIMEOUT",
        "Seconds a retrying worker waits at the jax.distributed rejoin "
        "barrier for its peers before retrying the collective anyway "
        "(best-effort alignment; a missed barrier is counted, not fatal).")
declare("resilience.max_restarts", int, 3, "MXNET_RESILIENCE_MAX_RESTARTS",
        "In-process training restarts mx.resilience.run() performs after "
        "a WorkerLost escalation (each restart restores the last "
        "TrainState bundle) before re-raising to the caller.")
declare("serve.max_slots", int, 8, "MXNET_SERVE_MAX_SLOTS",
        "Decode slots in the mx.serve continuous-batching engine: the "
        "fixed batch dimension of the one resident compiled decode step "
        "and of every preallocated KV-cache array.")
declare("serve.buckets", str, "16,32,64,128,256,512",
        "MXNET_SERVE_BUCKETS",
        "Prompt-length buckets for prefill (comma-separated, ascending). "
        "Each bucket is one compiled prefill graph; prompts pad up to the "
        "smallest fitting bucket so a mixed request stream never compiles "
        "after warmup (the telemetry.recompile_limit detector is the "
        "guard rail). Buckets beyond the cache's max_seq are dropped.")
declare("serve.drain_window", int, 4, "MXNET_SERVE_DRAIN_WINDOW",
        "Bounded deferred-drain window of the serve loop: device-resident "
        "(token, done) vectors pending host fetch. Completions are "
        "observed at most this many steps late; larger windows keep the "
        "step loop fully sync-free, smaller ones free slots sooner.")
declare("autotune.cache_dir", str, "", "MXNET_AUTOTUNE_CACHE",
        "Directory for mx.autotune's winners.json ('' = fall back to "
        "compilation_cache_dir — the tuned configs live next to the XLA "
        "executables they produced — then <home>/autotune).")
declare("autotune.trial_seconds", float, 0.4, "MXNET_AUTOTUNE_TRIAL_SECONDS",
        "Target measured window per autotune trial (after warmup); short "
        "trials keep a full search under a minute, the winner's number is "
        "re-validated by production telemetry anyway.")
declare("autotune.trial_warmup", int, 1, "MXNET_AUTOTUNE_TRIAL_WARMUP",
        "Warmup calls per autotune trial before the timed window (the "
        "first call — trace + compile — is always excluded).")
declare("autotune.max_trials", int, 0, "MXNET_AUTOTUNE_MAX_TRIALS",
        "Cap on measured trials per search (0 = no cap): the cost model "
        "keeps the predicted-best survivors plus the default baseline and "
        "prunes the rest as 'ranked_out'.")
declare("autotune.hbm_fraction", float, 0.9, "MXNET_AUTOTUNE_HBM_FRACTION",
        "Fraction of the per-device bytes_limit (PJRT memory_stats, the "
        "memory.* gauges) usable as the autotune HBM budget — headroom "
        "for allocator fragmentation and the host's transfer buffers.")
declare("autotune.recompile_limit", int, 64,
        "MXNET_AUTOTUNE_RECOMPILE_LIMIT",
        "Trial-scoped telemetry.recompile_limit during an autotune "
        "search: every candidate legitimately compiles once, so the "
        "detector budget is widened for the trials and restored (with "
        "the pre-search compile counts) afterwards.")
declare("autotune.launch_overhead_items", float, 8.0,
        "MXNET_AUTOTUNE_LAUNCH_OVERHEAD_ITEMS",
        "Cost-model constant: per-launch dispatch overhead expressed in "
        "item-equivalents, amortized over batch*steps_per_call when "
        "ranking candidates (tunneled-TPU dispatch is ~1-7ms/launch).")
declare("autotune.kernel_trial_fraction", float, 0.5,
        "MXNET_AUTOTUNE_KERNEL_TRIAL_FRACTION",
        "Fraction of the VMEM-feasible kernel block-shape candidates the "
        "kernel-level search actually measures: the cost model (learned "
        "when it out-ranks the analytic one, see "
        "autotune.learned_rank_corr) ranks the grid and only the "
        "predicted-top fraction (min 1, always including the static "
        "default) gets a timed trial.")
declare("autotune.kernel_trial_seconds", float, 0.1,
        "MXNET_AUTOTUNE_KERNEL_TRIAL_SECONDS",
        "Target measured window per kernel block-shape trial — kernels "
        "are microseconds-scale, so a much shorter window than the "
        "step-level autotune.trial_seconds still averages hundreds of "
        "launches.")
declare("autotune.retune_on_drift", bool, False,
        "MXNET_AUTOTUNE_RETUNE_ON_DRIFT",
        "Arm the online kernel re-tuner: when mx.insight raises a "
        "step-time drift event, an armed Retuner re-searches kernel "
        "block shapes in a background thread and hot-swaps the winner "
        "at the next checkpoint boundary (autotune.retunes_total).")
declare("quantize.fused_matmul", str, "auto", "MXNET_QUANTIZE_FUSED_MATMUL",
        "Pallas fused quantize+int8-dot+dequant matmul for calibrated "
        "QuantizedDense layers: 'auto' (TPU only), 'on' (everywhere, "
        "interpret-mode off-TPU), 'off' (XLA dot_general fallback).")
declare("quantize.fp8_format", str, "e4m3", "MXNET_QUANTIZE_FP8_FORMAT",
        "fp8 activation/weight format for the fp8 matmul variant: 'e4m3' "
        "(more mantissa, inference default) or 'e5m2' (more range).")
declare("amp.fp8_history", int, 16, "MXNET_AMP_FP8_HISTORY",
        "Delayed-scaling amax history length (steps) for fp8 training: "
        "each tensor's quantization scale derives from the max |x| seen "
        "over this many past steps (docs/PRECISION.md).")
declare("amp.fp8_margin", float, 1.0, "MXNET_AMP_FP8_MARGIN",
        "Safety margin multiplied into the delayed-scaling amax before "
        "mapping it to the fp8 format's absmax; >1 trades headroom for "
        "resolution against inter-step amax growth.")
declare("amp.fp8_min_elems", int, 256, "MXNET_AMP_FP8_MIN_ELEMS",
        "Smallest 2-D '.weight' parameter (elements) the fp8 training "
        "path quantizes; smaller layers stay in the step's base dtype "
        "(the scale bookkeeping would cost more than the matmul saves).")
declare("comm.compress", str, "none", "MXNET_COMM_COMPRESS",
        "Gradient compression for the dp-axis reduction inside "
        "ShardedTrainStep: 'none', 'int8' (symmetric int8 with error "
        "feedback, ~4x fewer wire bytes) or 'bf16' (~2x). Requires a "
        "pure-dp mesh (docs/PRECISION.md).")
declare("comm.bucket_mb", float, 4.0, "MXNET_COMM_BUCKET_MB",
        "Flat gradient bucket size (MiB, fp32 element count) for the "
        "compressed dp reduction; each bucket reduces as an independent "
        "collective the XLA scheduler can overlap with backward compute.")
declare("autotune.fp8_parity_tol", float, 0.05, "MXNET_AUTOTUNE_FP8_PARITY_TOL",
        "Relative loss deviation vs an fp32 reference step above which a "
        "precision='fp8' autotune trial is rejected (status 'parity') — "
        "fp8 only ships on shape buckets that prove loss-curve parity.")
declare("serve.allow_fp8_requant", bool, False, "MXNET_SERVE_ALLOW_FP8_REQUANT",
        "Let int4_weights serve engines requantize fp8-trained "
        "checkpoints anyway (default off: double quantization below the "
        "fp8 grid's resolution degrades accuracy silently).")
declare("serve.quantize_min_elems", int, 4096, "MXNET_SERVE_QUANTIZE_MIN_ELEMS",
        "Smallest parameter (elements) serve weight quantization touches; "
        "below it the bytes saved don't cover the dequant epilogue.")
declare("serve.quantize_ndim", int, 2, "MXNET_SERVE_QUANTIZE_NDIM",
        "Parameter rank serve weight quantization targets (2 = matmul "
        "weights; biases/norms always pass through in fp).")
declare("serve.quantize_group_size", int, 128,
        "MXNET_SERVE_QUANTIZE_GROUP_SIZE",
        "Input-axis group size for int4 group-wise weight scales; rows "
        "whose width is not divisible fall back to one scale per row.")
declare("trace.enable", bool, False, "MXNET_TRACE",
        "Enable the mx.trace span recorder (causal tracing through the "
        "train step, pipeline prefetch, serve request and autotune trial "
        "lifecycles); disabled, every hook costs one module-attribute "
        "read, like telemetry.enable.")
declare("trace.buffer", int, 4096, "MXNET_TRACE_BUFFER",
        "Capacity of the per-process mx.trace span ring buffer; overflow "
        "drops oldest-first and counts trace.dropped_total.")
declare("telemetry.http_port", int, 0, "MXNET_TELEMETRY_PORT",
        "Arm the stdlib ops endpoint at import on this port (0 = off): "
        "GET /metrics (Prometheus exposition), /healthz, /trace?last=N. "
        "mx.telemetry.serve_http(port) starts it at runtime; port 0 "
        "there binds an ephemeral port.")
declare("analyze.report_path", str, "", "MXNET_ANALYZE_REPORT",
        "Saved tools/mxlint.py --json document to fold into training-run "
        "reports as the 'analyze' plane ('' = only in-process "
        "mx.analyze.run_suite results are reported).")
declare("fleet.lease_dir", str, "", "MXNET_FLEET_LEASE_DIR",
        "Shared directory for the file-backed heartbeat-lease fallback "
        "of the mx.fleet health plane ('' = coordination-service only). "
        "Every host renews host-<rank>.lease there; peers whose lease "
        "age exceeds fleet.lease_timeout are treated as lost.")
declare("fleet.lease_interval", float, 1.0, "MXNET_FLEET_LEASE_INTERVAL",
        "Seconds between heartbeat-lease renewals published by the "
        "mx.fleet health plane's background thread.")
declare("fleet.lease_timeout", float, 5.0, "MXNET_FLEET_LEASE_TIMEOUT",
        "Lease age (seconds) past which a peer host counts as lost: the "
        "fleet supervisor re-plans the mesh over the survivors. Keep "
        "comfortably above fleet.lease_interval.")
declare("fleet.step_deadline", float, 0.0, "MXNET_FLEET_STEP_DEADLINE",
        "Wall-clock budget (seconds) for one training step before the "
        "fleet watchdog treats the host as wedged and escalates a "
        "structured WorkerLost (0 = watchdog off; stragglers are gauged "
        "at fleet.slow_fraction of the deadline either way).")
declare("fleet.slow_fraction", float, 0.5, "MXNET_FLEET_SLOW_FRACTION",
        "Fraction of fleet.step_deadline past which a host counts as a "
        "straggler (fleet.stragglers gauge) while still making progress "
        "— slow, not wedged.")
declare("fleet.min_dp", int, 1, "MXNET_FLEET_MIN_DP",
        "Floor on the data-parallel axis the degrade planner may shrink "
        "to after host loss; when no surviving layout reaches it the "
        "supervisor parks (fleet.parked gauge) and waits for capacity "
        "instead of training on a uselessly small mesh.")
declare("insight.enable", bool, False, "MXNET_INSIGHT",
        "Master switch for the mx.insight attribution plane (XLA cost "
        "capture, live MFU/roofline gauges, step-time drift detection, "
        "fleet snapshots). Disabled, every insight hook costs one "
        "attribute read.")
declare("insight.drift_window", int, 32, "MXNET_INSIGHT_DRIFT_WINDOW",
        "Samples anchoring the drift detector's robust baseline "
        "(median + MAD) and setting the EWMA half-life over step-time "
        "sources; an injected slowdown must alarm within this many "
        "samples.")
declare("insight.drift_sigma", float, 3.0, "MXNET_INSIGHT_DRIFT_SIGMA",
        "Robust z-score (MAD-scaled) the step-time EWMA must exceed "
        "above baseline, two samples running, before insight.drift "
        "fires — the false-positive vs time-to-detect dial.")
declare("insight.snapshot_interval", float, 5.0,
        "MXNET_INSIGHT_SNAPSHOT_INTERVAL",
        "Seconds between atomic insight-<rank>.json fleet snapshots "
        "published next to the heartbeat leases (riding the "
        "HealthPlane.beat cadence, so no extra thread).")
declare("insight.input_bound_ratio", float, 0.5,
        "MXNET_INSIGHT_INPUT_BOUND_RATIO",
        "Fraction of the measured step time the pipeline.input_stall_"
        "seconds p50 must exceed before the roofline verdict flips to "
        "'input' — the data plane, not the math, is the bottleneck "
        "(surfaced on /insight and in bench rows).")
declare("stream.on_corrupt", str, "raise", "MXNET_STREAM_ON_CORRUPT",
        "Checksum-failure policy for mx.stream record reads: 'raise' "
        "escalates a structured CorruptRecord (carried into blackbox "
        "postmortem bundles), 'skip' drops the record and counts it in "
        "stream.records_skipped_total.")
declare("stream.open_retries", int, 2, "MXNET_STREAM_OPEN_RETRIES",
        "Shard-open attempts retried (with stream.open_backoff * attempt "
        "sleeps) before mx.stream escalates a WorkerLost-style "
        "ShardUnreadable; the bounded budget is what guarantees "
        "escalation instead of a hang.")
declare("stream.open_backoff", float, 0.05, "MXNET_STREAM_OPEN_BACKOFF",
        "Base backoff (seconds) between shard-open retries; attempt k "
        "sleeps k * backoff.")
declare("insight.straggler_ratio", float, 1.5,
        "MXNET_INSIGHT_STRAGGLER_RATIO",
        "A host whose step-time EWMA (from its fleet snapshot) exceeds "
        "this multiple of the fleet median is marked a straggler by "
        "check_peers, independent of the fixed fleet.slow_fraction "
        "deadline cutoff.")
declare("resilience.keep_bundles", int, 3, "MXNET_RESILIENCE_KEEP_BUNDLES",
        "Valid TrainState bundle generations retained by save() as the "
        "degrade path's fallback chain (<path>.gN history hard-links); "
        "torn and older generations are deleted at save time. 0 keeps "
        "only the primary bundle file.")
declare("resilience.restart_window_steps", int, 1000,
        "MXNET_RESILIENCE_RESTART_WINDOW",
        "Healthy-progress window (optimizer steps between WorkerLost "
        "events) after which mx.resilience.run's restart budget resets, "
        "so N transient faults spread over a long run don't exhaust "
        "resilience.max_restarts; 0 keeps the budget monotonic.")
declare("telemetry.report_max_bytes", int, 0,
        "MXNET_TELEMETRY_REPORT_MAX_BYTES",
        "Size cap (bytes) for a TrainingTelemetry JSONL report file; when "
        "the next record would cross it the file rotates to the next free "
        "<path>.gNNNN generation (whole records only, never truncated "
        "mid-line) so ROADMAP item 5 keeps every generation discoverable "
        "via TrainingTelemetry.generations(). 0 = unbounded.")
declare("telemetry.event_ring", int, 256, "MXNET_TELEMETRY_EVENT_RING",
        "Capacity of the bounded telemetry event ring that captures "
        "python warnings (RecompileWarning et al.) and framework log "
        "records >= WARNING once mx.blackbox arms its capture hooks; "
        "postmortem bundles embed this ring so crashes carry the "
        "warnings that preceded them.")
declare("blackbox.enable", bool, False, "MXNET_BLACKBOX",
        "Arm the mx.blackbox flight recorder: sys/threading excepthooks, "
        "warning/log capture into the telemetry event ring, and shadow "
        "snapshots riding HealthPlane.beat; terminal triggers (uncaught "
        "exception, preemption, WorkerLost, non-finite escalation, "
        "insight drift) then write one crash-atomic checksummed "
        "postmortem bundle. Disabled, every hook costs one module-"
        "attribute read.")
declare("blackbox.dir", str, "", "MXNET_BLACKBOX_DIR",
        "Directory for blackbox-<rank>-<step>.json postmortem bundles "
        "('' = fall back to fleet.lease_dir at dump time so surviving "
        "hosts can read a dead peer's bundle; if that is also unset, "
        "dumps are skipped).")
declare("blackbox.window", int, 256, "MXNET_BLACKBOX_WINDOW",
        "Last-N evidence window a postmortem bundle embeds: newest N "
        "trace spans and newest N telemetry events (the metric snapshot "
        "and knob dump are always whole).")
declare("blackbox.checkpoint_interval", float, 10.0,
        "MXNET_BLACKBOX_CHECKPOINT_INTERVAL",
        "Seconds between shadow bundle snapshots riding HealthPlane.beat "
        "(no extra thread) so SIGKILL/OOM — where no excepthook runs — "
        "still leaves a <=interval-stale bundle per host; 0 disables "
        "shadow snapshots.")
declare("blackbox.keep", int, 3, "MXNET_BLACKBOX_KEEP",
        "Newest postmortem bundles retained per rank by dump()'s "
        "retention sweep (older bundle + .sha256 sidecar pairs are "
        "deleted); 0 keeps every bundle.")
declare("serve.max_queue", int, 0, "MXNET_SERVE_MAX_QUEUE",
        "Bound on requests waiting for a decode slot; submit() past it "
        "raises a structured EngineBusy (counted as "
        "serve.rejected_total) so callers get backpressure instead of "
        "an unbounded queue. 0 = unbounded.")
declare("serve.health_window", float, 30.0, "MXNET_SERVE_HEALTH_WINDOW",
        "Seconds without a decode step while work is pending before the "
        "serve engine reports itself unhealthy on the ops /healthz "
        "endpoint (step-loop liveness, not static OK).")
declare("goodput.enable", bool, False, "MXNET_GOODPUT",
        "Master switch for the mx.goodput wall-clock ledger (badput "
        "attribution, fleet device-second merge, SLO burn rates). "
        "Disabled, every goodput hook costs one attribute read.")
declare("goodput.target", float, 0.0, "MXNET_GOODPUT_TARGET",
        "Training goodput SLO: the target fraction of wall clock spent "
        "in compute (e.g. 0.95). Setting it arms the 5m/1h error-"
        "budget burn-rate gauges and the goodput /healthz provider; "
        "0 disables the SLO layer.")
declare("goodput.burn_threshold", float, 2.0,
        "MXNET_GOODPUT_BURN_THRESHOLD",
        "Error-budget burn rate past which the goodput /healthz "
        "provider reports unhealthy (503) — only when every burn "
        "window agrees, so a 5-minute blip alone never pages.")
declare("goodput.snapshot_interval", float, 5.0,
        "MXNET_GOODPUT_SNAPSHOT_INTERVAL",
        "Seconds between atomic goodput-<rank>.json ledger snapshots "
        "published next to the heartbeat leases (riding the "
        "HealthPlane.beat cadence, so no extra thread).")
declare("serve.slo_ttft_ms", float, 0.0, "MXNET_SERVE_SLO_TTFT_MS",
        "Serving SLO: time-to-first-token objective in milliseconds. "
        "A finished prefill slower than this counts into "
        "serve.slo_violations_total{kind=ttft} and the per-engine "
        "burn gauge; 0 disarms the ttft objective.")
declare("serve.slo_tpot_ms", float, 0.0, "MXNET_SERVE_SLO_TPOT_MS",
        "Serving SLO: per-output-token decode latency objective in "
        "milliseconds, checked at request finish; violations count "
        "into serve.slo_violations_total{kind=tpot}. 0 disarms.")
declare("serve.slo_target", float, 0.99, "MXNET_SERVE_SLO_TARGET",
        "Fraction of requests that must meet the serve SLO "
        "objectives; 1 - target is the error budget the "
        "serve.slo_burn_rate gauges burn against.")
declare("serve.prefix_cache", int, 0, "MXNET_SERVE_PREFIX_CACHE",
        "Enable the engine's radix prefix cache (1 = on): requests "
        "sharing a cached token-block prefix copy the matching KV rows "
        "inside the fixed donated cache allocation and prefill only "
        "the suffix. Off by default — enabling adds a block-copy and a "
        "per-bucket suffix-prefill executable to the warmup grid.")
declare("serve.prefix_block", int, 16, "MXNET_SERVE_PREFIX_BLOCK",
        "Tokens per KV block in the prefix cache's radix index (and in "
        "mx.servefleet's prefix-fingerprint router): reuse happens at "
        "whole-block granularity, so smaller blocks match more but "
        "index more.")
declare("serve.prefix_capacity", int, 0, "MXNET_SERVE_PREFIX_CAPACITY",
        "Max blocks the prefix cache's radix index may hold before "
        "LRU-evicting refcount-0 leaves; 0 = unbounded (the natural "
        "bound is max_slots * max_seq / prefix_block — the index only "
        "ever points at rows of the fixed cache allocation).")
declare("serve.spec_tokens", int, 4, "MXNET_SERVE_SPEC_TOKENS",
        "Speculative-decoding proposal length k: the draft model "
        "proposes k tokens greedily per round and the big model "
        "verifies all k in one batched call. Used only when the "
        "engine was built with a draft model.")
declare("serve.slo_classes", str, "", "MXNET_SERVE_SLO_CLASSES",
        "Multi-tenant SLO classes, comma-separated, highest priority "
        "first (e.g. 'gold,bronze'). Admission dequeues strict-"
        "priority with starvation aging (serve.class_aging_ms); '' = "
        "one implicit 'default' class (plain FIFO, the single-tenant "
        "behaviour).")
declare("serve.class_aging_ms", float, 0.0, "MXNET_SERVE_CLASS_AGING_MS",
        "Starvation-aging knob for SLO-class admission: a queued "
        "request waiting longer than this is promoted ahead of "
        "strict priority (oldest aged request first). 0 = pure "
        "strict priority (low classes can starve under overload).")
declare("serve.class_max_queue", str, "", "MXNET_SERVE_CLASS_MAX_QUEUE",
        "Per-class queue budgets as 'class=N,class=N' (e.g. "
        "'gold=8,bronze=64'): submit() rejects a class past its own "
        "budget with EngineBusy(queue_full) even when the global "
        "serve.max_queue still has room. Classes absent from the spec "
        "fall back to the global bound.")
declare("serve.phase_sampling", int, 64, "MXNET_SERVE_PHASE_SAMPLING",
        "Per-request cap on always-on phase timing samples "
        "(queue_wait/prefill/decode_step) kept for stats()['phases'] "
        "without the tracer armed; 0 restores the trace-only "
        "behaviour (one attribute read on the disabled path).")
declare("servefleet.min_replicas", int, 1, "MXNET_SERVEFLEET_MIN_REPLICAS",
        "Floor on live serving replicas a mx.servefleet group may drop "
        "to: rolling weight updates take replicas out one at a time "
        "only while the rest stay at or above this floor, and the "
        "scale-in path refuses to drain below it.")
declare("servefleet.max_replicas", int, 0, "MXNET_SERVEFLEET_MAX_REPLICAS",
        "Ceiling the SLO-driven scale-out path may grow a mx.servefleet "
        "group to (unparking parked replicas first, then building new "
        "engines); 0 caps at the replica count the fleet was "
        "constructed with.")
declare("servefleet.stall_deadline", float, 2.0,
        "MXNET_SERVEFLEET_STALL_DEADLINE",
        "Seconds a replica's engine may sit with pending work and no "
        "decode-step progress before the fleet supervisor declares it "
        "stalled and fails its requests over to the survivors (the "
        "serve.replica_stall drill drives this path).")
declare("servefleet.scale_patience", int, 3,
        "MXNET_SERVEFLEET_SCALE_PATIENCE",
        "Consecutive supervisor ticks an SLO burn-rate breach (scale "
        "out) or an occupancy-floor underrun (scale in) must persist "
        "before mx.servefleet acts — and the cooldown ticks after an "
        "action before it will act again.")
declare("servefleet.occupancy_floor", float, 0.25,
        "MXNET_SERVEFLEET_OCCUPANCY_FLOOR",
        "Mean slot occupancy across live replicas below which the "
        "mx.servefleet autoscaler drains and parks one replica "
        "(never below servefleet.min_replicas).")
declare("servefleet.canary_tokens", int, 8,
        "MXNET_SERVEFLEET_CANARY_TOKENS",
        "Greedy tokens generated per pinned canary prompt when a "
        "rolling weight update validates a replica's freshly loaded "
        "checkpoint before returning it to the router; divergence "
        "from the checkpoint's canary card triggers auto-rollback.")
declare("servefleet.ledger_retain", int, 1024,
        "MXNET_SERVEFLEET_LEDGER_RETAIN",
        "Completed requests the mx.servefleet exactly-once ledger keeps "
        "(most recent first) to absorb duplicate client submits of an "
        "already-finished idempotency key; older completions are "
        "evicted so a long-running fleet's memory and per-tick sweep "
        "stay bounded.  In-flight requests are never evicted.")


# -- dmlc::Parameter analog -------------------------------------------------

class Field:
    """Typed field of a Params struct (DMLC_DECLARE_FIELD analog)."""

    def __init__(self, typ, default=None, doc="", lower=None, upper=None,
                 choices=None):
        self.typ = typ
        self.default = default
        self.doc = doc
        self.lower = lower
        self.upper = upper
        self.choices = choices
        self.name = None  # set by Params.__init_subclass__

    def validate(self, value):
        if value is None:
            return None
        try:
            value = (self.typ(value)
                     if not isinstance(value, self.typ) else value)
        except (TypeError, ValueError):
            raise MXNetError(
                f"{self.name}: expected {self.typ.__name__}, got {value!r}")
        if self.lower is not None and value < self.lower:
            raise MXNetError(f"{self.name}={value} below lower bound "
                             f"{self.lower}")
        if self.upper is not None and value > self.upper:
            raise MXNetError(f"{self.name}={value} above upper bound "
                             f"{self.upper}")
        if self.choices is not None and value not in self.choices:
            raise MXNetError(f"{self.name}={value!r} not in {self.choices}")
        return value


class Params:
    """Typed config struct: declare fields as class attributes.

    The analog of ``dmlc::Parameter<T>`` (reference:
    src/imperative/cached_op.h:412-459):

        class CachedOpConfig(Params):
            inline_limit = Field(int, 2, "inline small graphs", lower=0)
            static_alloc = Field(bool, False, "pre-allocate buffers")

    Construction validates kwargs against the declared fields; unknown
    keys raise.  ``describe()`` documents the struct.
    """

    _fields: dict[str, Field] = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        fields = dict(getattr(cls, "_fields", {}))
        for key, val in list(vars(cls).items()):
            if isinstance(val, Field):
                val.name = key
                fields[key] = val
        cls._fields = fields

    def __init__(self, **kwargs):
        for key, field in self._fields.items():
            setattr(self, key, field.validate(
                kwargs.pop(key, field.default)))
        if kwargs:
            raise MXNetError(
                f"{type(self).__name__}: unknown fields {sorted(kwargs)}; "
                f"declared: {sorted(self._fields)}")

    def to_dict(self):
        return {k: getattr(self, k) for k in self._fields}

    @classmethod
    def describe(cls):
        lines = [cls.__name__ + ":"]
        for key, f in sorted(cls._fields.items()):
            bounds = ""
            if f.lower is not None or f.upper is not None:
                bounds = f" range[{f.lower},{f.upper}]"
            if f.choices is not None:
                bounds += f" choices={sorted(f.choices)}"
            lines.append(f"  {key} ({f.typ.__name__}, "
                         f"default={f.default!r}){bounds}: {f.doc}")
        return "\n".join(lines)

    def __repr__(self):
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._fields)
        return f"{type(self).__name__}({inner})"

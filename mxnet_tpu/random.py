"""mx.random — global seed facade over JAX splittable keys.

Reference parity: python/mxnet/random.py (mx.random.seed seeds per-device
kRandom/kParallelRandom resources, src/resource.cc). TPU-native design: one
process-global threefry key; `_next_key()` splits a fresh subkey per sampler
call. Seeding is therefore exactly reproducible, like the reference's
seed_state, while staying functional underneath.
"""
from __future__ import annotations

import threading

import jax

from . import config

_lock = threading.Lock()
# lazily materialized: building a PRNGKey initializes the jax backend, and
# importing the package must NOT touch devices (spawned dataloader workers
# and CLI tools import mxnet_tpu with no accelerator in reach)
_key = None
_trace = threading.local()


def _global_key():
    global _key
    if _key is None:
        _key = jax.random.PRNGKey(config.get("seed"))
    return _key


def seed(seed_state, ctx="all"):
    """Seed the global generator (reference: random.py seed(seed_state, ctx))."""
    global _key, _fallback_n
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))
        _fallback_n = 0


def get_state():
    """Snapshot of the global RNG streams — the jax key, the tracer-
    fallback counter, and numpy's global generator (which seeds samplers
    and dataset shuffles).  Everything is plain numpy/python so it
    pickles into a TrainState bundle; ``set_state`` restores it bitwise."""
    import numpy as onp
    with _lock:
        key = None if _key is None else onp.asarray(_key)
    return {"key": key, "fallback_n": _fallback_n,
            "numpy": onp.random.get_state()}


def set_state(state):
    """Restore a snapshot from :func:`get_state` (elastic resume)."""
    global _key, _fallback_n
    import numpy as onp
    k = state.get("key")
    with _lock:
        _key = None if k is None else jax.numpy.asarray(
            onp.asarray(k, dtype=onp.uint32))
        _fallback_n = int(state.get("fallback_n", 0))
    np_state = state.get("numpy")
    if np_state is not None:
        onp.random.set_state(np_state)


_fallback_n = 0


def _next_key():
    # Inside a hybridized trace, keys split from the traced per-call key so
    # each compiled invocation gets fresh randomness (dropout etc.).
    stack = getattr(_trace, "stack", None)
    if stack:
        cur = stack[-1]
        nxt, sub = jax.random.split(cur)
        stack[-1] = nxt
        return sub
    global _key, _fallback_n
    with _lock:
        nxt, sub = jax.random.split(_global_key())
        if isinstance(nxt, jax.core.Tracer):
            # Called under an external jit trace without a trace_key_scope:
            # never leak a tracer into the process-global key. Derive a unique
            # constant key instead (randomness is then baked per-trace; pass
            # an explicit key for per-step randomness under jit).
            _fallback_n += 1
            import sys
            ag = sys.modules.get("mxnet_tpu.autograd")
            if ag is not None and ag.is_training():
                import warnings
                warnings.warn(
                    "mxnet_tpu.random: RNG drawn inside an external jit "
                    "trace without a trace_key_scope — the sample (e.g. a "
                    "dropout mask) is baked into the compiled program and "
                    "repeats every step. Use hybridize()/functional_call "
                    "or pass an explicit key.", stacklevel=3)
            # tag keeps this stream disjoint from any seeded eager stream
            return jax.random.fold_in(
                jax.random.PRNGKey(0x7A17BA5E), _fallback_n)
        _key = nxt
    return sub


class trace_key_scope:
    """Scope installing a (possibly traced) base key for _next_key splits.
    Used by the hybridize cache so compiled programs take randomness as an
    input instead of baking a constant key into the executable."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        if not hasattr(_trace, "stack"):
            _trace.stack = []
        _trace.stack.append(self._key)
        return self

    def __exit__(self, *exc):
        _trace.stack.pop()


def key(n=None):
    """Expose raw JAX keys for native-jax interop."""
    if n is None:
        return _next_key()
    return jax.random.split(_next_key(), n)


# legacy mx.random.* samplers alias the np.random implementations
def __getattr__(name):
    from .numpy import random as npr
    if hasattr(npr, name):
        return getattr(npr, name)
    raise AttributeError(name)

"""mx.callback — training callbacks.

Reference parity: python/mxnet/callback.py (Speedometer:91,
do_checkpoint, LogValidationMetricsCallback, ProgressBar).  Callbacks
receive BatchEndParam-style objects with epoch/nbatch/eval_metric
attributes — the estimator and 1.x-style loops both produce them.
"""
from __future__ import annotations

import logging
import time
from collections import namedtuple

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


class Speedometer:
    """Log samples/sec + metrics every `frequent` batches
    (reference: callback.py:91)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if not self.init:
            self.init = True
            self.tic = time.time()
            return
        if count % self.frequent != 0:
            return
        speed = self.frequent * self.batch_size / (time.time() - self.tic)
        msg = f"Epoch[{param.epoch}] Batch [{count}]\tSpeed: " \
              f"{speed:.2f} samples/sec"
        if param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                msg += f"\t{name}={value:f}"
            if self.auto_reset:
                param.eval_metric.reset()
        logging.getLogger(__name__).info(msg)
        self.tic = time.time()


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving `prefix`-NNNN checkpoints
    (reference: callback.py do_checkpoint over model.save_checkpoint)."""
    from . import model as _model

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            _model.save_checkpoint(prefix, iter_no + 1, sym,
                                   arg or {}, aux or {})
    return _callback


class LogValidationMetricsCallback:
    """Epoch-end callback logging validation metrics (reference:
    callback.py LogValidationMetricsCallback)."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.getLogger(__name__).info(
                "Epoch[%d] Validation-%s=%f", param.epoch, name, value)

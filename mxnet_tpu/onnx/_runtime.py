"""A jnp-backed ONNX graph evaluator.

Serves two roles: (1) `mx.onnx.import_model` — run third-party or exported
ONNX models inside the framework (the reference keeps its importer in
mx.contrib / onnx2mx, reference: python/mxnet/onnx/mx2onnx/_export_onnx.py
module docstring notes the paired direction), and (2) the round-trip oracle
for the exporter's tests: export -> parse -> evaluate -> compare with the
original TPU forward.

Supports the op subset the exporter emits plus common aliases (Relu,
Softmax, Gemm) so simple externally-produced models also load.  Evaluation
is jit-friendly: building `make_fn` returns a pure function of the graph
inputs that can be wrapped in jax.jit.
"""
from __future__ import annotations

import numpy as np

from . import serde
from .serde import node_attrs, np_dtype, to_array

_OPS = {}


def _op(*names):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn
    return deco


def _jnp():
    import jax.numpy as jnp
    return jnp


# elementwise ---------------------------------------------------------------

for _name, _fn in [
    ("Add", lambda a, b: a + b), ("Sub", lambda a, b: a - b),
    ("Mul", lambda a, b: a * b), ("Div", lambda a, b: a / b),
    ("Pow", lambda a, b: a ** b), ("Neg", lambda x: -x),
    ("Max", lambda *xs: _reduce_variadic("maximum", xs)),
    ("Min", lambda *xs: _reduce_variadic("minimum", xs)),
]:
    _OPS[_name] = (lambda f: (lambda attrs, *ins: f(*ins)))(_fn)


def _reduce_variadic(name, xs):
    jnp = _jnp()
    out = xs[0]
    for x in xs[1:]:
        out = getattr(jnp, name)(out, x)
    return out


def _unary(fname):
    def impl(attrs, x):
        jnp = _jnp()
        return getattr(jnp, fname)(x)
    return impl


for _o, _f in [("Exp", "exp"), ("Log", "log"), ("Tanh", "tanh"),
               ("Sqrt", "sqrt"), ("Abs", "abs"), ("Sign", "sign"),
               ("Floor", "floor"), ("Ceil", "ceil"),
               ("Sin", "sin"), ("Cos", "cos"), ("Atan", "arctan"),
               ("Asin", "arcsin"), ("Acos", "arccos"),
               ("Sinh", "sinh"), ("Cosh", "cosh")]:
    _OPS[_o] = _unary(_f)


@_op("Round")
def _round(attrs, x):
    return _jnp().round(x)


@_op("Reciprocal")
def _reciprocal(attrs, x):
    return 1.0 / x


@_op("Erf")
def _erf(attrs, x):
    import jax
    return jax.scipy.special.erf(x)


@_op("Sigmoid")
def _sigmoid(attrs, x):
    import jax
    return jax.nn.sigmoid(x)


@_op("Relu")
def _relu(attrs, x):
    return _jnp().maximum(x, 0)


@_op("Softmax")
def _softmax(attrs, x):
    import jax
    return jax.nn.softmax(x, axis=attrs.get("axis", -1))


@_op("Clip")
def _clip(attrs, x, lo=None, hi=None):
    jnp = _jnp()
    if lo is not None:
        x = jnp.maximum(x, lo)
    if hi is not None:
        x = jnp.minimum(x, hi)
    return x


@_op("Mod")
def _mod(attrs, a, b):
    jnp = _jnp()
    if attrs.get("fmod", 0):
        return jnp.fmod(a, b)
    return jnp.mod(a, b)


@_op("Identity")
def _identity(attrs, x):
    return x


@_op("Cast")
def _cast(attrs, x):
    return x.astype(np_dtype(attrs["to"]))


@_op("Where")
def _where(attrs, cond, a, b):
    return _jnp().where(cond, a, b)


for _o, _f in [("Equal", "equal"), ("Less", "less"),
               ("LessOrEqual", "less_equal"), ("Greater", "greater"),
               ("GreaterOrEqual", "greater_equal"),
               ("And", "logical_and"), ("Or", "logical_or"),
               ("Xor", "logical_xor")]:
    def _mk(f):
        return lambda attrs, a, b: getattr(_jnp(), f)(a, b)
    _OPS[_o] = _mk(_f)


@_op("Not")
def _not(attrs, x):
    return _jnp().logical_not(x)


# shape ---------------------------------------------------------------------

@_op("Reshape")
def _reshape(attrs, x, shape):
    return _jnp().reshape(x, [int(d) for d in np.asarray(shape)])


@_op("Transpose")
def _transpose(attrs, x):
    return _jnp().transpose(x, attrs.get("perm"))


@_op("Squeeze")
def _squeeze(attrs, x, axes=None):
    ax = tuple(int(a) for a in np.asarray(axes)) if axes is not None else None
    return _jnp().squeeze(x, axis=ax)


@_op("Unsqueeze")
def _unsqueeze(attrs, x, axes):
    return _jnp().expand_dims(x, tuple(int(a) for a in np.asarray(axes)))


@_op("Expand")
def _expand(attrs, x, shape):
    jnp = _jnp()
    target = [int(d) for d in np.asarray(shape)]
    # ONNX Expand uses numpy broadcasting vs the target shape
    return jnp.broadcast_to(x, jnp.broadcast_shapes(tuple(target),
                                                    x.shape))


@_op("Concat")
def _concat(attrs, *xs):
    return _jnp().concatenate(xs, axis=attrs["axis"])


@_op("Slice")
def _slice(attrs, x, starts, ends, axes=None, steps=None):
    starts = [int(v) for v in np.asarray(starts)]
    ends = [int(v) for v in np.asarray(ends)]
    axes = ([int(v) for v in np.asarray(axes)] if axes is not None
            else list(range(len(starts))))
    steps = ([int(v) for v in np.asarray(steps)] if steps is not None
             else [1] * len(starts))
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        # INT64_MIN end with negative step means "through element 0"
        if sp < 0 and en <= -(2 ** 62):
            en = None
        idx[ax] = slice(st, en, sp)
    return x[tuple(idx)]


@_op("Pad")
def _pad(attrs, x, pads, value=None):
    jnp = _jnp()
    pads = [int(v) for v in np.asarray(pads)]
    rank = x.ndim
    width = [(pads[i], pads[i + rank]) for i in range(rank)]
    cv = 0 if value is None else np.asarray(value).item()
    return jnp.pad(x, width, constant_values=cv)


@_op("Range")
def _range(attrs, start, limit, delta):
    return _jnp().arange(np.asarray(start).item(), np.asarray(limit).item(),
                         np.asarray(delta).item())


@_op("CumSum")
def _cumsum(attrs, x, axis):
    r = _jnp().cumsum(x, axis=int(np.asarray(axis)))
    if attrs.get("reverse", 0):
        raise NotImplementedError("CumSum reverse")
    return r


# reductions ----------------------------------------------------------------

def _reduce(fname):
    def impl(attrs, x, axes=None):
        jnp = _jnp()
        # axes arrive as an input (opset 13+ ReduceSum / opset 18+ others)
        # or as an attribute (older opsets); honor whichever is present
        if axes is not None:
            ax = tuple(int(a) for a in np.asarray(axes))
        else:
            ax = tuple(attrs["axes"]) if "axes" in attrs else None
        return getattr(jnp, fname)(x, axis=ax,
                                   keepdims=bool(attrs.get("keepdims", 1)))
    return impl


_OPS["ReduceSum"] = _reduce("sum")
_OPS["ReduceMax"] = _reduce("max")
_OPS["ReduceMin"] = _reduce("min")
_OPS["ReduceProd"] = _reduce("prod")
_OPS["ReduceMean"] = _reduce("mean")


@_op("Split")
def _split(attrs, x, sizes=None):
    jnp = _jnp()
    axis = attrs.get("axis", 0)
    sz = [int(s) for s in np.asarray(sizes).reshape(-1)]
    offs = np.cumsum([0] + sz)
    return [jnp.take(x, jnp.arange(offs[i], offs[i + 1]), axis=axis)
            for i in range(len(sz))]


@_op("Sign")
def _sign(attrs, x):
    return _jnp().sign(x)


@_op("Atan")
def _atan(attrs, x):
    return _jnp().arctan(x)


@_op("TopK")
def _topk(attrs, x, k):
    jnp = _jnp()
    k = int(np.asarray(k).reshape(-1)[0])
    axis = attrs.get("axis", -1)
    largest = attrs.get("largest", 1)
    sorted_ = attrs.get("sorted", 1)  # lax.top_k always sorts
    if axis not in (-1, x.ndim - 1):
        x_sw = jnp.moveaxis(x, axis, -1)
    else:
        x_sw = x
    src = x_sw if largest else -x_sw
    import jax
    vals, idx = jax.lax.top_k(src, k)
    if not largest:
        vals = -vals
    if axis not in (-1, x.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(np.int64)


@_op("ScatterND")
def _scatternd(attrs, data, indices, updates):
    jnp = _jnp()
    red = attrs.get("reduction", "none")
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    if red == "add":
        return data.at[idx].add(updates)
    if red in ("none", b"none", ""):
        return data.at[idx].set(updates)
    if red == "mul":
        return data.at[idx].multiply(updates)
    raise NotImplementedError(f"ScatterND reduction {red!r}")


@_op("ArgMax")
def _argmax(attrs, x):
    r = _jnp().argmax(x, axis=attrs.get("axis", 0))
    if attrs.get("keepdims", 1):
        r = _jnp().expand_dims(r, attrs.get("axis", 0))
    return r.astype(np.int64)


@_op("ArgMin")
def _argmin(attrs, x):
    r = _jnp().argmin(x, axis=attrs.get("axis", 0))
    if attrs.get("keepdims", 1):
        r = _jnp().expand_dims(r, attrs.get("axis", 0))
    return r.astype(np.int64)


# matmul / conv / pooling ---------------------------------------------------

@_op("MatMul")
def _matmul(attrs, a, b):
    return _jnp().matmul(a, b)


@_op("Einsum")
def _einsum(attrs, *xs):
    return _jnp().einsum(attrs["equation"], *xs)


@_op("Gemm")
def _gemm(attrs, a, b, c=None):
    jnp = _jnp()
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    out = alpha * jnp.matmul(a, b)
    if c is not None:
        out = out + beta * c
    return out


@_op("Conv")
def _conv(attrs, x, w, b=None):
    import jax
    jnp = _jnp()
    nd = x.ndim - 2
    strides = attrs.get("strides", [1] * nd)
    dil = attrs.get("dilations", [1] * nd)
    group = attrs.get("group", 1)
    pads = attrs.get("pads", [0] * (2 * nd))
    padding = [(pads[i], pads[i + nd]) for i in range(nd)]
    if attrs.get("auto_pad", "NOTSET") not in ("NOTSET", "VALID"):
        raise NotImplementedError("Conv auto_pad=SAME_*")
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dil, feature_group_count=group)
    if b is not None:
        out = out + jnp.reshape(b, (1, -1) + (1,) * nd)
    return out


def _pool(reducer, init, x, attrs, average=False, count_include_pad=False):
    import jax
    if attrs.get("ceil_mode", 0):
        raise NotImplementedError("pooling ceil_mode=1")
    if attrs.get("auto_pad", "NOTSET") not in ("NOTSET", "VALID"):
        raise NotImplementedError("pooling auto_pad=SAME_*")
    kernel = attrs["kernel_shape"]
    nd = len(kernel)
    strides = attrs.get("strides", [1] * nd)
    dil = attrs.get("dilations", [1] * nd)
    pads = attrs.get("pads", [0] * (2 * nd))
    padding = [(0, 0), (0, 0)] + [(pads[i], pads[i + nd]) for i in range(nd)]
    window = (1, 1) + tuple(kernel)
    stride = (1, 1) + tuple(strides)
    dilation = (1, 1) + tuple(dil)
    out = jax.lax.reduce_window(x, init, reducer, window, stride, padding,
                                window_dilation=dilation)
    if average:
        if count_include_pad:
            out = out / float(np.prod(kernel))
        else:
            ones = _jnp().ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        stride, padding,
                                        window_dilation=dilation)
            out = out / cnt
    return out


@_op("MaxPool")
def _maxpool(attrs, x):
    import jax
    init = (-np.inf if np.issubdtype(x.dtype, np.floating)
            else np.iinfo(x.dtype).min)
    return _pool(jax.lax.max, init, x, attrs)


@_op("AveragePool")
def _avgpool(attrs, x):
    import jax
    return _pool(jax.lax.add, 0.0, x, attrs, average=True,
                 count_include_pad=bool(attrs.get("count_include_pad", 0)))


@_op("GlobalAveragePool")
def _gap(attrs, x):
    return _jnp().mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


# gather --------------------------------------------------------------------

@_op("Gather")
def _gather(attrs, x, idx):
    return _jnp().take(x, idx, axis=attrs.get("axis", 0))


@_op("GatherElements")
def _gather_elements(attrs, x, idx):
    return _jnp().take_along_axis(x, idx, axis=attrs.get("axis", 0))


@_op("GatherND")
def _gather_nd(attrs, x, idx):
    if attrs.get("batch_dims", 0):
        raise NotImplementedError("GatherND batch_dims")
    depth = idx.shape[-1]
    return x[tuple(_jnp().moveaxis(idx, -1, 0)[i] for i in range(depth))]


@_op("Constant")
def _constant(attrs):
    if "value" in attrs:
        return _jnp().asarray(attrs["value"])
    raise NotImplementedError("Constant without tensor value")


@_op("ConstantOfShape")
def _constant_of_shape(attrs, shape):
    val = attrs.get("value", np.zeros(1, np.float32))
    return _jnp().full([int(d) for d in np.asarray(shape)],
                       np.asarray(val).reshape(()).item(),
                       dtype=np.asarray(val).dtype)


# --------------------------------------------------------------------------

def _run_scan(attrs, vals, outer_env):
    """ONNX Scan via lax.scan: body subgraph nodes become the scan body;
    names not defined in the body resolve from the enclosing graph env
    (outer-scope captures, which lax treats as closure constants)."""
    import jax
    jnp = _jnp()
    from .serde import node_attrs as _na, to_array as _ta
    body = attrs["body"]
    n_scan = int(attrs["num_scan_inputs"])
    dirs = list(attrs.get("scan_input_directions", [])) or [0] * n_scan
    out_dirs = list(attrs.get("scan_output_directions", []))
    n_state = len(vals) - n_scan
    state0 = vals[:n_state]
    xs = vals[n_state:]
    if any(dirs):
        xs = [jnp.flip(x, 0) if d else x for x, d in zip(xs, dirs)]
    body_nodes = [(n.op_type, list(n.input), list(n.output), _na(n))
                  for n in body.node]
    body_inits = {t.name: _ta(t) for t in body.initializer}
    in_names = [vi.name for vi in body.input]
    out_names = [vi.name for vi in body.output]
    n_ys = len(out_names) - n_state

    def step(carry, x_slices):
        env = dict(outer_env)
        env.update(body_inits)
        for nm, v in zip(in_names[:n_state], carry):
            env[nm] = v
        for nm, v in zip(in_names[n_state:], x_slices):
            env[nm] = v
        for op_type, ins, outs, a in body_nodes:
            vv = [env[i] if i else None for i in ins]
            res = (_run_scan(a, vv, env) if op_type == "Scan"
                   else _OPS[op_type](a, *vv))
            if not isinstance(res, (list, tuple)):
                res = [res]
            for name, v in zip(outs, res):
                env[name] = v
        outs_v = [env[o] for o in out_names]
        return tuple(outs_v[:n_state]), tuple(outs_v[n_state:])

    final, ys = jax.lax.scan(step, tuple(state0), tuple(xs))
    ys = list(ys)
    for i, y in enumerate(ys):
        if i < len(out_dirs) and out_dirs[i]:
            ys[i] = jnp.flip(y, 0)
    return list(final) + ys


def make_fn(model, weights_override=None):
    """Build `fn(*inputs) -> list[jnp.ndarray]` from a ModelProto.

    `weights_override` replaces initializer values by name (static —
    folded into any jit of the returned fn, so shape-position
    initializers keep working)."""
    graph = model.graph
    weights = {t.name: to_array(t) for t in graph.initializer}
    for k, v in (weights_override or {}).items():
        if k not in weights:
            raise KeyError(f"no initializer named {k!r}")
        weights[k] = np.asarray(v)
    input_names = [vi.name for vi in graph.input
                   if vi.name not in weights]
    output_names = [vi.name for vi in graph.output]
    nodes = [(n.op_type, list(n.input), list(n.output), node_attrs(n))
             for n in graph.node]

    def _check_ops(node_list):
        for n in node_list:
            if n.op_type == "Scan":
                for a in n.attribute:
                    if a.name == "body":
                        _check_ops(a.g.node)  # validate subgraphs at load
            elif n.op_type not in _OPS:
                raise NotImplementedError(
                    f"ONNX op {n.op_type!r} unsupported")
    _check_ops(graph.node)

    def fn(*args, **kwargs):
        jnp = _jnp()
        # initializers stay as host numpy: shape/axes-position inputs must
        # be static under jit; tensor-position uses are folded as constants
        env = dict(weights)
        bound = dict(zip(input_names, args))
        bound.update(kwargs)
        for k in input_names:
            if k not in bound:
                raise ValueError(f"missing graph input {k!r}")
            env[k] = jnp.asarray(bound[k])
        for op_type, ins, outs, attrs in nodes:
            vals = [env[i] if i else None for i in ins]
            if op_type == "Scan":
                res = _run_scan(attrs, vals, env)
            else:
                res = _OPS[op_type](attrs, *vals)
            if not isinstance(res, (list, tuple)):
                res = [res]
            for name, v in zip(outs, res):
                env[name] = v
        return [env[o] for o in output_names]

    fn.input_names = input_names
    fn.output_names = output_names
    return fn

"""A jnp-backed ONNX graph evaluator.

Serves two roles: (1) `mx.onnx.import_model` — run third-party or exported
ONNX models inside the framework (the reference keeps its importer in
mx.contrib / onnx2mx, reference: python/mxnet/onnx/mx2onnx/_export_onnx.py
module docstring notes the paired direction), and (2) the round-trip oracle
for the exporter's tests: export -> parse -> evaluate -> compare with the
original TPU forward.

Supports the op subset the exporter emits plus common aliases (Relu,
Softmax, Gemm) so simple externally-produced models also load.  Evaluation
is jit-friendly: building `make_fn` returns a pure function of the graph
inputs that can be wrapped in jax.jit.
"""
from __future__ import annotations

import numpy as np

from . import serde
from .serde import node_attrs, np_dtype, to_array

_OPS = {}


def _op(*names):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn
    return deco


def _jnp():
    import jax.numpy as jnp
    return jnp


# elementwise ---------------------------------------------------------------

for _name, _fn in [
    ("Add", lambda a, b: a + b), ("Sub", lambda a, b: a - b),
    ("Mul", lambda a, b: a * b), ("Div", lambda a, b: a / b),
    ("Pow", lambda a, b: a ** b), ("Neg", lambda x: -x),
    ("Max", lambda *xs: _reduce_variadic("maximum", xs)),
    ("Min", lambda *xs: _reduce_variadic("minimum", xs)),
]:
    _OPS[_name] = (lambda f: (lambda attrs, *ins: f(*ins)))(_fn)


def _reduce_variadic(name, xs):
    jnp = _jnp()
    out = xs[0]
    for x in xs[1:]:
        out = getattr(jnp, name)(out, x)
    return out


def _unary(fname):
    def impl(attrs, x):
        jnp = _jnp()
        return getattr(jnp, fname)(x)
    return impl


for _o, _f in [("Exp", "exp"), ("Log", "log"), ("Tanh", "tanh"),
               ("Sqrt", "sqrt"), ("Abs", "abs"), ("Sign", "sign"),
               ("Floor", "floor"), ("Ceil", "ceil"),
               ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
               ("Atan", "arctan"), ("Asin", "arcsin"), ("Acos", "arccos"),
               ("Sinh", "sinh"), ("Cosh", "cosh"), ("Asinh", "arcsinh"),
               ("Acosh", "arccosh"), ("Atanh", "arctanh"),
               ("IsNaN", "isnan")]:
    _OPS[_o] = _unary(_f)


@_op("IsInf")
def _isinf(attrs, x):
    jnp = _jnp()
    pos = attrs.get("detect_positive", 1)
    neg = attrs.get("detect_negative", 1)
    if pos and neg:
        return jnp.isinf(x)
    if pos:
        return jnp.isposinf(x)
    if neg:
        return jnp.isneginf(x)
    return jnp.zeros_like(x, dtype=bool)   # spec: neither -> all False


@_op("Round")
def _round(attrs, x):
    return _jnp().round(x)


@_op("Reciprocal")
def _reciprocal(attrs, x):
    return 1.0 / x


@_op("Erf")
def _erf(attrs, x):
    import jax
    return jax.scipy.special.erf(x)


@_op("Sigmoid")
def _sigmoid(attrs, x):
    import jax
    return jax.nn.sigmoid(x)


@_op("Relu")
def _relu(attrs, x):
    return _jnp().maximum(x, 0)


@_op("Softmax")
def _softmax(attrs, x):
    import jax
    return jax.nn.softmax(x, axis=attrs.get("axis", -1))


@_op("Clip")
def _clip(attrs, x, lo=None, hi=None):
    jnp = _jnp()
    if lo is not None:
        x = jnp.maximum(x, lo)
    if hi is not None:
        x = jnp.minimum(x, hi)
    return x


@_op("Mod")
def _mod(attrs, a, b):
    jnp = _jnp()
    if attrs.get("fmod", 0):
        return jnp.fmod(a, b)
    return jnp.mod(a, b)


@_op("Identity")
def _identity(attrs, x):
    return x


@_op("Cast")
def _cast(attrs, x):
    return x.astype(np_dtype(attrs["to"]))


@_op("Where")
def _where(attrs, cond, a, b):
    return _jnp().where(cond, a, b)


for _o, _f in [("Equal", "equal"), ("Less", "less"),
               ("LessOrEqual", "less_equal"), ("Greater", "greater"),
               ("GreaterOrEqual", "greater_equal"),
               ("And", "logical_and"), ("Or", "logical_or"),
               ("Xor", "logical_xor")]:
    def _mk(f):
        return lambda attrs, a, b: getattr(_jnp(), f)(a, b)
    _OPS[_o] = _mk(_f)


@_op("Not")
def _not(attrs, x):
    return _jnp().logical_not(x)


# shape ---------------------------------------------------------------------

@_op("Reshape")
def _reshape(attrs, x, shape):
    return _jnp().reshape(x, [int(d) for d in np.asarray(shape)])


@_op("Transpose")
def _transpose(attrs, x):
    return _jnp().transpose(x, attrs.get("perm"))


@_op("Squeeze")
def _squeeze(attrs, x, axes=None):
    ax = tuple(int(a) for a in np.asarray(axes)) if axes is not None else None
    return _jnp().squeeze(x, axis=ax)


@_op("Unsqueeze")
def _unsqueeze(attrs, x, axes):
    return _jnp().expand_dims(x, tuple(int(a) for a in np.asarray(axes)))


@_op("Expand")
def _expand(attrs, x, shape):
    jnp = _jnp()
    target = [int(d) for d in np.asarray(shape)]
    # ONNX Expand uses numpy broadcasting vs the target shape
    return jnp.broadcast_to(x, jnp.broadcast_shapes(tuple(target),
                                                    x.shape))


@_op("Concat")
def _concat(attrs, *xs):
    return _jnp().concatenate(xs, axis=attrs["axis"])


@_op("Slice")
def _slice(attrs, x, starts, ends, axes=None, steps=None):
    starts = [int(v) for v in np.asarray(starts)]
    ends = [int(v) for v in np.asarray(ends)]
    axes = ([int(v) for v in np.asarray(axes)] if axes is not None
            else list(range(len(starts))))
    steps = ([int(v) for v in np.asarray(steps)] if steps is not None
             else [1] * len(starts))
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        # INT64_MIN end with negative step means "through element 0"
        if sp < 0 and en <= -(2 ** 62):
            en = None
        idx[ax] = slice(st, en, sp)
    return x[tuple(idx)]


@_op("Pad")
def _pad(attrs, x, pads, value=None):
    jnp = _jnp()
    pads = [int(v) for v in np.asarray(pads)]
    rank = x.ndim
    width = [(pads[i], pads[i + rank]) for i in range(rank)]
    cv = 0 if value is None else np.asarray(value).item()
    return jnp.pad(x, width, constant_values=cv)


@_op("Range")
def _range(attrs, start, limit, delta):
    return _jnp().arange(np.asarray(start).item(), np.asarray(limit).item(),
                         np.asarray(delta).item())


@_op("CumSum")
def _cumsum(attrs, x, axis):
    jnp = _jnp()
    ax = int(np.asarray(axis))
    exclusive = bool(attrs.get("exclusive", 0))
    if attrs.get("reverse", 0):
        x = jnp.flip(x, axis=ax)
    r = jnp.cumsum(x, axis=ax)
    if exclusive:
        r = r - x
    if attrs.get("reverse", 0):
        r = jnp.flip(r, axis=ax)
    return r


# reductions ----------------------------------------------------------------

def _reduce(fname):
    def impl(attrs, x, axes=None):
        jnp = _jnp()
        # axes arrive as an input (opset 13+ ReduceSum / opset 18+ others)
        # or as an attribute (older opsets); honor whichever is present
        if axes is not None:
            ax = tuple(int(a) for a in np.asarray(axes))
        else:
            ax = tuple(attrs["axes"]) if "axes" in attrs else None
        return getattr(jnp, fname)(x, axis=ax,
                                   keepdims=bool(attrs.get("keepdims", 1)))
    return impl


_OPS["ReduceSum"] = _reduce("sum")
_OPS["ReduceMax"] = _reduce("max")
_OPS["ReduceMin"] = _reduce("min")
_OPS["ReduceProd"] = _reduce("prod")
_OPS["ReduceMean"] = _reduce("mean")


@_op("Split")
def _split(attrs, x, sizes=None):
    jnp = _jnp()
    axis = attrs.get("axis", 0)
    sz = [int(s) for s in np.asarray(sizes).reshape(-1)]
    offs = np.cumsum([0] + sz)
    return [jnp.take(x, jnp.arange(offs[i], offs[i + 1]), axis=axis)
            for i in range(len(sz))]


@_op("Sign")
def _sign(attrs, x):
    return _jnp().sign(x)


@_op("Atan")
def _atan(attrs, x):
    return _jnp().arctan(x)


@_op("TopK")
def _topk(attrs, x, k):
    jnp = _jnp()
    k = int(np.asarray(k).reshape(-1)[0])
    axis = attrs.get("axis", -1)
    largest = attrs.get("largest", 1)
    sorted_ = attrs.get("sorted", 1)  # lax.top_k always sorts
    if axis not in (-1, x.ndim - 1):
        x_sw = jnp.moveaxis(x, axis, -1)
    else:
        x_sw = x
    src = x_sw if largest else -x_sw
    import jax
    vals, idx = jax.lax.top_k(src, k)
    if not largest:
        vals = -vals
    if axis not in (-1, x.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(np.int64)


@_op("ScatterND")
def _scatternd(attrs, data, indices, updates):
    jnp = _jnp()
    red = attrs.get("reduction", "none")
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    if red == "add":
        return data.at[idx].add(updates)
    if red in ("none", b"none", ""):
        return data.at[idx].set(updates)
    if red == "mul":
        return data.at[idx].multiply(updates)
    if red == "max":
        return data.at[idx].max(updates)
    if red == "min":
        return data.at[idx].min(updates)
    raise NotImplementedError(f"ScatterND reduction {red!r}")


@_op("ArgMax")
def _argmax(attrs, x):
    r = _jnp().argmax(x, axis=attrs.get("axis", 0))
    if attrs.get("keepdims", 1):
        r = _jnp().expand_dims(r, attrs.get("axis", 0))
    return r.astype(np.int64)


@_op("ArgMin")
def _argmin(attrs, x):
    r = _jnp().argmin(x, axis=attrs.get("axis", 0))
    if attrs.get("keepdims", 1):
        r = _jnp().expand_dims(r, attrs.get("axis", 0))
    return r.astype(np.int64)


# matmul / conv / pooling ---------------------------------------------------

@_op("MatMul")
def _matmul(attrs, a, b):
    return _jnp().matmul(a, b)


@_op("Einsum")
def _einsum(attrs, *xs):
    return _jnp().einsum(attrs["equation"], *xs)


@_op("Gemm")
def _gemm(attrs, a, b, c=None):
    jnp = _jnp()
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    out = alpha * jnp.matmul(a, b)
    if c is not None:
        out = out + beta * c
    return out


def _auto_pads(auto_pad, in_sizes, kernel, strides, dil):
    """ONNX auto_pad SAME_UPPER/SAME_LOWER -> per-dim (begin, end) pads:
    total = max((ceil(in/stride)-1)*stride + eff_kernel - in, 0); UPPER
    puts the odd unit at the end, LOWER at the beginning."""
    pads = []
    for i, size in enumerate(in_sizes):
        eff_k = dil[i] * (kernel[i] - 1) + 1
        out = -(-size // strides[i])          # ceil div
        total = max((out - 1) * strides[i] + eff_k - size, 0)
        lo = total // 2 if auto_pad in ("SAME_UPPER", b"SAME_UPPER") \
            else total - total // 2
        pads.append((lo, total - lo))
    return pads


def _norm_autopad(ap):
    return ap.decode() if isinstance(ap, bytes) else ap


@_op("Conv")
def _conv(attrs, x, w, b=None):
    import jax
    jnp = _jnp()
    nd = x.ndim - 2
    strides = attrs.get("strides", [1] * nd)
    dil = attrs.get("dilations", [1] * nd)
    group = attrs.get("group", 1)
    ap = _norm_autopad(attrs.get("auto_pad", "NOTSET"))
    if ap in ("SAME_UPPER", "SAME_LOWER"):
        padding = _auto_pads(ap, x.shape[2:], w.shape[2:], strides, dil)
    else:
        pads = attrs.get("pads", [0] * (2 * nd))
        padding = [(pads[i], pads[i + nd]) for i in range(nd)]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dil, feature_group_count=group)
    if b is not None:
        out = out + jnp.reshape(b, (1, -1) + (1,) * nd)
    return out


def _pool(reducer, init, x, attrs, average=False, count_include_pad=False):
    import jax
    jnp = _jnp()
    kernel = attrs["kernel_shape"]
    nd = len(kernel)
    strides = attrs.get("strides", [1] * nd)
    dil = attrs.get("dilations", [1] * nd)
    ap = _norm_autopad(attrs.get("auto_pad", "NOTSET"))
    if ap in ("SAME_UPPER", "SAME_LOWER"):
        spans = _auto_pads(ap, x.shape[2:], kernel, strides, dil)
    else:
        pads = attrs.get("pads", [0] * (2 * nd))
        spans = [(pads[i], pads[i + nd]) for i in range(nd)]
    # ceil_mode: extend the end so the last (partial) window fits; the
    # overhang cells count as identity for max and are excluded from the
    # average divisor (ONNX AveragePool spec)
    extras = []
    for i in range(nd):
        eff_k = dil[i] * (kernel[i] - 1) + 1
        span = x.shape[2 + i] + spans[i][0] + spans[i][1]
        if attrs.get("ceil_mode", 0):
            n_out = -(-(span - eff_k) // strides[i]) + 1
            # a window may not START in the end padding (ONNX/torch rule) —
            # otherwise AveragePool's divisor would be 0 for that window
            while n_out > 1 and (n_out - 1) * strides[i] >= \
                    x.shape[2 + i] + spans[i][0]:
                n_out -= 1
        else:
            n_out = (span - eff_k) // strides[i] + 1
        extras.append(max((n_out - 1) * strides[i] + eff_k - span, 0))
    window = (1, 1) + tuple(kernel)
    stride = (1, 1) + tuple(strides)
    dilation = (1, 1) + tuple(dil)
    padding = [(0, 0), (0, 0)] + [(b, e + x_) for (b, e), x_ in
                                  zip(spans, extras)]
    out = jax.lax.reduce_window(x, init, reducer, window, stride, padding,
                                window_dilation=dilation)
    if average:
        overhang = [(0, 0), (0, 0)] + [(0, x_) for x_ in extras]
        if count_include_pad:
            # divisor counts explicit pads but never the ceil overhang:
            # ones over the explicitly-padded extent, zero beyond it
            padded = x.shape[:2] + tuple(
                x.shape[2 + i] + spans[i][0] + spans[i][1]
                for i in range(nd))
            cnt = jax.lax.reduce_window(jnp.ones(padded, x.dtype), 0.0,
                                        jax.lax.add, window, stride,
                                        overhang, window_dilation=dilation)
        else:
            cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                        window, stride, padding,
                                        window_dilation=dilation)
        out = out / cnt
    return out


@_op("MaxPool")
def _maxpool(attrs, x):
    import jax
    init = (-np.inf if np.issubdtype(x.dtype, np.floating)
            else np.iinfo(x.dtype).min)
    return _pool(jax.lax.max, init, x, attrs)


@_op("AveragePool")
def _avgpool(attrs, x):
    import jax
    return _pool(jax.lax.add, 0.0, x, attrs, average=True,
                 count_include_pad=bool(attrs.get("count_include_pad", 0)))


@_op("GlobalAveragePool")
def _gap(attrs, x):
    return _jnp().mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


# gather --------------------------------------------------------------------

@_op("Gather")
def _gather(attrs, x, idx):
    return _jnp().take(x, idx, axis=attrs.get("axis", 0))


@_op("GatherElements")
def _gather_elements(attrs, x, idx):
    return _jnp().take_along_axis(x, idx, axis=attrs.get("axis", 0))


@_op("GatherND")
def _gather_nd(attrs, x, idx):
    if attrs.get("batch_dims", 0):
        raise NotImplementedError("GatherND batch_dims")
    depth = idx.shape[-1]
    return x[tuple(_jnp().moveaxis(idx, -1, 0)[i] for i in range(depth))]


# vision ops common in third-party detection/segmentation graphs ------------

@_op("Resize")
def _resize(attrs, x, roi=None, scales=None, sizes=None):
    """Reference analog: mx2onnx exports UpSampling/contrib.BilinearResize2D
    as Resize (_op_translations_opset13.py). Supported: mode nearest
    (floor/asymmetric and round_prefer_floor/half_pixel) and linear
    (half_pixel); the conventions the common exporters emit."""
    jnp = _jnp()
    mode = _norm_autopad(attrs.get("mode", "nearest"))
    ctm = _norm_autopad(
        attrs.get("coordinate_transformation_mode", "half_pixel"))
    nearest_mode = _norm_autopad(
        attrs.get("nearest_mode", "round_prefer_floor"))
    if sizes is not None:
        out_shape = [int(s) for s in np.asarray(sizes)]
        axis_scales = [o / d for o, d in zip(out_shape, x.shape)]
    else:
        axis_scales = [float(s) for s in np.asarray(scales)]
        out_shape = [int(np.floor(d * s))
                     for d, s in zip(x.shape, axis_scales)]
    if mode == "linear":
        if ctm not in ("half_pixel", "pytorch_half_pixel"):
            raise NotImplementedError(f"Resize linear with {ctm}")
        import jax
        return jax.image.resize(x, out_shape, method="linear",
                                antialias=False).astype(x.dtype)
    if mode != "nearest":
        raise NotImplementedError(f"Resize mode {mode!r}")
    out = x
    for ax in range(x.ndim):
        if out_shape[ax] == out.shape[ax]:
            continue
        # the ORIGINAL scale drives coordinate mapping (spec: floor(d*s)
        # output size but src = pos/s), not out/in
        scale = axis_scales[ax]
        pos = jnp.arange(out_shape[ax], dtype=jnp.float32)
        if ctm == "asymmetric":
            src = pos / scale
        elif ctm in ("half_pixel", "pytorch_half_pixel"):
            src = (pos + 0.5) / scale - 0.5
        elif ctm == "align_corners":
            src = pos * (x.shape[ax] - 1) / max(out_shape[ax] - 1, 1)
        else:
            raise NotImplementedError(f"Resize nearest with {ctm}")
        if nearest_mode == "floor":
            idx = jnp.floor(src)
        elif nearest_mode == "ceil":
            idx = jnp.ceil(src)
        elif nearest_mode == "round_prefer_ceil":
            idx = jnp.floor(src + 0.5)
        else:  # round_prefer_floor
            idx = jnp.ceil(src - 0.5)
        idx = jnp.clip(idx, 0, x.shape[ax] - 1).astype(jnp.int32)
        out = jnp.take(out, idx, axis=ax)
    return out


@_op("NonMaxSuppression")
def _nms(attrs, boxes, scores, max_output_boxes_per_class=None,
         iou_threshold=None, score_threshold=None):
    """Classic per-class greedy NMS (host-side: the output shape is
    data-dependent, so this op is eager-only — like the reference's
    _contrib_box_nms import path). boxes (N,B,4), scores (N,C,B);
    returns (K, 3) int64 [batch, class, box]."""
    b = np.asarray(boxes)
    s = np.asarray(scores)
    max_out = (int(np.asarray(max_output_boxes_per_class))
               if max_output_boxes_per_class is not None else 0)
    if max_out == 0:
        # spec: 0 (or absent) means "no output produced"
        return _jnp().zeros((0, 3), np.int64)
    iou_t = (float(np.asarray(iou_threshold))
             if iou_threshold is not None else 0.0)
    score_t = (float(np.asarray(score_threshold))
               if score_threshold is not None else -np.inf)
    center = attrs.get("center_point_box", 0)
    sel = []
    for n in range(b.shape[0]):
        if center:
            cx, cy, w, h = (b[n, :, 0], b[n, :, 1], b[n, :, 2], b[n, :, 3])
            y1, x1 = cy - h / 2, cx - w / 2
            y2, x2 = cy + h / 2, cx + w / 2
        else:
            y1, x1, y2, x2 = (b[n, :, 0], b[n, :, 1], b[n, :, 2], b[n, :, 3])
            y1, y2 = np.minimum(y1, y2), np.maximum(y1, y2)
            x1, x2 = np.minimum(x1, x2), np.maximum(x1, x2)
        area = (y2 - y1) * (x2 - x1)
        for c in range(s.shape[1]):
            order = np.argsort(-s[n, c])
            order = order[s[n, c][order] > score_t]
            keep = []
            while order.size and len(keep) < max_out:
                i = order[0]
                keep.append(i)
                rest = order[1:]
                yy1 = np.maximum(y1[i], y1[rest])
                xx1 = np.maximum(x1[i], x1[rest])
                yy2 = np.minimum(y2[i], y2[rest])
                xx2 = np.minimum(x2[i], x2[rest])
                inter = (np.maximum(yy2 - yy1, 0)
                         * np.maximum(xx2 - xx1, 0))
                iou = inter / (area[i] + area[rest] - inter + 1e-12)
                order = rest[iou <= iou_t]
            sel.extend((n, c, int(i)) for i in keep)
    return _jnp().asarray(np.array(sel, np.int64).reshape(-1, 3))


@_op("RoiAlign")
def _roi_align(attrs, x, rois, batch_indices):
    """RoiAlign (reference export path: _contrib_ROIAlign ->
    _op_translations). Bilinear-sampled average/max pooling per ROI bin;
    vectorized gathers like ops/deformable.py. sampling_ratio=0 (adaptive)
    needs concrete rois, so it is eager-only."""
    jnp = _jnp()
    oh = attrs.get("output_height", 1)
    ow = attrs.get("output_width", 1)
    sratio = attrs.get("sampling_ratio", 0)
    scale = attrs.get("spatial_scale", 1.0)
    mode = _norm_autopad(attrs.get("mode", "avg"))
    # the attribute only exists from opset 16 (default half_pixel there);
    # opset 10-15 graphs have no 0.5 offset — make_fn injects __opset__
    default_ctm = ("half_pixel" if attrs.get("__opset__", 17) >= 16
                   else "output_half_pixel")
    offset = 0.5 if _norm_autopad(
        attrs.get("coordinate_transformation_mode", default_ctm)) \
        == "half_pixel" else 0.0
    N, C, H, W = x.shape
    r = np.asarray(rois).astype(np.float64) * scale - offset
    nroi = r.shape[0]
    if sratio <= 0:
        rh = max(1, int(np.ceil(np.max(
            (r[:, 3] - r[:, 1]) / oh)))) if nroi else 1
        rw = max(1, int(np.ceil(np.max(
            (r[:, 2] - r[:, 0]) / ow)))) if nroi else 1
    else:
        rh = rw = int(sratio)
    # sample grid: per roi/bin, rh x rw bilinear samples
    bh = (r[:, 3] - r[:, 1]) / oh          # (R,) bin heights
    bw = (r[:, 2] - r[:, 0]) / ow
    iy = (np.arange(rh) + 0.5) / rh        # (rh,) in-bin fractions
    ix = (np.arange(rw) + 0.5) / rw
    ys = (r[:, 1, None, None] + (np.arange(oh)[None, :, None] +
                                 iy[None, None, :]) * bh[:, None, None])
    xs = (r[:, 0, None, None] + (np.arange(ow)[None, :, None] +
                                 ix[None, None, :]) * bw[:, None, None])
    ys = jnp.asarray(ys)                   # (R, oh, rh)
    xs = jnp.asarray(xs)                   # (R, ow, rw)
    y = ys[:, :, :, None, None]            # (R, oh, rh, 1, 1)
    xx = xs[:, None, None, :, :]           # (R, 1, 1, ow, rw)
    y0, x0 = jnp.floor(y), jnp.floor(xx)
    wy1, wx1 = y - y0, xx - x0
    xg = x.reshape(N, C, H * W)
    bi = np.asarray(batch_indices).astype(np.int32)
    xg = jnp.take(xg, jnp.asarray(bi), axis=0)   # (R, C, H*W)

    def corner(cy, cx):
        inside = ((cy >= 0) & (cy < H) & (cx >= 0) & (cx < W))
        idx = (jnp.clip(cy, 0, H - 1).astype(jnp.int32) * W
               + jnp.clip(cx, 0, W - 1).astype(jnp.int32))
        idx = jnp.broadcast_to(idx, (nroi, oh, rh, ow, rw))
        flat = idx.reshape(nroi, 1, -1)
        v = jnp.take_along_axis(
            xg, jnp.broadcast_to(flat, (nroi, C, flat.shape[-1])), axis=-1)
        v = v.reshape(nroi, C, oh, rh, ow, rw)
        m = jnp.broadcast_to(inside, (nroi, oh, rh, ow, rw))
        return v * m[:, None].astype(x.dtype)

    w00 = ((1 - wy1) * (1 - wx1)).astype(x.dtype)
    w01 = ((1 - wy1) * wx1).astype(x.dtype)
    w10 = (wy1 * (1 - wx1)).astype(x.dtype)
    w11 = (wy1 * wx1).astype(x.dtype)
    sampled = (corner(y0, x0) * w00[:, None] + corner(y0, x0 + 1) * w01[:, None]
               + corner(y0 + 1, x0) * w10[:, None]
               + corner(y0 + 1, x0 + 1) * w11[:, None])
    if mode == "max":
        return sampled.max(axis=(3, 5))
    return sampled.mean(axis=(3, 5))


@_op("Constant")
def _constant(attrs):
    if "value" in attrs:
        return _jnp().asarray(attrs["value"])
    raise NotImplementedError("Constant without tensor value")


@_op("ConstantOfShape")
def _constant_of_shape(attrs, shape):
    val = attrs.get("value", np.zeros(1, np.float32))
    return _jnp().full([int(d) for d in np.asarray(shape)],
                       np.asarray(val).reshape(()).item(),
                       dtype=np.asarray(val).dtype)


# --------------------------------------------------------------------------

def _run_scan(attrs, vals, outer_env):
    """ONNX Scan via lax.scan: body subgraph nodes become the scan body;
    names not defined in the body resolve from the enclosing graph env
    (outer-scope captures, which lax treats as closure constants)."""
    import jax
    jnp = _jnp()
    from .serde import node_attrs as _na, to_array as _ta
    body = attrs["body"]
    n_scan = int(attrs["num_scan_inputs"])
    dirs = list(attrs.get("scan_input_directions", [])) or [0] * n_scan
    out_dirs = list(attrs.get("scan_output_directions", []))
    n_state = len(vals) - n_scan
    state0 = vals[:n_state]
    xs = vals[n_state:]
    if any(dirs):
        xs = [jnp.flip(x, 0) if d else x for x, d in zip(xs, dirs)]
    body_nodes = [(n.op_type, list(n.input), list(n.output), _na(n))
                  for n in body.node]
    body_inits = {t.name: _ta(t) for t in body.initializer}
    in_names = [vi.name for vi in body.input]
    out_names = [vi.name for vi in body.output]
    n_ys = len(out_names) - n_state

    def step(carry, x_slices):
        env = dict(outer_env)
        env.update(body_inits)
        for nm, v in zip(in_names[:n_state], carry):
            env[nm] = v
        for nm, v in zip(in_names[n_state:], x_slices):
            env[nm] = v
        for op_type, ins, outs, a in body_nodes:
            vv = [env[i] if i else None for i in ins]
            res = (_run_scan(a, vv, env) if op_type == "Scan"
                   else _OPS[op_type](a, *vv))
            if not isinstance(res, (list, tuple)):
                res = [res]
            for name, v in zip(outs, res):
                env[name] = v
        outs_v = [env[o] for o in out_names]
        return tuple(outs_v[:n_state]), tuple(outs_v[n_state:])

    final, ys = jax.lax.scan(step, tuple(state0), tuple(xs))
    ys = list(ys)
    for i, y in enumerate(ys):
        if i < len(out_dirs) and out_dirs[i]:
            ys[i] = jnp.flip(y, 0)
    return list(final) + ys


def make_fn(model, weights_override=None):
    """Build `fn(*inputs) -> list[jnp.ndarray]` from a ModelProto.

    `weights_override` replaces initializer values by name (static —
    folded into any jit of the returned fn, so shape-position
    initializers keep working)."""
    graph = model.graph
    weights = {t.name: to_array(t) for t in graph.initializer}
    for k, v in (weights_override or {}).items():
        if k not in weights:
            raise KeyError(f"no initializer named {k!r}")
        weights[k] = np.asarray(v)
    input_names = [vi.name for vi in graph.input
                   if vi.name not in weights]
    output_names = [vi.name for vi in graph.output]
    opset = 17
    for oi in getattr(model, "opset_import", []):
        if getattr(oi, "domain", "") in ("", "ai.onnx"):
            opset = oi.version or opset
    nodes = [(n.op_type, list(n.input), list(n.output),
              dict(node_attrs(n), __opset__=opset))
             for n in graph.node]

    def _check_ops(node_list):
        for n in node_list:
            if n.op_type == "Scan":
                for a in n.attribute:
                    if a.name == "body":
                        _check_ops(a.g.node)  # validate subgraphs at load
            elif n.op_type not in _OPS:
                raise NotImplementedError(
                    f"ONNX op {n.op_type!r} unsupported")
    _check_ops(graph.node)

    def fn(*args, **kwargs):
        jnp = _jnp()
        # initializers stay as host numpy: shape/axes-position inputs must
        # be static under jit; tensor-position uses are folded as constants
        env = dict(weights)
        bound = dict(zip(input_names, args))
        bound.update(kwargs)
        for k in input_names:
            if k not in bound:
                raise ValueError(f"missing graph input {k!r}")
            env[k] = jnp.asarray(bound[k])
        for op_type, ins, outs, attrs in nodes:
            vals = [env[i] if i else None for i in ins]
            if op_type == "Scan":
                res = _run_scan(attrs, vals, env)
            else:
                res = _OPS[op_type](attrs, *vals)
            if not isinstance(res, (list, tuple)):
                res = [res]
            for name, v in zip(outs, res):
                env[name] = v
        return [env[o] for o in output_names]

    fn.input_names = input_names
    fn.output_names = output_names
    return fn

"""jaxpr -> ONNX graph translation.

The TPU-native analog of the reference exporter
(reference: python/mxnet/onnx/mx2onnx/_export_onnx.py MXNetGraph.create_onnx_graph_proto,
with ~200 per-op translations under mx2onnx/_op_translations/).  The
reference walks an NNVM symbol graph node by node; here the source of truth
is what actually executes on TPU — the jaxpr traced from a HybridBlock's
forward — and each lax primitive has an ONNX translation.  Sub-jaxprs
(jit/custom_jvp/remat) are inlined, RNG plumbing is removed by dead-code
elimination of the eval-mode trace.

Opset 17 semantics throughout (ReduceSum takes axes as input; ReduceMax/
Min/Prod as attribute).
"""
from __future__ import annotations

import numpy as np

from . import serde
from .serde import make_node, make_tensor, make_value_info, onnx_dtype

_INT64_MIN = -(2 ** 63)
_INT64_MAX = 2 ** 63 - 1


# --------------------------------------------------------------------------
# dead-code elimination (our own, over the public jaxpr datatypes)
# --------------------------------------------------------------------------

def _dce(jaxpr):
    """Drop equations whose outputs are never used (e.g. the RNG key
    plumbing traced by functional_call in eval mode)."""
    from jax.extend import core as jcore  # Literal/Var types
    needed = {v for v in jaxpr.outvars if not isinstance(v, jcore.Literal)}
    keep = []
    for eqn in reversed(jaxpr.eqns):
        if any(v in needed for v in eqn.outvars):
            keep.append(eqn)
            for v in eqn.invars:
                if not isinstance(v, jcore.Literal):
                    needed.add(v)
    keep.reverse()
    return jaxpr.replace(eqns=keep)


# --------------------------------------------------------------------------
# translation context
# --------------------------------------------------------------------------

class _Ctx:
    def __init__(self):
        self.nodes = []
        self.initializers = {}
        self._n = 0
        self.env = {}           # jax Var -> onnx value name

    def fresh(self, prefix="t"):
        self._n += 1
        return f"{prefix}_{self._n}"

    def node(self, op, inputs, n_out=1, out=None, **attrs):
        outs = out if out is not None \
            else [self.fresh(op.lower()) for _ in range(n_out)]
        if isinstance(outs, str):
            outs = [outs]
        self.nodes.append(make_node(op, list(inputs), outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def const(self, array, name=None):
        arr = np.asarray(array)
        name = name or self.fresh("const")
        self.initializers[name] = make_tensor(name, arr)
        return name

    def i64(self, values):
        return self.const(np.asarray(values, np.int64))

    def name_of(self, atom):
        from jax.extend import core as jcore
        if isinstance(atom, jcore.Literal):
            return self.const(np.asarray(atom.val, atom.aval.dtype))
        return self.env[atom]

    def bind(self, var, name):
        self.env[var] = name


def _shape(atom):
    return tuple(atom.aval.shape)


def _dtype(atom):
    return atom.aval.dtype


# --------------------------------------------------------------------------
# primitive handlers
# --------------------------------------------------------------------------

_HANDLERS = {}


def _reg(*names):
    def deco(fn):
        for n in names:
            _HANDLERS[n] = fn
        return fn
    return deco


def _simple(onnx_op):
    def h(ctx, eqn, ins, out):
        ctx.node(onnx_op, ins, out=out)
    return h


for _lax, _onnx in [
    ("add", "Add"), ("add_any", "Add"), ("sub", "Sub"), ("mul", "Mul"),
    ("div", "Div"), ("max", "Max"), ("min", "Min"), ("pow", "Pow"),
    ("neg", "Neg"), ("exp", "Exp"), ("log", "Log"), ("tanh", "Tanh"),
    ("logistic", "Sigmoid"), ("erf", "Erf"), ("sqrt", "Sqrt"),
    ("abs", "Abs"), ("sign", "Sign"), ("floor", "Floor"),
    ("ceil", "Ceil"), ("round", "Round"),
    ("sin", "Sin"), ("cos", "Cos"), ("tan", "Tan"), ("atan", "Atan"),
    ("asin", "Asin"), ("acos", "Acos"), ("sinh", "Sinh"), ("cosh", "Cosh"),
    ("asinh", "Asinh"), ("acosh", "Acosh"), ("atanh", "Atanh"),
    ("eq", "Equal"), ("lt", "Less"), ("le", "LessOrEqual"),
    ("gt", "Greater"), ("ge", "GreaterOrEqual"),
    ("and", "And"), ("or", "Or"), ("xor", "Xor"), ("not", "Not"),
    ("copy", "Identity"), ("stop_gradient", "Identity"),
]:
    if _onnx:
        _reg(_lax)(_simple(_onnx))


@_reg("rsqrt")
def _rsqrt(ctx, eqn, ins, out):
    s = ctx.node("Sqrt", ins)
    ctx.node("Reciprocal", [s], out=out)


@_reg("square")
def _square(ctx, eqn, ins, out):
    ctx.node("Mul", [ins[0], ins[0]], out=out)


@_reg("erfc")
def _erfc(ctx, eqn, ins, out):
    one = ctx.const(np.asarray(1, _dtype(eqn.invars[0])))
    e = ctx.node("Erf", ins)
    ctx.node("Sub", [one, e], out=out)


@_reg("log1p")
def _log1p(ctx, eqn, ins, out):
    one = ctx.const(np.asarray(1, _dtype(eqn.invars[0])))
    ctx.node("Log", [ctx.node("Add", [ins[0], one])], out=out)


@_reg("expm1")
def _expm1(ctx, eqn, ins, out):
    one = ctx.const(np.asarray(1, _dtype(eqn.invars[0])))
    ctx.node("Sub", [ctx.node("Exp", ins), one], out=out)


@_reg("ne")
def _ne(ctx, eqn, ins, out):
    ctx.node("Not", [ctx.node("Equal", ins)], out=out)


@_reg("exp2")
def _exp2(ctx, eqn, ins, out):
    two = ctx.const(np.asarray(2, _dtype(eqn.invars[0])))
    ctx.node("Pow", [two, ins[0]], out=out)


@_reg("cbrt")
def _cbrt(ctx, eqn, ins, out):
    # sign-preserving cube root: sign(x) * |x|^(1/3)
    third = ctx.const(np.asarray(1.0 / 3.0, _dtype(eqn.invars[0])))
    mag = ctx.node("Pow", [ctx.node("Abs", ins), third])
    ctx.node("Mul", [ctx.node("Sign", ins), mag], out=out)


@_reg("is_finite")
def _is_finite(ctx, eqn, ins, out):
    # opset-17 IsInf/IsNaN only accept f32/f64 (16-bit support is opset
    # 20); cast first so fp16/bf16 AMP graphs stay spec-valid
    x = ins[0]
    if np.dtype(_dtype(eqn.invars[0])).itemsize < 4:
        x = ctx.node("Cast", [x], to=onnx_dtype(np.float32))
    inf = ctx.node("IsInf", [x])
    nan = ctx.node("IsNaN", [x])
    ctx.node("Not", [ctx.node("Or", [inf, nan])], out=out)


@_reg("rem")
def _rem(ctx, eqn, ins, out):
    ctx.node("Mod", ins, out=out, fmod=1)


@_reg("clamp")
def _clamp(ctx, eqn, ins, out):
    lo, x, hi = ins
    ctx.node("Clip", [x, lo, hi], out=out)


@_reg("integer_pow")
def _integer_pow(ctx, eqn, ins, out):
    y = eqn.params["y"]
    exp = ctx.const(np.asarray(y, _dtype(eqn.invars[0])))
    ctx.node("Pow", [ins[0], exp], out=out)


@_reg("convert_element_type")
def _convert(ctx, eqn, ins, out):
    ctx.node("Cast", ins, out=out,
             to=onnx_dtype(eqn.params["new_dtype"]))


@_reg("select_n")
def _select_n(ctx, eqn, ins, out):
    pred, *cases = ins
    if len(cases) != 2 or _dtype(eqn.invars[0]) != np.bool_:
        raise NotImplementedError(
            f"select_n with {len(cases)} cases / non-bool predicate")
    # select_n: False -> cases[0], True -> cases[1]; Where picks X when cond
    ctx.node("Where", [pred, cases[1], cases[0]], out=out)


@_reg("transpose")
def _transpose(ctx, eqn, ins, out):
    ctx.node("Transpose", ins, out=out,
             perm=list(eqn.params["permutation"]))


@_reg("reshape")
def _reshape(ctx, eqn, ins, out):
    x = ins[0]
    dims = eqn.params.get("dimensions")
    if dims is not None:
        x = ctx.node("Transpose", [x], perm=list(dims))
    shape = ctx.i64(eqn.params["new_sizes"])
    ctx.node("Reshape", [x, shape], out=out)


@_reg("squeeze")
def _squeeze(ctx, eqn, ins, out):
    axes = ctx.i64(eqn.params["dimensions"])
    ctx.node("Squeeze", [ins[0], axes], out=out)


@_reg("expand_dims")
def _expand_dims(ctx, eqn, ins, out):
    axes = ctx.i64(eqn.params["dimensions"])
    ctx.node("Unsqueeze", [ins[0], axes], out=out)


@_reg("broadcast_in_dim")
def _broadcast_in_dim(ctx, eqn, ins, out):
    shape = tuple(eqn.params["shape"])
    bdims = tuple(eqn.params["broadcast_dimensions"])
    in_shape = _shape(eqn.invars[0])
    mid = [1] * len(shape)
    for src, dst in enumerate(bdims):
        mid[dst] = in_shape[src]
    x = ins[0]
    if tuple(mid) != in_shape:
        x = ctx.node("Reshape", [x, ctx.i64(mid)])
    if tuple(mid) == shape:
        ctx.node("Identity", [x], out=out)
    else:
        ctx.node("Expand", [x, ctx.i64(shape)], out=out)


@_reg("concatenate")
def _concat(ctx, eqn, ins, out):
    ctx.node("Concat", ins, out=out, axis=int(eqn.params["dimension"]))


@_reg("slice")
def _slice(ctx, eqn, ins, out):
    p = eqn.params
    rank = len(_shape(eqn.invars[0]))
    strides = p["strides"] or (1,) * rank
    ctx.node("Slice", [ins[0], ctx.i64(p["start_indices"]),
                       ctx.i64(p["limit_indices"]), ctx.i64(range(rank)),
                       ctx.i64(strides)], out=out)


@_reg("rev")
def _rev(ctx, eqn, ins, out):
    dims = list(eqn.params["dimensions"])
    n = len(dims)
    ctx.node("Slice", [ins[0], ctx.i64([-1] * n),
                       ctx.i64([_INT64_MIN] * n), ctx.i64(dims),
                       ctx.i64([-1] * n)], out=out)


@_reg("dynamic_slice")
def _dynamic_slice(ctx, eqn, ins, out):
    operand, *starts = ins
    sizes = list(eqn.params["slice_sizes"])
    op_shape = _shape(eqn.invars[0])
    rank = len(sizes)
    axes_one = ctx.i64([0])
    starts64 = [ctx.node("Cast", [ctx.node("Unsqueeze", [s, axes_one])],
                         to=onnx_dtype(np.int64)) for s in starts]
    start_vec = (starts64[0] if rank == 1
                 else ctx.node("Concat", starts64, axis=0))
    # lax.dynamic_slice clamps start into [0, dim - size]; ONNX Slice
    # clamps the end instead, so reproduce the start clamp explicitly
    max_start = ctx.i64([d - s for d, s in zip(op_shape, sizes)])
    start_vec = ctx.node("Max", [start_vec, ctx.i64([0] * rank)])
    start_vec = ctx.node("Min", [start_vec, max_start])
    ends = ctx.node("Add", [start_vec, ctx.i64(sizes)])
    ctx.node("Slice", [operand, start_vec, ends, ctx.i64(range(rank))],
             out=out)


@_reg("pad")
def _pad(ctx, eqn, ins, out):
    operand, value = ins
    cfg = eqn.params["padding_config"]
    if any(i != 0 for _, _, i in cfg):
        raise NotImplementedError("interior (dilating) pad")
    rank = len(cfg)
    lo = [max(p, 0) for p, _, _ in cfg]
    hi = [max(p, 0) for _, p, _ in cfg]
    x = operand
    if any(lo) or any(hi):
        x = ctx.node("Pad", [x, ctx.i64(lo + hi), value], mode="constant")
    if any(p < 0 for p, _, _ in cfg) or any(p < 0 for _, p, _ in cfg):
        starts = [max(-p, 0) for p, _, _ in cfg]
        ends = [s + d for s, d in zip(starts, _shape(eqn.outvars[0]))]
        x = ctx.node("Slice", [x, ctx.i64(starts), ctx.i64(ends),
                               ctx.i64(range(rank))])
    ctx.node("Identity", [x], out=out)


@_reg("iota")
def _iota(ctx, eqn, ins, out):
    # Range (+ Reshape/Expand) instead of a baked constant: a broadcast
    # iota over a large shape must not bloat the exported file
    p = eqn.params
    shape = tuple(p["shape"])
    dim = p["dimension"]
    dt = np.dtype(p["dtype"])
    # ONNX Range only supports float/double/int16/int32/int64: generate in
    # the target dtype for signed int/float >= 32-bit, else int64 + Cast
    # (unsigned dtypes in particular must go through the Cast path)
    gen_dt = dt if dt.kind in "if" and dt.itemsize >= 4 else np.int64
    r = ctx.node("Range", [ctx.const(np.asarray(0, gen_dt)),
                           ctx.const(np.asarray(shape[dim], gen_dt)),
                           ctx.const(np.asarray(1, gen_dt))])
    if gen_dt != dt:
        r = ctx.node("Cast", [r], to=onnx_dtype(dt))
    if len(shape) > 1:
        mid = [1] * len(shape)
        mid[dim] = shape[dim]
        r = ctx.node("Reshape", [r, ctx.i64(mid)])
        r = ctx.node("Expand", [r, ctx.i64(shape)])
    ctx.node("Identity", [r], out=out)


@_reg("cumsum")
def _cumsum(ctx, eqn, ins, out):
    axis = ctx.const(np.asarray(eqn.params["axis"], np.int64))
    ctx.node("CumSum", [ins[0], axis], out=out,
             reverse=int(eqn.params.get("reverse", False)))


def _reduce(onnx_op, axes_as_input):
    def h(ctx, eqn, ins, out):
        axes = list(eqn.params["axes"])
        if axes_as_input:
            ctx.node(onnx_op, [ins[0], ctx.i64(axes)], out=out, keepdims=0)
        else:
            ctx.node(onnx_op, ins, out=out, axes=axes, keepdims=0)
    return h


_reg("reduce_sum")(_reduce("ReduceSum", True))
_reg("reduce_max")(_reduce("ReduceMax", False))
_reg("reduce_min")(_reduce("ReduceMin", False))
_reg("reduce_prod")(_reduce("ReduceProd", False))


@_reg("reduce_and", "reduce_or")
def _reduce_bool(ctx, eqn, ins, out):
    op = "ReduceMin" if eqn.primitive.name == "reduce_and" else "ReduceMax"
    x = ctx.node("Cast", ins, to=onnx_dtype(np.int32))
    r = ctx.node(op, [x], axes=list(eqn.params["axes"]), keepdims=0)
    ctx.node("Cast", [r], to=onnx_dtype(np.bool_), out=out)


@_reg("argmax", "argmin")
def _argminmax(ctx, eqn, ins, out):
    op = "ArgMax" if eqn.primitive.name == "argmax" else "ArgMin"
    (axis,) = eqn.params["axes"]
    r = ctx.node(op, ins, axis=int(axis), keepdims=0)
    want = eqn.params["index_dtype"]
    if np.dtype(want) != np.int64:
        ctx.node("Cast", [r], to=onnx_dtype(want), out=out)
    else:
        ctx.node("Identity", [r], out=out)


@_reg("dot_general")
def _dot_general(ctx, eqn, ins, out):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[:2]
    lr, rr = len(_shape(lhs)), len(_shape(rhs))
    out_dtype = _dtype(eqn.outvars[0])
    a, b = ins
    # cast inputs when XLA would accumulate in a wider type
    # (preferred_element_type); ONNX matmul has no accumulator control.
    if _dtype(lhs) != out_dtype:
        a = ctx.node("Cast", [a], to=onnx_dtype(out_dtype))
    if _dtype(rhs) != out_dtype:
        b = ctx.node("Cast", [b], to=onnx_dtype(out_dtype))
    if (lr == 2 and rr == 2 and lb == () and lc == (1,) and rc == (0,)):
        ctx.node("MatMul", [a, b], out=out)
        return
    # general case: Einsum (opset 12+), equation built from dimension_numbers
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    l_sub = [None] * lr
    r_sub = [None] * rr
    batch = []
    for i, j in zip(lb, rb):
        c = next(letters)
        l_sub[i] = r_sub[j] = c
        batch.append(c)
    for i, j in zip(lc, rc):
        c = next(letters)
        l_sub[i] = r_sub[j] = c
    l_free = []
    for i in range(lr):
        if l_sub[i] is None:
            l_sub[i] = next(letters)
            l_free.append(l_sub[i])
    r_free = []
    for j in range(rr):
        if r_sub[j] is None:
            r_sub[j] = next(letters)
            r_free.append(r_sub[j])
    eq = f"{''.join(l_sub)},{''.join(r_sub)}->" \
         f"{''.join(batch + l_free + r_free)}"
    ctx.node("Einsum", [a, b], out=out, equation=eq)


@_reg("conv_general_dilated")
def _conv(ctx, eqn, ins, out):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed convolution (lhs_dilation)")
    if p.get("batch_group_count", 1) != 1:
        raise NotImplementedError("batch_group_count != 1")
    nd = len(p["window_strides"])
    x, w = ins
    # transpose input to NCHW if its spec is not already (N, C, spatial...)
    if tuple(lhs_spec) != tuple(range(nd + 2)):
        x = ctx.node("Transpose", [x], perm=list(lhs_spec))
    if tuple(rhs_spec) != tuple(range(nd + 2)):
        w = ctx.node("Transpose", [w], perm=list(rhs_spec))
    pads = [lo for lo, _ in p["padding"]] + [hi for _, hi in p["padding"]]
    conv = ctx.node("Conv", [x, w],
                    strides=list(p["window_strides"]),
                    dilations=list(p["rhs_dilation"]),
                    group=int(p["feature_group_count"]),
                    pads=pads)
    if tuple(out_spec) != tuple(range(nd + 2)):
        inv = [0] * (nd + 2)
        for i, d in enumerate(out_spec):
            inv[d] = i
        ctx.node("Transpose", [conv], perm=inv, out=out)
    else:
        ctx.node("Identity", [conv], out=out)


def _window_attrs(eqn):
    p = eqn.params
    wd = tuple(p["window_dimensions"])
    ws = tuple(p["window_strides"])
    pad = tuple(p["padding"])
    if any(d != 1 for d in p.get("base_dilation", (1,) * len(wd))):
        raise NotImplementedError("base_dilation in pooling")
    if wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError("pooling window over batch/channel dims")
    kernel = list(wd[2:])
    strides = list(ws[2:])
    pads = [lo for lo, _ in pad[2:]] + [hi for _, hi in pad[2:]]
    dil = list(p.get("window_dilation", (1,) * len(wd))[2:])
    return kernel, strides, pads, dil


@_reg("reduce_window_max")
def _maxpool(ctx, eqn, ins, out):
    kernel, strides, pads, dil = _window_attrs(eqn)
    ctx.node("MaxPool", ins, out=out, kernel_shape=kernel,
             strides=strides, pads=pads, dilations=dil)


@_reg("reduce_window_sum")
def _sumpool(ctx, eqn, ins, out):
    kernel, strides, pads, dil = _window_attrs(eqn)
    if any(d != 1 for d in dil):
        raise NotImplementedError("window_dilation in sum-pooling")
    avg = ctx.node("AveragePool", ins, kernel_shape=kernel,
                   strides=strides, pads=pads, count_include_pad=1)
    count = ctx.const(np.asarray(float(np.prod(kernel)),
                                 _dtype(eqn.invars[0])))
    ctx.node("Mul", [avg, count], out=out)


@_reg("gather")
def _gather(ctx, eqn, ins, out):
    p = eqn.params
    dn = p["dimension_numbers"]
    operand, indices = eqn.invars[:2]
    op_shape = _shape(operand)
    idx_shape = _shape(indices)
    slice_sizes = tuple(p["slice_sizes"])
    rank = len(op_shape)
    x, idx = ins
    if _dtype(indices) != np.int64:
        idx = ctx.node("Cast", [idx], to=onnx_dtype(np.int64))

    offset = tuple(dn.offset_dims)
    collapsed = tuple(dn.collapsed_slice_dims)
    start_map = tuple(dn.start_index_map)
    ob = tuple(getattr(dn, "operand_batching_dims", ()))
    sb = tuple(getattr(dn, "start_indices_batching_dims", ()))

    # Pattern B: take_along_axis -> GatherElements (+ layout transposes)
    if (ob and offset == () and len(collapsed) == 1
            and start_map == collapsed
            and all(s == 1 for s in slice_sizes)
            and ob == tuple(d for d in range(rank) if d != collapsed[0])
            and sb == tuple(range(len(ob)))):
        axis = collapsed[0]
        out_shape = idx_shape[:-1]
        idx2 = ctx.node("Reshape", [idx, ctx.i64(out_shape)])
        # gather output layout: (batching dims..., free idx dims);
        # GatherElements works in operand layout -> permute there and back
        perm = []
        for d in range(rank):
            perm.append(ob.index(d) if d != axis else rank - 1)
        if perm != list(range(rank)):
            idx2 = ctx.node("Transpose", [idx2], perm=perm)
        g = ctx.node("GatherElements", [x, idx2], axis=axis)
        inv = [0] * rank
        for i, d in enumerate(perm):
            inv[d] = i
        if perm != list(range(rank)):
            ctx.node("Transpose", [g], perm=inv, out=out)
        else:
            ctx.node("Identity", [g], out=out)
        return

    if ob or sb:
        raise NotImplementedError("gather with batching dims (general form)")

    # Pattern A: jnp.take/embedding -> Gather(axis)
    if (len(start_map) == 1 and collapsed == start_map
            and idx_shape[-1] == 1
            and all(slice_sizes[d] == (1 if d == start_map[0] else op_shape[d])
                    for d in range(rank))):
        axis = start_map[0]
        n_idx = len(idx_shape) - 1
        want_offset = tuple(
            d if d < axis else d - 1 + n_idx
            for d in range(rank) if d != axis)
        if offset == want_offset:
            idx2 = ctx.node("Reshape", [idx, ctx.i64(idx_shape[:-1])])
            ctx.node("Gather", [x, idx2], axis=axis, out=out)
            return

    # Pattern C: advanced integer indexing over leading dims -> GatherND
    depth = len(start_map)
    if (start_map == tuple(range(depth)) and collapsed == start_map
            and idx_shape[-1] == depth
            and all(slice_sizes[d] == 1 for d in range(depth))
            and all(slice_sizes[d] == op_shape[d]
                    for d in range(depth, rank))
            and offset == tuple(range(len(idx_shape) - 1,
                                      len(idx_shape) - 1 + rank - depth))):
        ctx.node("GatherND", [x, idx], out=out)
        return

    raise NotImplementedError(
        f"gather pattern not translatable: {dn}, slice_sizes={slice_sizes}")


# sub-jaxpr inlining ---------------------------------------------------------

def _inline(ctx, eqn, ins, out):
    params = eqn.params
    sub = None
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in params and params[key] is not None:
            sub = params[key]
            break
    if sub is None:
        raise NotImplementedError(
            f"no sub-jaxpr on {eqn.primitive.name}: {list(params)}")
    closed = sub if hasattr(sub, "jaxpr") else None
    inner = closed.jaxpr if closed is not None else sub
    consts = closed.consts if closed is not None else []
    names = _translate_jaxpr(ctx, inner, consts, ins)
    outs = [out] if isinstance(out, str) else out
    for name, o in zip(names, outs):
        if o is not None:
            ctx.node("Identity", [name], out=o)


for _p in ("jit", "pjit", "closed_call", "core_call", "remat",
           "remat2", "checkpoint", "custom_jvp_call", "custom_vjp_call",
           "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"):
    _reg(_p)(_inline)


@_reg("split")
def _split(ctx, eqn, ins, out):
    p = eqn.params
    sizes = [int(s) for s in p["sizes"]]
    axis = int(p["axis"])
    outs = out if isinstance(out, list) else [out]
    outs = [o or ctx.fresh("split_drop") for o in outs]
    ctx.node("Split", [ins[0], ctx.i64(sizes)], n_out=len(outs),
             out=outs, axis=axis)


@_reg("atan2")
def _atan2(ctx, eqn, ins, out):
    # no Atan2 in ONNX: atan(y/x) with quadrant correction via signs
    y, x = ins
    q = ctx.node("Div", [y, x])
    a = ctx.node("Atan", [q])
    dt = _dtype(eqn.invars[0])
    pi = ctx.const(np.asarray(np.pi, dt))
    zero = ctx.const(np.asarray(0, dt))
    x_neg = ctx.node("Less", [x, zero])
    y_neg = ctx.node("Less", [y, zero])
    corr_sign = ctx.node("Where", [y_neg, ctx.const(np.asarray(-1, dt)),
                                   ctx.const(np.asarray(1, dt))])
    corr = ctx.node("Mul", [corr_sign, pi])
    corrected = ctx.node("Add", [a, corr])
    ctx.node("Where", [x_neg, corrected, a], out=out)


@_reg("cumprod")
def _cumprod(ctx, eqn, ins, out):
    # CumProd is not standard ONNX: exp(cumsum(log(x))) works for positive
    # inputs; general sign handling via cumulative sign products
    axis = eqn.params["axis"]
    dt = _dtype(eqn.invars[0])
    absx = ctx.node("Abs", ins)
    logx = ctx.node("Log", [absx])
    csum = ctx.node("CumSum", [logx, ctx.const(np.asarray(axis, np.int64))])
    mag = ctx.node("Exp", [csum])
    sign = ctx.node("Sign", ins)
    # cumulative product of signs: count of negatives so far, parity
    neg = ctx.node("Less", [sign, ctx.const(np.asarray(0, dt))])
    negf = ctx.node("Cast", [neg], to=onnx_dtype(np.dtype(np.float32)))
    negc = ctx.node("CumSum", [negf, ctx.const(np.asarray(axis, np.int64))])
    par = ctx.node("Mod", [negc, ctx.const(np.asarray(2.0, np.float32))],
                   fmod=1)
    two = ctx.const(np.asarray(-2.0, np.float32))
    sgn = ctx.node("Add", [ctx.node("Mul", [par, two]),
                           ctx.const(np.asarray(1.0, np.float32))])
    sgn_c = ctx.node("Cast", [sgn], to=onnx_dtype(dt))
    ctx.node("Mul", [mag, sgn_c], out=out)


@_reg("top_k")
def _top_k(ctx, eqn, ins, out):
    k = eqn.params["k"]
    vals, idx = ctx.node("TopK", [ins[0], ctx.i64([k])], n_out=2,
                         axis=-1, largest=1, sorted=1)
    outs = out if isinstance(out, list) else [out]
    ctx.node("Identity", [vals], out=outs[0])
    if len(outs) > 1 and outs[1] is not None:
        idx32 = ctx.node("Cast", [idx],
                         to=onnx_dtype(_dtype(eqn.outvars[1])))
        ctx.node("Identity", [idx32], out=outs[1])


@_reg("sort")
def _sort(ctx, eqn, ins, out):
    p = eqn.params
    dim = p.get("dimension", -1)
    n = _shape(eqn.invars[0])[dim]
    if len(ins) > 2:
        raise NotImplementedError("sort of >2 operands has no ONNX path")
    # 2-operand form: the argsort pattern (keys, iota) — TopK's index
    # output IS the sorted iota. TopK is unstable; accepted divergence.
    vals, idx = ctx.node("TopK", [ins[0], ctx.i64([n])], n_out=2,
                         axis=dim, largest=0, sorted=1)
    outs = out if isinstance(out, list) else [out]
    ctx.node("Identity", [vals], out=outs[0])
    for extra, var in zip(outs[1:], eqn.outvars[1:]):
        if extra is not None:
            cast = ctx.node("Cast", [idx], to=onnx_dtype(var.aval.dtype))
            ctx.node("Identity", [cast], out=extra)


@_reg("scatter", "scatter-update")
def _scatter_set(ctx, eqn, ins, out):
    _scatter_impl(ctx, eqn, ins, out, "none")


@_reg("scatter-add")
def _scatter_add(ctx, eqn, ins, out):
    _scatter_impl(ctx, eqn, ins, out, "add")


def _scatter_impl(ctx, eqn, ins, out, reduction):
    """Row-wise scatter (the .at[idx].set/.add pattern: index vector over
    axis 0, full trailing window) -> ONNX ScatterND."""
    dn = eqn.params["dimension_numbers"]
    operand, indices, updates = ins
    op_shape = _shape(eqn.invars[0])
    if (tuple(dn.scatter_dims_to_operand_dims) != (0,)
            or tuple(dn.inserted_window_dims) != (0,)):
        raise NotImplementedError(
            "only axis-0 row scatter translates to ONNX ScatterND")
    idx_shape = _shape(eqn.invars[1])
    # lax scatter indices: (..., 1); ScatterND wants (..., 1) int64 too
    idx64 = ctx.node("Cast", [indices], to=onnx_dtype(np.dtype(np.int64)))
    if len(idx_shape) == 1:
        idx64 = ctx.node("Unsqueeze", [idx64, ctx.i64([-1])])
    kwargs = {} if reduction == "none" else {"reduction": reduction}
    ctx.node("ScatterND", [operand, idx64, updates], out=out, **kwargs)


@_reg("scan")
def _scan(ctx, eqn, ins, out):
    """lax.scan -> ONNX Scan. Body consts become outer-scope references
    (ONNX subgraphs capture enclosing names); carries map to Scan state
    variables, xs to scan inputs, ys to scan outputs."""
    p = eqn.params
    closed = p["jaxpr"]
    inner = closed.jaxpr
    n_const, n_carry = p["num_consts"], p["num_carry"]
    reverse = bool(p.get("reverse", False))
    const_names = ins[:n_const]
    carry_init = ins[n_const:n_const + n_carry]
    xs_names = ins[n_const + n_carry:]
    n_xs = len(xs_names)
    n_ys = len(inner.outvars) - n_carry

    # build the body subgraph with its own node list
    body_in_names = []
    sub_nodes = []
    saved_nodes, ctx.nodes = ctx.nodes, sub_nodes
    try:
        body = serde.GraphProto()
        body.name = ctx.fresh("scan_body")
        env = {}
        for var, cname in zip(inner.invars[:n_const], const_names):
            env[var] = cname  # outer-scope capture
        for var in inner.invars[n_const:]:
            nm = ctx.fresh("scan_in")
            env[var] = nm
            body_in_names.append(nm)
            aval = var.aval
            body.input.add().CopyFrom(make_value_info(
                nm, aval.dtype, aval.shape))
        out_names = _translate_jaxpr(ctx, inner, closed.consts,
                                     [env[v] for v in inner.invars])
        produced = {o for n in sub_nodes for o in n.output}
        for i, (nm, var) in enumerate(zip(out_names, inner.outvars)):
            if nm not in produced or out_names.count(nm) > 1:
                nm2 = ctx.fresh("scan_out")
                ctx.node("Identity", [nm], out=nm2)
                nm = nm2
                out_names[i] = nm
            body.output.add().CopyFrom(make_value_info(
                nm, var.aval.dtype, var.aval.shape))
        for n in sub_nodes:
            body.node.add().CopyFrom(n)
    finally:
        ctx.nodes = saved_nodes

    outs = out if isinstance(out, list) else [out]
    scan_outs = [o or ctx.fresh("scan_drop") for o in outs]
    direction = [1 if reverse else 0] * n_xs
    ctx.node("Scan", list(carry_init) + list(xs_names),
             n_out=len(scan_outs), out=scan_outs, body=body,
             num_scan_inputs=n_xs,
             scan_input_directions=direction,
             scan_output_directions=[1 if reverse else 0] * n_ys)


# --------------------------------------------------------------------------
# jaxpr walker
# --------------------------------------------------------------------------

def _translate_jaxpr(ctx, jaxpr, consts, invar_names):
    """Translate one (open) jaxpr; returns the onnx names of its outvars."""
    from jax.extend import core as jcore
    env = dict()

    def name_of(atom):
        if isinstance(atom, jcore.Literal):
            return ctx.const(np.asarray(atom.val, atom.aval.dtype))
        if atom in env:
            return env[atom]
        return ctx.env[atom]

    for var, val in zip(jaxpr.constvars, consts):
        env[var] = ctx.const(np.asarray(val))
    for var, name in zip(jaxpr.invars, invar_names):
        env[var] = name

    saved = ctx.env
    ctx.env = {**saved, **env}
    try:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            h = _HANDLERS.get(prim)
            if h is None:
                raise NotImplementedError(
                    f"lax primitive {prim!r} has no ONNX translation")
            ins = [name_of(v) for v in eqn.invars]
            outs = []
            for v in eqn.outvars:
                if type(v).__name__ == "DropVar":
                    outs.append(None)
                else:
                    n = ctx.fresh(prim)
                    ctx.env[v] = n
                    env[v] = n
                    outs.append(n)
            if len(outs) == 1:
                h(ctx, eqn, ins, outs[0])
            else:
                h(ctx, eqn, ins, outs)
        return [name_of(v) for v in jaxpr.outvars]
    finally:
        ctx.env = saved
        ctx.env.update(env)


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def _leaf_names(tree, fallback_prefix):
    """Flatten-order names for pytree leaves, from their key paths
    (dict keys / field names), so names always align with tree_flatten
    order — which for dicts is *sorted* key order, not insertion order."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for i, (path, _leaf) in enumerate(flat):
        if path and hasattr(path[-1], "key"):
            names.append(str(path[-1].key))
        elif path:
            names.append(jax.tree_util.keystr(path).strip("[]'\""))
        else:
            names.append(f"{fallback_prefix}_{i}")
    return names


def trace_to_onnx(fn, example_args, *, graph_name="mxnet_tpu",
                  param_args=(), input_names=None, opset=17):
    """Trace `fn(*param_args, *example_args)` and translate to a ModelProto.

    `param_args` leaves become graph initializers (weights baked into the
    model, named by their pytree key paths — e.g. dict keys);
    `example_args` leaves become graph inputs.
    """
    import jax

    closed = jax.make_jaxpr(fn)(*param_args, *example_args)
    jaxpr = _dce(closed.jaxpr)

    ctx = _Ctx()
    flat_params, _ = jax.tree_util.tree_flatten(list(param_args))
    flat_inputs, _ = jax.tree_util.tree_flatten(list(example_args))
    param_names = _leaf_names(list(param_args), "param")
    n_params = len(flat_params)

    invar_names = []
    graph_inputs = []
    for i, var in enumerate(jaxpr.invars):
        if i < n_params:
            name = param_names[i]
            ctx.initializers[name] = make_tensor(
                name, np.asarray(flat_params[i]))
            invar_names.append(name)
        else:
            j = i - n_params
            name = (input_names[j] if input_names else f"input_{j}")
            graph_inputs.append(make_value_info(
                name, var.aval.dtype, var.aval.shape))
            invar_names.append(name)
        ctx.env[var] = name

    out_names = _translate_jaxpr(ctx, jaxpr, closed.consts, invar_names)

    graph = serde.GraphProto()
    graph.name = graph_name
    # an output that is directly an input/initializer needs a node
    final = []
    produced = {o for n in ctx.nodes for o in n.output}
    for i, (name, var) in enumerate(zip(out_names, closed.jaxpr.outvars)):
        if name not in produced or name in ctx.initializers \
                or name in final:
            # the `final` check: a model returning the same traced value
            # twice must not emit two graph.outputs with one name
            name = ctx.node("Identity", [name], out=f"output_{i}")
        final.append(name)
    for n in ctx.nodes:
        graph.node.add().CopyFrom(n)
    for t in ctx.initializers.values():
        graph.initializer.add().CopyFrom(t)
    for vi in graph_inputs:
        graph.input.add().CopyFrom(vi)
    for name, var in zip(final, closed.jaxpr.outvars):
        graph.output.add().CopyFrom(make_value_info(
            name, var.aval.dtype, var.aval.shape))
    return serde.make_model(graph, opset=opset)

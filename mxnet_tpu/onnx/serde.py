"""ONNX protobuf construction/readback helpers.

Plays the role of the `onnx.helper` / `onnx.numpy_helper` surface the
reference exporter leans on (reference:
python/mxnet/onnx/mx2onnx/_export_onnx.py:33-60 builds NodeProto/
TensorProto/GraphProto through onnx.helper).  Here the schema is compiled
locally (onnx_mxtpu.proto, wire-compatible with upstream ONNX), so the
framework has no dependency on the `onnx` package.
"""
from __future__ import annotations

import numpy as np

from . import onnx_mxtpu_pb2 as P

TensorProto = P.TensorProto
ModelProto = P.ModelProto
GraphProto = P.GraphProto
NodeProto = P.NodeProto
AttributeProto = P.AttributeProto

# numpy dtype name <-> TensorProto.DataType (public ONNX enum values).
_NP2ONNX = {
    "float32": P.TensorProto.FLOAT,
    "uint8": P.TensorProto.UINT8,
    "int8": P.TensorProto.INT8,
    "uint16": P.TensorProto.UINT16,
    "int16": P.TensorProto.INT16,
    "int32": P.TensorProto.INT32,
    "int64": P.TensorProto.INT64,
    "bool": P.TensorProto.BOOL,
    "float16": P.TensorProto.FLOAT16,
    "float64": P.TensorProto.DOUBLE,
    "uint32": P.TensorProto.UINT32,
    "uint64": P.TensorProto.UINT64,
    "bfloat16": P.TensorProto.BFLOAT16,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def onnx_dtype(np_dtype) -> int:
    name = np.dtype(np_dtype).name if not isinstance(np_dtype, str) else np_dtype
    # jax may hand us e.g. ml_dtypes.bfloat16 whose dtype name is 'bfloat16'
    name = str(name)
    if name not in _NP2ONNX:
        raise ValueError(f"dtype {name!r} has no ONNX mapping")
    return _NP2ONNX[name]


def np_dtype(onnx_enum: int):
    if onnx_enum not in _ONNX2NP:
        raise ValueError(f"ONNX data_type {onnx_enum} unsupported")
    name = _ONNX2NP[onnx_enum]
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def make_tensor(name: str, array) -> P.TensorProto:
    """Serialize an array as a TensorProto with little-endian raw_data."""
    arr = np.asarray(array)
    if str(arr.dtype) == "bfloat16":
        enum = P.TensorProto.BFLOAT16
    else:
        enum = onnx_dtype(arr.dtype)
    t = P.TensorProto()
    t.name = name
    t.data_type = enum
    t.dims.extend(arr.shape)
    a = arr
    if a.dtype.byteorder == ">":
        a = a.byteswap()
    t.raw_data = np.ascontiguousarray(a).tobytes()
    return t


def to_array(t: P.TensorProto) -> np.ndarray:
    """TensorProto -> numpy array (raw_data or typed repeated fields)."""
    dt = np_dtype(t.data_type)
    shape = tuple(t.dims)
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=dt).reshape(shape).copy()
    if t.float_data:
        return np.asarray(t.float_data, np.float32).astype(dt).reshape(shape)
    if t.int64_data:
        return np.asarray(t.int64_data, np.int64).astype(dt).reshape(shape)
    if t.int32_data:
        # int32_data also carries f16/bf16/bool/int8/16 per the ONNX spec;
        # f16/bf16 are stored as raw 16-bit patterns, not values
        raw32 = np.asarray(t.int32_data, np.int32)
        if t.data_type in (P.TensorProto.FLOAT16, P.TensorProto.BFLOAT16):
            return raw32.astype(np.uint16).view(dt).reshape(shape)
        return raw32.astype(dt).reshape(shape)
    if t.double_data:
        return np.asarray(t.double_data, np.float64).astype(dt).reshape(shape)
    if t.uint64_data:
        return np.asarray(t.uint64_data, np.uint64).astype(dt).reshape(shape)
    return np.zeros(shape, dt)


def _set_attr(a: P.AttributeProto, value):
    if isinstance(value, bool):
        a.type, a.i = P.AttributeProto.INT, int(value)
    elif isinstance(value, (int, np.integer)):
        a.type, a.i = P.AttributeProto.INT, int(value)
    elif isinstance(value, (float, np.floating)):
        a.type, a.f = P.AttributeProto.FLOAT, float(value)
    elif isinstance(value, str):
        a.type, a.s = P.AttributeProto.STRING, value.encode()
    elif isinstance(value, bytes):
        a.type, a.s = P.AttributeProto.STRING, value
    elif isinstance(value, P.TensorProto):
        a.type = P.AttributeProto.TENSOR
        a.t.CopyFrom(value)
    elif isinstance(value, P.GraphProto):
        a.type = P.AttributeProto.GRAPH
        a.g.CopyFrom(value)
    elif isinstance(value, (list, tuple, np.ndarray)):
        vals = list(value)
        if all(isinstance(v, (int, np.integer)) for v in vals):
            a.type = P.AttributeProto.INTS
            a.ints.extend(int(v) for v in vals)
        elif all(isinstance(v, (int, float, np.floating, np.integer))
                 for v in vals):
            a.type = P.AttributeProto.FLOATS
            a.floats.extend(float(v) for v in vals)
        elif all(isinstance(v, str) for v in vals):
            a.type = P.AttributeProto.STRINGS
            a.strings.extend(v.encode() for v in vals)
        else:
            raise TypeError(f"attr list {value!r} unsupported")
    else:
        raise TypeError(f"attr {value!r} unsupported")


def make_node(op_type: str, inputs, outputs, name: str = "", **attrs):
    n = P.NodeProto()
    n.op_type = op_type
    n.input.extend(inputs)
    n.output.extend(outputs)
    n.name = name or (outputs[0] if outputs else op_type)
    for k, v in attrs.items():
        if v is None:
            continue
        a = n.attribute.add()
        a.name = k
        _set_attr(a, v)
    return n


def attr_value(a: P.AttributeProto):
    T = P.AttributeProto
    if a.type == T.INT:
        return a.i
    if a.type == T.FLOAT:
        return a.f
    if a.type == T.STRING:
        return a.s.decode()
    if a.type == T.INTS:
        return list(a.ints)
    if a.type == T.FLOATS:
        return list(a.floats)
    if a.type == T.STRINGS:
        return [s.decode() for s in a.strings]
    if a.type == T.TENSOR:
        return to_array(a.t)
    if a.type == T.GRAPH:
        return a.g
    raise ValueError(f"attribute type {a.type} unsupported")


def node_attrs(node: P.NodeProto) -> dict:
    return {a.name: attr_value(a) for a in node.attribute}


def make_value_info(name: str, dtype, shape) -> P.ValueInfoProto:
    vi = P.ValueInfoProto()
    vi.name = name
    vi.type.tensor_type.elem_type = onnx_dtype(dtype)
    sh = vi.type.tensor_type.shape
    for d in shape:
        dim = sh.dim.add()
        if isinstance(d, str):
            dim.dim_param = d
        else:
            dim.dim_value = int(d)
    return vi


def make_model(graph: P.GraphProto, opset: int = 17,
               producer: str = "mxnet_tpu") -> P.ModelProto:
    m = P.ModelProto()
    m.ir_version = 8
    m.producer_name = producer
    m.graph.CopyFrom(graph)
    m.opset_import.add(domain="", version=opset)
    return m


def save_model(model: P.ModelProto, path: str) -> str:
    with open(path, "wb") as f:
        f.write(model.SerializeToString())
    return path


def load_model(path: str) -> P.ModelProto:
    m = P.ModelProto()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    return m

"""mx.onnx — ONNX model export/import.

Reference parity: python/mxnet/onnx (mx2onnx/_export_onnx.py
MXNetGraph + ~200 op translations, public API onnx/__init__.py
export_model).  TPU-native design: instead of walking an NNVM symbol
graph, the exporter traces the block's eval-mode forward to a jaxpr — the
exact program the TPU executes — and translates lax primitives to ONNX
(opset 17).  The importer evaluates ONNX graphs with jnp so round-trips
are verified without any external ONNX runtime.

    mx.onnx.export_model(net, "model.onnx", args=(x,))
    net2 = mx.onnx.import_model("model.onnx")   # ONNXBlock, callable

The protobuf schema is compiled locally (onnx_mxtpu.proto) and is
wire-compatible with upstream ONNX files.
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..numpy.multiarray import ndarray, _wrap
from . import serde
from ._export import trace_to_onnx
from ._runtime import make_fn
from .serde import load_model, save_model

__all__ = ["export_model", "import_model", "load_model", "save_model",
           "run_model", "ONNXBlock", "trace_to_onnx", "make_fn"]


def _raw(x):
    return x._data if isinstance(x, ndarray) else x


def export_model(net, path, args=None, input_names=None, opset=17,
                 graph_name=None):
    """Export a HybridBlock / Symbol / python function to an ONNX file.

    Parameters mirror the reference `mx.onnx.export_model`
    (python/mxnet/onnx/__init__.py): the model plus example inputs; weights
    become graph initializers named by their structural parameter names.

    - HybridBlock: traced via ``functional.functional_call`` in eval mode.
    - Symbol: free variables other than bound constants become inputs;
      ``args`` must be a dict name -> example ndarray.
    - callable: traced as-is with ``args`` as example inputs.
    """
    from .. import functional
    from ..gluon.block import Block

    if isinstance(net, Block):
        if args is None:
            raise MXNetError("export_model needs example input args")
        ex = tuple(_raw(a) for a in (args if isinstance(args, (tuple, list))
                                     else (args,)))
        params = functional.param_arrays(net)

        def fwd(params, *inputs):
            out, _ = functional.functional_call(net, params, *inputs,
                                                train=False)
            return out

        model = trace_to_onnx(
            fwd, ex, param_args=(params,), input_names=input_names,
            graph_name=graph_name or type(net).__name__, opset=opset)
    elif hasattr(net, "_eval_with"):  # mx.sym.Symbol
        if not isinstance(args, dict):
            raise MXNetError("Symbol export needs args={name: example}")
        arg_names = [n for n in net.list_arguments() if n in args]
        ex = tuple(_raw(args[n]) for n in arg_names)

        def fwd(*inputs):
            bound = {n: _wrap(v) for n, v in zip(arg_names, inputs)}
            out = net._eval_with(bound)
            import jax
            return jax.tree_util.tree_map(
                _raw, out, is_leaf=lambda x: isinstance(x, ndarray))

        model = trace_to_onnx(
            fwd, ex, input_names=input_names or arg_names,
            graph_name=graph_name or "symbol", opset=opset)
    elif callable(net):
        if args is None:
            args = ()
        elif not isinstance(args, (tuple, list)):
            args = (args,)
        ex = tuple(_raw(a) for a in args)
        model = trace_to_onnx(net, ex, input_names=input_names,
                              graph_name=graph_name or getattr(
                                  net, "__name__", "fn"), opset=opset)
    else:
        raise MXNetError(f"cannot export {type(net)}")
    return save_model(model, path)


def run_model(model_or_path, inputs):
    """Evaluate an ONNX model with mx ndarray/array inputs; returns a list
    of mx ndarrays."""
    model = (load_model(model_or_path) if isinstance(model_or_path, str)
             else model_or_path)
    fn = make_fn(model)
    raw = [_raw(x) for x in inputs]
    return [_wrap(o) for o in fn(*raw)]


class ONNXBlock:
    """Callable wrapper over an imported ONNX graph (the analog of loading
    an exported model back through SymbolBlock, reference
    gluon/block.py:1638).  Weights live in ``.params`` as mx ndarrays and
    can be re-assigned before calls (triggering a re-jit, since weights
    are folded as constants); the underlying evaluation is jit-compiled
    on first call per weight snapshot."""

    def __init__(self, model):
        self.model = model
        fn = make_fn(model)
        self.input_names = fn.input_names
        self.output_names = fn.output_names
        self.params = {t.name: _wrap(serde.to_array(t))
                       for t in model.graph.initializer}
        self._jitted = None
        self._params_snapshot = None

    def __call__(self, *args):
        import jax
        # snapshot holds references, so object identity can't be recycled
        stale = (self._params_snapshot is None
                 or any(self._params_snapshot.get(k) is not v
                        for k, v in self.params.items()))
        if self._jitted is None or stale:
            override = {k: onp.asarray(_raw(v))
                        for k, v in self.params.items()}
            self._jitted = jax.jit(make_fn(self.model, override))
            self._params_snapshot = dict(self.params)
        outs = self._jitted(*[_raw(a) for a in args])
        outs = [_wrap(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def import_model(path):
    """Load an ONNX file into a runnable ONNXBlock."""
    return ONNXBlock(load_model(path))

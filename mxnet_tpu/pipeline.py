"""mx.pipeline — async host<->device overlap engine.

Reference parity: MXNet's identity #1 is the async dependency-scheduling
engine (include/mxnet/engine.h) that keeps devices busy while the user
writes sequential code; on the input side the reference pairs it with
iter_prefetcher.h's threaded prefetch chain.  Here PJRT async dispatch
already IS the compute engine (jax arrays are futures), so what remains —
and what this module provides — is overlap at the two host boundaries
TVM-style latency hiding (arxiv 1802.04799) says dominate accelerator
utilization:

1. **Input**: :class:`DevicePrefetcher` runs ``jax.device_put`` (laid out
   against a trainer's mesh/PartitionSpecs when given) on a background
   thread with a bounded in-flight window, so the H2D copy of batch N+1
   overlaps step N's compute.  TPU steps are frequently infeed-bound
   (arxiv 2008.01040); the prefetcher is the cure the learned-performance-
   model work motivates.  Exposed as ``DataLoader(prefetch_to_device=...)``
   and as the standalone :func:`prefetch_to_device` wrapper for any batch
   iterator.
2. **Output**: :class:`DeferredWindow` keeps per-step scalar reads
   (grad norms, metric accumulators) as device values inside a bounded
   FIFO and fetches them in bulk at epoch boundaries or explicit
   ``drain()`` — the hot step loop never calls ``float()`` /
   ``block_until_ready`` on a fresh value, so dispatch stays sync-free
   end to end.

:func:`sync_guard` is the transfer-guard context the test suite uses to
*prove* a code path performs no host sync: every instrumented sync site
(``ndarray.asnumpy``/``item``/``wait_to_read``, ``engine.wait_all``,
``Trainer._grad_norm``, forced window evictions) reports into active
guards via :func:`note_host_sync`.  Guards are thread-local, so the
prefetcher's own background transfers never pollute a guarded step loop.

Disabled cost: no prefetcher constructed -> batch iterators are returned
unchanged; the sync probes threaded through the stack gate on one module
attribute read (``_guard_depth``), mirroring ``fault._active`` /
``telemetry._active`` (CI enforces the <2% budget in
benchmark/pipeline_overlap.py).

Resilience: a prefetcher buffers batches the training loop has NOT seen
yet; the DataLoader's served-batch cursor is incremented by the *consumer*
loop, so TrainState bundles stay authoritative and buffered-but-unserved
batches replay after preemption (tests/test_pipeline.py proves this
bitwise).  The ``pipeline.prefetch_stall`` fault point wedges the
background thread between batches; the consumer's stall deadline then
hands the same source iterator to a replacement thread, preserving order
— and a producer that was merely slow (not wedged) still delivers its
in-flight batch, because fetch and enqueue are serialized under one lock.
"""
from __future__ import annotations

import queue
import threading
import time

from . import config as _config
from . import fault as _fault
from . import goodput as _goodput
from . import telemetry as _telemetry
from . import trace as _trace

__all__ = ["DevicePrefetcher", "prefetch_to_device", "DeferredWindow",
           "maybe_device_put", "ensure_sharded", "sync_guard",
           "note_host_sync", "SyncGuard", "take", "arm_site_counts",
           "sync_site_counts", "reset_site_counts"]


def take(source, n):
    """Yield at most ``n`` batches from ``source``, then release it:
    ``close()`` is called on the iterator (or the source) when either
    side defines it, so peeling a sample batch off a DevicePrefetcher or
    a worker-backed DataLoader doesn't leave its background machinery
    running.  Used by the autotune surfaces to borrow one batch from the
    caller's loader."""
    it = iter(source)
    try:
        for _ in range(int(n)):
            try:
                yield next(it)
            except StopIteration:
                return
    finally:
        close = getattr(it, "close", None) or getattr(source, "close", None)
        if callable(close):
            try:
                close()
            except Exception:
                pass

_telemetry.declare_metric(
    "pipeline.input_stall_seconds", "histogram",
    "time the training loop blocked waiting on the device prefetch queue",
    buckets=_telemetry.TIME_BUCKETS)
_telemetry.declare_metric(
    "pipeline.inflight_depth", "gauge",
    "prefetched batches buffered when the loop asked for one")
_telemetry.declare_metric(
    "pipeline.batches_total", "counter",
    "batches delivered through DevicePrefetchers")
_telemetry.declare_metric(
    "pipeline.h2d_bytes_total", "counter",
    "bytes actually moved host->device by prefetch puts (already-resident, "
    "correctly-sharded leaves are skipped and not counted)")
_telemetry.declare_metric(
    "pipeline.stall_recovered_total", "counter",
    "prefetch threads declared stalled and replaced")
_telemetry.declare_metric(
    "pipeline.deferred_evictions_total", "counter",
    "DeferredWindow overflows forced to fetch on the hot path")
_telemetry.declare_metric(
    "pipeline.host_syncs_total", "counter",
    "host syncs observed by the instrumented sync sites, by site "
    "(recorded once mx.telemetry or mx.blackbox arms the site counter)")


# ---------------------------------------------------------------------------
# transfer guard: prove a code path performs no host sync
# ---------------------------------------------------------------------------

#: hot-path gate — sync sites read this one attribute; 0 keeps every probe
#: a single no-op branch (same design as fault._active)
_guard_depth = 0
_guard_lock = threading.Lock()
_tls = threading.local()


class SyncGuard:
    """Counter handed back by :func:`sync_guard`: total host syncs seen
    while active, broken down by site name in ``sites``."""

    __slots__ = ("count", "sites")

    def __init__(self):
        self.count = 0
        self.sites = {}

    def _note(self, site):
        self.count += 1
        self.sites[site] = self.sites.get(site, 0) + 1


class sync_guard:
    """Context manager counting host syncs on the *current thread*:

        with mx.pipeline.sync_guard() as g:
            run_steps()
        assert g.count == 0, g.sites

    Thread-local by design — background prefetch transfers do not count
    against a guarded training loop.
    """

    def __enter__(self):
        global _guard_depth
        g = SyncGuard()
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(g)
        with _guard_lock:
            _guard_depth += 1
        return g

    def __exit__(self, *exc):
        global _guard_depth
        _tls.stack.pop()
        with _guard_lock:
            _guard_depth -= 1
        return False


#: process-lifetime host syncs by call site, fed by note_host_sync; read
#: via sync_site_counts() (telemetry.snapshot()["sync_sites"] and
#: blackbox bundles).  Only populated while some owner holds the arm
#: sentinel below or a guard keeps _guard_depth nonzero.
_site_totals: dict[str, int] = {}
#: owners (mx.telemetry, mx.blackbox) currently biasing _guard_depth so
#: sync sites report with no user guard on the thread
_armed_owners: set = set()


def arm_site_counts(owner, on=True):
    """Idempotently bias ``_guard_depth`` by one while any ``owner``
    (telemetry / blackbox) wants process-lifetime per-site sync counts,
    so the instrumented call sites report without a :func:`sync_guard`
    active on the thread.  :class:`SyncGuard` semantics are untouched —
    only guards on the calling thread's stack accumulate into guard
    objects.  Returns True while armed."""
    global _guard_depth
    with _guard_lock:
        had = bool(_armed_owners)
        if on:
            _armed_owners.add(owner)
        else:
            _armed_owners.discard(owner)
        have = bool(_armed_owners)
        if have and not had:
            _guard_depth += 1
        elif had and not have:
            _guard_depth -= 1
    return bool(_armed_owners)


def sync_site_counts():
    """Process-lifetime host-sync counts by call site (sorted copy)."""
    with _guard_lock:
        return dict(sorted(_site_totals.items()))


def reset_site_counts():
    """Drop the per-site sync totals (telemetry.reset test isolation);
    the armed owners and guard depth are untouched."""
    with _guard_lock:
        _site_totals.clear()


def note_host_sync(site):
    """Report one host sync into every guard active on this thread and
    into the process-lifetime per-site totals.  Call sites gate on
    ``pipeline._guard_depth`` first so the disabled cost is one
    attribute read."""
    stack = getattr(_tls, "stack", None)
    if stack:
        for g in stack:
            g._note(site)
    with _guard_lock:
        _site_totals[site] = _site_totals.get(site, 0) + 1
    if _telemetry._active:
        _telemetry.inc("pipeline.host_syncs_total", site=site)


# ---------------------------------------------------------------------------
# device placement helpers
# ---------------------------------------------------------------------------

_nd_cache = None


def _nd():
    # lazy: numpy/multiarray imports this module (sync probes), so the
    # reverse import must happen at call time, after the package finished
    # initializing
    global _nd_cache
    if _nd_cache is None:
        from .numpy import multiarray as _nd_cache_mod
        _nd_cache = _nd_cache_mod
    return _nd_cache


def maybe_device_put(raw, target=None):
    """``jax.device_put`` that skips already-resident, correctly-placed
    values.  Returns ``(value, moved)`` — ``moved`` False means the input
    was already where it should be and no transfer was issued.

    ``target`` may be None (default device; any committed jax.Array is
    accepted as-is), a jax Device, or a ``jax.sharding.Sharding`` (layout
    equivalence checked via ``Sharding.is_equivalent_to``).
    """
    import jax
    if isinstance(raw, jax.Array):
        if target is None:
            return raw, False
        sharding = getattr(raw, "sharding", None)
        if sharding is not None:
            try:
                if hasattr(target, "is_equivalent_to"):
                    if sharding.is_equivalent_to(target, raw.ndim):
                        return raw, False
                elif getattr(raw, "devices", None) and \
                        raw.devices() == {target}:
                    return raw, False
            except Exception:  # noqa: BLE001 - fall through to a real put
                pass
    out = jax.device_put(raw) if target is None \
        else jax.device_put(raw, target)
    return out, True


def _local_nbytes(arr):
    """Bytes this host actually holds of ``arr``: the sum of its
    addressable shards.  ``arr.nbytes`` is the *global logical* size,
    which over-reports multi-host/multi-device sharded puts by roughly
    the shard count."""
    try:
        shards = arr.addressable_shards
    except Exception:  # noqa: BLE001 - non-jax arrays, exotic shardings
        shards = None
    if shards:
        return sum(getattr(s.data, "nbytes", 0) for s in shards)
    return getattr(arr, "nbytes", 0)


def ensure_sharded(raw, sharding):
    """Place one raw array against ``sharding``, skipping the put when its
    layout already matches (the sync-free path for prefetched batches);
    accounts real transfers in ``pipeline.h2d_bytes_total``."""
    out, moved = maybe_device_put(raw, sharding)
    if moved and _telemetry._active:
        _telemetry.inc("pipeline.h2d_bytes_total", _local_nbytes(out))
    return out


# ---------------------------------------------------------------------------
# deferred host-fetch window
# ---------------------------------------------------------------------------

def _fetch(value):
    """Device scalar (jax array / mx ndarray / nested tuple) -> float(s)."""
    if isinstance(value, tuple):
        return tuple(_fetch(v) for v in value)
    if isinstance(value, (int, float)):
        return float(value)
    return float(getattr(value, "_data", value))


class DeferredWindow:
    """Bounded FIFO of ``(device_value, sink)`` pairs whose host fetch is
    deferred off the step loop.

    ``push()`` enqueues a device scalar (or tuple of scalars) and the
    callback that consumes its float value(s); nothing touches the host
    until ``drain()`` — epoch boundary, explicit ``.get()`` — or until the
    window overflows, in which case the oldest entry is fetched in place
    (by then its value is ``window`` steps old and almost always already
    computed, but the fetch is still counted as a host sync so
    ``sync_guard`` stays honest).
    """

    def __init__(self, window=None):
        self._window = max(0, int(
            window if window is not None
            else _config.get("pipeline.deferred_window")))
        self._pending = []

    def __len__(self):
        return len(self._pending)

    def push(self, value, sink):
        self._pending.append((value, sink))
        while len(self._pending) > self._window:
            if _guard_depth:
                note_host_sync("deferred_evict")
            if _telemetry._active:
                _telemetry.inc("pipeline.deferred_evictions_total")
            self._drain_one()

    def _drain_one(self):
        value, sink = self._pending.pop(0)
        sink(_fetch(value))

    def drain(self):
        """Fetch and deliver every pending value, oldest first."""
        if _trace._active and self._pending:
            with _trace.span("pipeline.drain", category="pipeline",
                             pending=len(self._pending)):
                while self._pending:
                    self._drain_one()
            return
        while self._pending:
            self._drain_one()

    def clear(self):
        """Drop pending values without fetching (metric reset)."""
        self._pending.clear()


# ---------------------------------------------------------------------------
# device prefetcher
# ---------------------------------------------------------------------------

_DONE = object()


class _Raise:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class DevicePrefetcher:
    """Background-thread ``device_put`` pipeline over any batch iterator.

    ``source`` yields host batches (arrays or tuples/lists of arrays);
    the prefetch thread places each leaf on device — against
    ``shardings`` when given (a single target or a per-position sequence
    of ``NamedSharding``/device targets) — and buffers up to ``depth``
    ready batches.  The consuming loop then receives batches whose H2D
    copy already happened while the previous step computed.

    Already-on-device, correctly-laid-out leaves are passed through
    without a second put (``maybe_device_put``), so chaining a prefetcher
    into ``ShardedTrainStep`` costs nothing extra.

    Stall recovery: if no batch arrives within ``stall_timeout`` the
    thread is presumed wedged (fault point ``pipeline.prefetch_stall``
    injects exactly this); a replacement thread takes over the same
    source iterator under a lock, so batches are neither lost nor
    reordered.  The whole fetch->put->offer sequence runs under that
    lock, so even a superseded thread that was merely *slow* inside
    ``next(source)`` (cold start, heavy augmentation, network FS) still
    delivers its in-flight batch — the replacement cannot fetch the
    following batch until the lock is released, and the consumer accepts
    every queued item because queue order is source order by
    construction.  Generations exist only to retire replaced threads.
    """

    def __init__(self, source, shardings=None, depth=None,
                 stall_timeout=None):
        self._source = iter(source)
        self._shardings = shardings
        self._depth = max(1, int(
            depth if depth is not None
            else _config.get("pipeline.prefetch_depth")))
        self._stall_timeout = float(
            stall_timeout if stall_timeout is not None
            else _config.get("pipeline.stall_timeout"))
        self._q = queue.Queue(maxsize=self._depth)
        self._source_lock = threading.Lock()
        self._closed = threading.Event()
        self._gen = 0
        self._thread = None
        self._done = False
        self._trace_ctx = None

    # -- background side ----------------------------------------------------

    def _start(self):
        if _trace._active and self._trace_ctx is None:
            # span context of the consumer that spawned us: every h2d
            # span on the prefetch thread parents back to it
            self._trace_ctx = _trace.current_context()
        t = threading.Thread(target=self._run, args=(self._gen,),
                             name="mx-device-prefetch", daemon=True)
        self._thread = t
        t.start()

    def _stale(self, gen):
        return self._closed.is_set() or gen != self._gen

    def _offer(self, item):
        """Enqueue one item.  Called with ``_source_lock`` held, so queue
        order is source order even across a stall-recovery handover.
        Aborts only on close — a superseded thread's in-flight batch is
        still valid and must not be dropped."""
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, gen):
        if _trace._active and self._trace_ctx:
            _trace.adopt(self._trace_ctx)
        while not self._stale(gen):
            if _fault._active and _fault.fire("pipeline.prefetch_stall"):
                # wedge BETWEEN batches, holding neither the source lock
                # nor a batch — the replacement thread loses nothing
                while not self._stale(gen):
                    time.sleep(0.02)
                return
            with self._source_lock:
                # superseded while waiting for the lock: nothing fetched
                # yet, so retire and let the replacement take over
                if self._stale(gen):
                    return
                try:
                    try:
                        item = next(self._source)
                    except StopIteration:
                        # mxlint: disable=LCK002(hand-off under the source lock is the stall-recovery contract; _offer bounds each put to 0.1s and rechecks staleness)
                        self._offer(_DONE)
                        return
                    # the offer stays under the lock on purpose: if this
                    # thread was declared stalled while inside next(), a
                    # slow-but-alive producer still hands its batch on
                    # instead of dropping it, and the replacement (blocked
                    # on the lock) cannot fetch the following batch first
                    t0 = (time.perf_counter()
                          if _goodput._active else 0.0)
                    if _trace._active:
                        with _trace.span("pipeline.h2d",
                                         category="pipeline"):
                            payload = self._put_batch(item)
                    else:
                        payload = self._put_batch(item)
                    if _goodput._active:
                        _goodput.note("h2d",
                                      time.perf_counter() - t0)
                except BaseException as exc:  # noqa: BLE001 - to consumer
                    # mxlint: disable=LCK002(same bounded hand-off as above; the exception must reach the consumer before the thread retires)
                    self._offer(_Raise(exc))
                    return
                # mxlint: disable=LCK002(the offer stays under the lock on purpose, see comment above; the put is bounded and staleness-checked, so no unbounded block)
                if not self._offer(payload):
                    return

    def _target_for(self, n):
        sh = self._shardings
        if not isinstance(sh, (tuple, list)):
            return [sh] * n
        return list(sh)[:n] + [None] * max(0, n - len(sh))

    def _put_batch(self, batch):
        if isinstance(batch, (tuple, list)):
            targets = self._target_for(len(batch))
            return type(batch)(
                self._put_leaf(b, t) for b, t in zip(batch, targets))
        return self._put_leaf(batch, self._target_for(1)[0])

    def _put_leaf(self, leaf, target):
        import jax
        nd = _nd()
        if isinstance(leaf, (tuple, list)):
            return type(leaf)(self._put_leaf(x, target) for x in leaf)
        wrap = isinstance(leaf, nd.ndarray)
        raw = leaf._data if wrap else leaf
        if not (wrap or isinstance(raw, jax.Array)
                or hasattr(raw, "__array__")):
            return leaf  # non-array payload (ids, metadata) passes through
        out = ensure_sharded(raw, target)
        # leaves keep their flavor: mx ndarrays come back as mx ndarrays,
        # raw numpy/jax leaves come back as device-placed jax.Arrays — no
        # silent type change for users prefetching plain jax pipelines
        return nd._wrap(out) if wrap else out

    # -- consumer side ------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if self._thread is None:
            self._start()
        t0 = time.perf_counter()
        deadline = t0 + self._stall_timeout
        while True:
            try:
                # every queued item is valid regardless of which thread
                # generation offered it: offers happen under _source_lock,
                # so queue order is source order by construction
                item = self._q.get(timeout=min(
                    0.2, max(0.001, deadline - time.perf_counter())))
                break
            except queue.Empty:
                if time.perf_counter() >= deadline:
                    self._recover_stall()
                    deadline = time.perf_counter() + self._stall_timeout
        if _telemetry._active:
            _telemetry.observe("pipeline.input_stall_seconds",
                               time.perf_counter() - t0)
            _telemetry.set_gauge("pipeline.inflight_depth", self._q.qsize())
        if item is _DONE:
            self._done = True
            raise StopIteration
        if isinstance(item, _Raise):
            self._done = True
            raise item.exc
        if _telemetry._active:
            _telemetry.inc("pipeline.batches_total")
        return item

    def _recover_stall(self):
        """Replace a presumed-wedged prefetch thread: bump the generation
        (the old thread retires at its next loop-top check) and hand the
        source iterator to a fresh thread.  Lossless when the old thread
        was merely slow rather than wedged: it still holds the source
        lock, so it delivers its in-flight batch before the replacement
        can fetch the next one.  A thread wedged forever *inside*
        ``next(source)`` keeps the lock and must be cured at the source
        (e.g. the DataLoader's own heartbeat respawn)."""
        _fault.record("pipeline.stall_recovered")
        if _telemetry._active:
            _telemetry.inc("pipeline.stall_recovered_total")
        self._gen += 1
        self._start()

    def close(self):
        """Stop the prefetch thread and close the underlying source
        iterator (running its cleanup — e.g. the DataLoader's shm
        bookkeeping).  Idempotent; called by DataLoader.__iter__'s
        ``finally`` when the consuming loop abandons the epoch."""
        self._closed.set()
        t, self._thread = self._thread, None
        if t is not None:
            while True:  # drain so a put-blocked thread can observe close
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=2.0)
        close_src = getattr(self._source, "close", None)
        if close_src is not None and (t is None or not t.is_alive()):
            try:
                close_src()
            except Exception:  # noqa: BLE001 - best-effort source cleanup
                pass
        self._done = True

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def prefetch_to_device(batches, target=True, depth=None, stall_timeout=None):
    """Wrap any batch iterator in a :class:`DevicePrefetcher`.

    ``target=True`` prefetches to the default device; a Device/Sharding
    (or per-position sequence) lays batches out explicitly; ``None`` or
    ``False`` disables prefetching and returns ``batches`` unchanged —
    the zero-overhead off switch.
    """
    if target is None or target is False:
        return batches
    return DevicePrefetcher(batches,
                            shardings=None if target is True else target,
                            depth=depth, stall_timeout=stall_timeout)

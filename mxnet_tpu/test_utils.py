"""mx.test_utils.

Reference parity: python/mxnet/test_utils.py — assert_almost_equal (:656,
dtype-aware tolerances), check_numeric_gradient (:1044 finite differences),
check_consistency (:1491 cross-device oracle), environment helpers. These
are the kernel-correctness oracles the whole reference test suite leans on
(SURVEY §4); the TPU analog of check_consistency runs the same function on
cpu and the accelerator backend.
"""
from __future__ import annotations

import contextlib
import os

import numpy as onp

from .base import MXNetError
from .numpy.multiarray import ndarray

_DTYPE_TOL = {
    onp.dtype("float16"): (1e-2, 1e-2),
    onp.dtype("float32"): (1e-4, 1e-5),
    onp.dtype("float64"): (1e-6, 1e-8),
}


def default_rtol_atol(*arrays):
    rtol, atol = 1e-5, 1e-7
    for a in arrays:
        dt = onp.dtype(str(a.dtype)) if str(a.dtype) != "bfloat16" else None
        if dt is None:
            return (1e-2, 1e-2)
        if dt in _DTYPE_TOL:
            r, t = _DTYPE_TOL[dt]
            rtol, atol = max(rtol, r), max(atol, t)
    return rtol, atol


def _to_np(a):
    if isinstance(a, ndarray):
        return a.asnumpy()
    return onp.asarray(a)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Reference: test_utils.py:656."""
    a_np, b_np = _to_np(a), _to_np(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(a_np if not hasattr(a, "dtype") else a,
                                 b_np if not hasattr(b, "dtype") else b)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    onp.testing.assert_allclose(a_np.astype(onp.float64),
                                b_np.astype(onp.float64),
                                rtol=rtol, atol=atol, equal_nan=equal_nan,
                                err_msg=f"{names[0]} vs {names[1]}")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def check_numeric_gradient(f, inputs, grads=None, eps=1e-3, rtol=1e-2,
                           atol=1e-4):
    """Finite-difference gradient check (reference: test_utils.py:1044).

    f: callable(list of ndarrays) -> scalar ndarray. inputs: list of
    ndarrays with attach_grad() to compare against; if grads is given, it is
    the list of analytic grads instead.
    """
    from . import autograd
    from .numpy import array

    if grads is None:
        for x in inputs:
            x.attach_grad()
        with autograd.record():
            out = f(inputs)
        out.backward()
        grads = [x.grad.asnumpy() for x in inputs]

    for xi, x in enumerate(inputs):
        base = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(base)
        flat = base.reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            xs = list(inputs)
            xs[xi] = array(base.reshape(x.shape).astype(onp.float32))
            fp = float(f(xs).asnumpy().sum())
            flat[i] = orig - eps
            xs[xi] = array(base.reshape(x.shape).astype(onp.float32))
            fm = float(f(xs).asnumpy().sum())
            flat[i] = orig
            ng_flat[i] = (fp - fm) / (2 * eps)
        onp.testing.assert_allclose(grads[xi], num_grad, rtol=rtol, atol=atol,
                                    err_msg=f"input {xi} gradient mismatch")


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run fn on several backends and compare (reference: test_utils.py:1491
    — the cross-device kernel oracle). ctx_list defaults to [cpu, default]."""
    import jax
    from .numpy import array
    results = []
    platforms = ["cpu"]
    if jax.devices()[0].platform != "cpu":
        platforms.append(jax.devices()[0].platform)
    for plat in platforms:
        dev = jax.devices(plat)[0] if plat != "axon" else jax.devices()[0]
        placed = [array(x.asnumpy() if isinstance(x, ndarray) else x)
                  for x in inputs]
        with jax.default_device(dev):
            results.append(fn(placed))
    for r in results[1:]:
        assert_almost_equal(results[0], r, rtol, atol)
    return results


@contextlib.contextmanager
def environment(*args):
    """Scoped env vars (reference: test_utils.py environment)."""
    if len(args) == 2:
        updates = {args[0]: args[1]}
    else:
        updates = args[0]
    old = {k: os.environ.get(k) for k in updates}
    try:
        for k, v in updates.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def rand_ndarray(shape, dtype="float32", scale=1.0):
    from .numpy import random as npr
    return npr.uniform(-scale, scale, size=shape, dtype=dtype)


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim).tolist())


def same(a, b):
    return onp.array_equal(_to_np(a), _to_np(b))


def effective_dtype(x):
    return x.dtype


def default_context():
    from .context import current_context
    return current_context()


def set_default_context(ctx):
    ctx.__enter__()


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))

"""mx.test_utils.

Reference parity: python/mxnet/test_utils.py — assert_almost_equal (:656,
dtype-aware tolerances), check_numeric_gradient (:1044 finite differences),
check_consistency (:1491 cross-device oracle), environment helpers. These
are the kernel-correctness oracles the whole reference test suite leans on
(SURVEY §4); the TPU analog of check_consistency runs the same function on
cpu and the accelerator backend.
"""
from __future__ import annotations

import contextlib
import os

import numpy as onp

from .base import MXNetError
from .numpy.multiarray import ndarray

_DTYPE_TOL = {
    onp.dtype("float16"): (1e-2, 1e-2),
    onp.dtype("float32"): (1e-4, 1e-5),
    onp.dtype("float64"): (1e-6, 1e-8),
}


def default_rtol_atol(*arrays):
    rtol, atol = 1e-5, 1e-7
    for a in arrays:
        dt = onp.dtype(str(a.dtype)) if str(a.dtype) != "bfloat16" else None
        if dt is None:
            return (1e-2, 1e-2)
        if dt in _DTYPE_TOL:
            r, t = _DTYPE_TOL[dt]
            rtol, atol = max(rtol, r), max(atol, t)
    return rtol, atol


def _to_np(a):
    if isinstance(a, ndarray):
        return a.asnumpy()
    return onp.asarray(a)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Reference: test_utils.py:656."""
    a_np, b_np = _to_np(a), _to_np(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(a_np if not hasattr(a, "dtype") else a,
                                 b_np if not hasattr(b, "dtype") else b)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    onp.testing.assert_allclose(a_np.astype(onp.float64),
                                b_np.astype(onp.float64),
                                rtol=rtol, atol=atol, equal_nan=equal_nan,
                                err_msg=f"{names[0]} vs {names[1]}")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def check_numeric_gradient(f, inputs, grads=None, eps=1e-3, rtol=1e-2,
                           atol=1e-4):
    """Finite-difference gradient check (reference: test_utils.py:1044).

    f: callable(list of ndarrays) -> scalar ndarray. inputs: list of
    ndarrays with attach_grad() to compare against; if grads is given, it is
    the list of analytic grads instead.
    """
    from . import autograd
    from .numpy import array

    if grads is None:
        for x in inputs:
            x.attach_grad()
        with autograd.record():
            out = f(inputs)
        out.backward()
        grads = [x.grad.asnumpy() for x in inputs]

    for xi, x in enumerate(inputs):
        base = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(base)
        flat = base.reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            xs = list(inputs)
            xs[xi] = array(base.reshape(x.shape).astype(onp.float32))
            fp = float(f(xs).asnumpy().sum())
            flat[i] = orig - eps
            xs[xi] = array(base.reshape(x.shape).astype(onp.float32))
            fm = float(f(xs).asnumpy().sum())
            flat[i] = orig
            ng_flat[i] = (fp - fm) / (2 * eps)
        onp.testing.assert_allclose(grads[xi], num_grad, rtol=rtol, atol=atol,
                                    err_msg=f"input {xi} gradient mismatch")


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run fn on several backends and compare (reference: test_utils.py:1491
    — the cross-device kernel oracle). ctx_list defaults to [cpu, default]."""
    import jax
    from .numpy import array
    results = []
    platforms = ["cpu"]
    if jax.devices()[0].platform != "cpu":
        platforms.append(jax.devices()[0].platform)
    for plat in platforms:
        dev = jax.devices(plat)[0] if plat != "axon" else jax.devices()[0]
        placed = [array(x.asnumpy() if isinstance(x, ndarray) else x)
                  for x in inputs]
        with jax.default_device(dev):
            results.append(fn(placed))
    for r in results[1:]:
        assert_almost_equal(results[0], r, rtol, atol)
    return results


@contextlib.contextmanager
def environment(*args):
    """Scoped env vars (reference: test_utils.py environment)."""
    if len(args) == 2:
        updates = {args[0]: args[1]}
    else:
        updates = args[0]
    old = {k: os.environ.get(k) for k in updates}
    try:
        for k, v in updates.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def rand_ndarray(shape, dtype="float32", scale=1.0):
    from .numpy import random as npr
    return npr.uniform(-scale, scale, size=shape, dtype=dtype)


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim).tolist())


def same(a, b):
    return onp.array_equal(_to_np(a), _to_np(b))


def effective_dtype(x):
    return x.dtype


def default_context():
    from .context import current_context
    return current_context()


def set_default_context(ctx):
    ctx.__enter__()


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def assert_allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    """numpy-style allclose assert (reference test_utils.py
    assert_allclose, a thin alias the op suites use)."""
    onp.testing.assert_allclose(_to_np(a), _to_np(b), rtol=rtol, atol=atol,
                                equal_nan=equal_nan)


def assert_exception(f, exception_type, *args, **kwargs):
    """Assert calling f raises exception_type (reference
    test_utils.py assert_exception)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(
        f"{f} did not raise {exception_type.__name__}")


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1),
            onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1),
            onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_coord_2d(x_low, x_high, y_low, y_high):
    x = onp.random.randint(x_low, x_high)
    y = onp.random.randint(y_low, y_high)
    return x, y


def random_arrays(*shapes):
    """Random float32 host arrays; a single shape returns one array.
    A shape may be a tuple/list, an int (1-D length), or () for a
    0-d scalar (reference test_utils.py random_arrays)."""
    def one(s):
        if isinstance(s, int):
            s = (s,)
        elif not isinstance(s, (list, tuple)):
            raise MXNetError(f"shape must be int or tuple, got {s!r}")
        if len(s) == 0:
            return onp.asarray(onp.random.randn(), "float32")
        return onp.random.randn(*s).astype("float32")

    arrays = [one(s) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def random_sample(population, k):
    """Sample WITHOUT replacement, order preserved by draw (reference
    test_utils.py random_sample)."""
    import random as _random_mod
    return _random_mod.sample(list(population), k)


def same_array(a, b):
    """True when two mx arrays alias one device buffer (reference
    test_utils.py same_array — it mutates to prove aliasing; device
    buffers are immutable here, so compare the underlying buffer
    identity instead)."""
    ra = a._data if isinstance(a, ndarray) else a
    rb = b._data if isinstance(b, ndarray) else b
    return ra is rb


def check_speed(f, *args, n=20, warmup=3, **kwargs):
    """Average seconds per call (reference test_utils.py check_speed);
    syncs via engine.wait_all so async dispatch doesn't flatter."""
    import time

    from . import engine
    for _ in range(warmup):
        f(*args, **kwargs)
    engine.wait_all()
    t0 = time.perf_counter()
    for _ in range(n):
        f(*args, **kwargs)
    engine.wait_all()
    return (time.perf_counter() - t0) / n


def gen_buckets_probs_with_ppf(ppf, num_buckets):
    """Equal-probability buckets from a percent-point function
    (reference test_utils.py gen_buckets_probs_with_ppf)."""
    probs = [1.0 / num_buckets] * num_buckets
    edges = [ppf(i / num_buckets) for i in range(num_buckets + 1)]
    buckets = [(edges[i], edges[i + 1]) for i in range(num_buckets)]
    return buckets, probs


def chi_square_check(generator, buckets, probs, nsamples=1000000):
    """Chi-square goodness-of-fit for an i.i.d. sampler (reference
    test_utils.py:2108). Returns (p_value, obs_freq, expected_freq).
    The survival function is gammaincc(df/2, chi2/2) (no scipy in this
    image; jax.scipy.special supplies the regularized gamma)."""
    from jax.scipy.special import gammaincc

    samples = onp.asarray(_to_np(generator(nsamples))).ravel()
    continuous = isinstance(buckets[0], (tuple, list))
    obs = onp.zeros(len(buckets))
    if continuous:
        # per-bucket low/high membership so samples in a gap between
        # non-contiguous buckets are excluded, not mis-tallied
        for i, (lo, hi) in enumerate(buckets):
            obs[i] = ((samples >= lo) & (samples < hi)).sum()
    else:
        for i, v in enumerate(buckets):
            obs[i] = (samples == v).sum()
    exp = onp.asarray(probs, "float64") * samples.size
    chi2 = float(((obs - exp) ** 2 / exp).sum())
    df = len(buckets) - 1
    p = float(gammaincc(df / 2.0, chi2 / 2.0))
    return p, obs, exp


def verify_generator(generator, buckets, probs, nsamples=1000000,
                     nrepeat=5, success_rate=0.25, alpha=0.05):
    """Repeat the chi-square test; pass when >= success_rate of the
    repeats clear alpha (reference test_utils.py verify_generator —
    RNG tests are statistical, single runs flake)."""
    ps = [chi_square_check(generator, buckets, probs, nsamples)[0]
          for _ in range(nrepeat)]
    successes = sum(p > alpha for p in ps)
    if successes / nrepeat < success_rate:
        raise AssertionError(
            f"generator failed the chi-square test: p values {ps} "
            f"(needed {success_rate:.0%} above alpha={alpha})")
    return ps

"""mx.stream — deterministic sharded streaming data plane.

The production IO surface (ROADMAP item 3): a streaming dataset over
sharded recordio archives that survives the same faults the compute
plane already does (docs/FAULT_TOLERANCE.md "Streaming data plane").

- **Shards**: :class:`ShardWriter` (driven by tools/make_shards.py, the
  im2rec.py analog) packs records round-robin into N ``shard-*.rec`` /
  ``.idx`` archives plus a ``manifest.json``.  Every record carries a
  12-byte envelope — ``<QI`` global record id + crc32 of the payload —
  so corruption is caught per record, not per file.  Global record id
  ``g`` lives in shard ``g % N`` at key ``g // N`` (a pure function:
  no offset table to keep consistent).
- **Determinism**: :class:`EpochPlan` derives the shard order from a
  seeded permutation of ``(seed, epoch)`` and each shard's sample order
  from ``(seed, epoch, shard)`` — the same SeedSequence idiom as
  RandomSampler, so an epoch is a pure function of the seed.
- **Assignment**: shard at position ``p`` of the shuffled order belongs
  to host ``p % dp`` — the dp axis of the :class:`MeshConfig` the
  training step runs under.
- **Cursor**: exactly ``(shard list, seed, offset)``.
  :class:`StreamSampler` is a DataLoader batch sampler whose
  ``state_dict(cursor=served_batches)`` snapshots the epoch's work-item
  list plus the served-batch count; it rides the elastic TrainState
  bundle through the existing ``loader`` slot, travels inside the
  crash-atomic checkpoint, and replays bitwise: resume regenerates the
  epoch from the stored items and skips the consumed prefix (the
  BatchSampler idiom), so batch boundaries are identical to the
  uninterrupted run.
- **Reassignment**: on host loss the FleetSupervisor calls
  :meth:`StreamSampler.take_over_host`: the dead host's *remaining*
  work (rolled forward from its last published ``stream-<rank>.json``
  cursor) is dealt deterministically across the survivors, each shard
  adopted exactly once (a per-epoch adopted-set guards re-entry).
  Records the dead host served after its last checkpoint were never
  durable — the training steps they fed rolled back with the bundle —
  so re-serving them keeps the epoch's served-record multiset exact:
  union over hosts and restarts == the epoch's record ids, multiplicity
  one (the test oracle in tests/test_stream.py).
- **Robustness**: per-record checksums with the ``stream.torn_record``
  / ``stream.shard_unreadable`` fault points; ``stream.on_corrupt``
  picks skip-with-count vs structured :class:`CorruptRecord`
  escalation; shard opens retry with bounded backoff and escalate as a
  WorkerLost-style :class:`ShardUnreadable`, never a hang.  All of it
  is visible as ``stream.*`` metrics and ``stream``-category trace
  spans; disabled, every hook is one module-attribute read (gated by
  benchmark/telemetry_overhead.py).
"""
from __future__ import annotations

import binascii
import io
import json
import os
import struct
import threading
import time

import numpy as onp

from . import config as _config
from . import fault as _fault
from . import telemetry as _telemetry
from . import trace as _trace
from .base import MXNetError
from .recordio import MXIndexedRecordIO, RecordIOCorrupt
from .resilience import WorkerLost

__all__ = ["ShardWriter", "ShardManifest", "StreamDataset", "StreamSampler",
           "EpochPlan", "CorruptRecord", "ShardUnreadable", "encode_record",
           "decode_record", "pack_sample", "unpack_sample",
           "validate_manifest", "read_cursor", "remaining_items"]

_telemetry.declare_metric(
    "stream.shards_assigned", "gauge",
    "shards this host owns for the epoch in progress (adopted shards "
    "from dead peers included)")
_telemetry.declare_metric(
    "stream.shards_completed_total", "counter",
    "shards this host served to the end (every record of the shard's "
    "epoch order emitted)")
_telemetry.declare_metric(
    "stream.shards_reassigned_total", "counter",
    "shards adopted from dead hosts via take_over_host — each exactly "
    "once per epoch")
_telemetry.declare_metric(
    "stream.records_served_total", "counter",
    "records read, checksum-verified and handed to the consumer")
_telemetry.declare_metric(
    "stream.records_skipped_total", "counter",
    "corrupt records dropped under stream.on_corrupt=skip")
_telemetry.declare_metric(
    "stream.open_retries_total", "counter",
    "shard-open attempts that failed and were retried with backoff")


def _count(name, n=1):
    if _telemetry._active:
        _telemetry.inc(name, n)


def _gauge(name, value):
    if _telemetry._active:
        _telemetry.set_gauge(name, value)


def _note_served(n=1):
    """The per-record hot-path hook (benchmark/telemetry_overhead.py
    probes this exact function with telemetry disabled)."""
    if _telemetry._active:
        _telemetry.inc("stream.records_served_total", n)


# ---------------------------------------------------------------------------
# record envelope
# ---------------------------------------------------------------------------

_REC_FORMAT = "<QI"       # global record id, crc32(payload)
_REC_SIZE = struct.calcsize(_REC_FORMAT)


class CorruptRecord(MXNetError):
    """A streamed record failed validation.  Structured so policy code
    can dispatch on the fields: ``shard`` (archive basename), ``record_id``
    (global id, None when the envelope itself is unreadable), ``kind``
    (``checksum`` | ``short_envelope`` | ``id_mismatch`` | ``missing`` |
    ``torn_tail`` | ``bad_magic``)."""

    def __init__(self, shard, record_id, kind, detail=""):
        self.shard = shard
        self.record_id = record_id
        self.kind = kind
        extra = f": {detail}" if detail else ""
        super().__init__(
            f"corrupt record {record_id} in shard {shard!r} [{kind}]{extra}")


class ShardUnreadable(WorkerLost):
    """A shard archive could not be opened after the bounded
    retry-with-backoff budget — the data-plane analog of a collective
    that exhausted its retries, so it reuses the WorkerLost structure
    (``op``/``key``/``attempts``/``last``) supervisors already dispatch
    on."""

    def __init__(self, shard, rank, attempts, last):
        super().__init__(op="shard_open", key=shard, rank=rank, nprocs=1,
                         attempts=attempts, last=last)
        self.shard = shard


def encode_record(record_id, payload):
    """Wrap ``payload`` bytes in the checksummed stream envelope."""
    crc = binascii.crc32(payload) & 0xffffffff
    return struct.pack(_REC_FORMAT, int(record_id), crc) + payload


def decode_record(buf, shard="?", expect_id=None):
    """Validate and strip the envelope: returns ``(record_id, payload)``
    or raises :class:`CorruptRecord`."""
    if buf is None or len(buf) < _REC_SIZE:
        raise CorruptRecord(shard, expect_id, "short_envelope",
                            f"{0 if buf is None else len(buf)} bytes")
    rid, crc = struct.unpack(_REC_FORMAT, buf[:_REC_SIZE])
    payload = buf[_REC_SIZE:]
    if binascii.crc32(payload) & 0xffffffff != crc:
        raise CorruptRecord(shard, rid, "checksum")
    if expect_id is not None and rid != int(expect_id):
        raise CorruptRecord(shard, rid, "id_mismatch",
                            f"expected {expect_id}")
    return rid, payload


def pack_sample(*arrays):
    """Serialize numpy arrays into one payload (npz container)."""
    bio = io.BytesIO()
    onp.savez(bio, *[onp.asarray(a) for a in arrays])
    return bio.getvalue()


def unpack_sample(payload):
    """Inverse of :func:`pack_sample`: one array, or a tuple of them."""
    with onp.load(io.BytesIO(payload)) as z:
        arrays = [z[k] for k in z.files]
    return arrays[0] if len(arrays) == 1 else tuple(arrays)


# ---------------------------------------------------------------------------
# shard archives + manifest
# ---------------------------------------------------------------------------

MANIFEST_NAME = "manifest.json"


class ShardWriter:
    """Pack records round-robin into N checksummed shard archives.

    Record ``g`` goes to shard ``g % num_shards`` at key
    ``g // num_shards`` — the id→location map every reader derives
    without a table.  ``close()`` writes the manifest and returns its
    path."""

    def __init__(self, out_dir, num_shards, prefix="shard"):
        if num_shards < 1:
            raise MXNetError(f"num_shards={num_shards} must be >= 1")
        self.out_dir = out_dir
        self.num_shards = int(num_shards)
        self.prefix = prefix
        os.makedirs(out_dir, exist_ok=True)
        self._names = [f"{prefix}-{i:05d}" for i in range(self.num_shards)]
        self._writers = [
            MXIndexedRecordIO(os.path.join(out_dir, n + ".idx"),
                              os.path.join(out_dir, n + ".rec"), "w")
            for n in self._names]
        self._counts = [0] * self.num_shards
        self.total = 0

    def append(self, payload):
        """Append one record; returns its global record id."""
        gid = self.total
        s = gid % self.num_shards
        self._writers[s].write_idx(gid // self.num_shards,
                                   encode_record(gid, payload))
        self._counts[s] += 1
        self.total += 1
        return gid

    def close(self):
        for w in self._writers:
            w.close()
        doc = {"version": 1, "assignment": "round_robin",
               "num_shards": self.num_shards, "total_records": self.total,
               "shards": [{"rec": n + ".rec", "idx": n + ".idx",
                           "records": c}
                          for n, c in zip(self._names, self._counts)]}
        path = os.path.join(self.out_dir, MANIFEST_NAME)
        from .serialization import atomic_write_bytes
        atomic_write_bytes(path, json.dumps(doc, indent=1).encode())
        return path

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ShardManifest:
    """Parsed manifest: shard entries with paths resolved against the
    manifest's directory."""

    def __init__(self, doc, root):
        if doc.get("version") != 1:
            raise MXNetError(f"unsupported manifest version "
                             f"{doc.get('version')!r}")
        self.root = root
        self.num_shards = int(doc["num_shards"])
        self.total_records = int(doc["total_records"])
        self.shards = doc["shards"]
        if len(self.shards) != self.num_shards:
            raise MXNetError(
                f"manifest lists {len(self.shards)} shards, "
                f"num_shards={self.num_shards}")

    @classmethod
    def load(cls, path):
        if os.path.isdir(path):
            path = os.path.join(path, MANIFEST_NAME)
        with open(path) as f:
            return cls(json.load(f), os.path.dirname(os.path.abspath(path)))

    def rec_path(self, shard_idx):
        return os.path.join(self.root, self.shards[shard_idx]["rec"])

    def idx_path(self, shard_idx):
        return os.path.join(self.root, self.shards[shard_idx]["idx"])

    def records(self, shard_idx):
        return int(self.shards[shard_idx]["records"])


def _as_manifest(manifest):
    if isinstance(manifest, ShardManifest):
        return manifest
    return ShardManifest.load(manifest)


def validate_manifest(manifest):
    """Re-read every record of every shard and verify its checksum and
    id (the ``tools/make_shards.py --validate`` body).  Returns a
    summary dict; corruption lands in ``errors`` instead of raising so
    one torn shard doesn't hide the rest."""
    m = _as_manifest(manifest)
    errors = []
    records = 0
    for s in range(m.num_shards):
        try:
            rdr = MXIndexedRecordIO(m.idx_path(s), m.rec_path(s), "r")
        except OSError as e:
            errors.append(f"shard {s}: unreadable: {e}")
            continue
        try:
            for key in range(m.records(s)):
                gid = key * m.num_shards + s
                try:
                    decode_record(rdr.read_idx(key),
                                  shard=m.shards[s]["rec"], expect_id=gid)
                    records += 1
                except (KeyError, CorruptRecord, RecordIOCorrupt) as e:
                    errors.append(f"shard {s} record {gid}: {e}")
        finally:
            rdr.close()
    return {"shards": m.num_shards, "records": records,
            "expected_records": m.total_records, "errors": errors,
            "ok": not errors and records == m.total_records}


# ---------------------------------------------------------------------------
# epoch plan: seeded shard shuffle + within-shard seeded sample shuffle
# ---------------------------------------------------------------------------

def _seed32(*parts):
    return int(onp.random.SeedSequence(list(parts)).generate_state(1)[0])


class EpochPlan:
    """The epoch as a pure function of ``(seed, epoch)``: a seeded
    permutation of the shards, and per shard a seeded permutation of its
    records (global ids)."""

    def __init__(self, manifest, seed, epoch):
        self.manifest = _as_manifest(manifest)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.shard_order = onp.random.RandomState(
            _seed32(self.seed, self.epoch)) \
            .permutation(self.manifest.num_shards).tolist()

    def shard_records(self, shard_idx):
        """This shard's record ids in the epoch's serving order."""
        n = self.manifest.records(shard_idx)
        perm = onp.random.RandomState(
            _seed32(self.seed, self.epoch, shard_idx + 1)).permutation(n)
        num = self.manifest.num_shards
        return [int(k) * num + shard_idx for k in perm]

    def host_shards(self, rank, dp):
        """Shards owned by ``rank`` on a ``dp``-way mesh: position ``p``
        of the shuffled order belongs to host ``p % dp``."""
        dp = max(1, int(dp))
        return [s for p, s in enumerate(self.shard_order) if p % dp == rank]


# ---------------------------------------------------------------------------
# dataset facade (random access by global record id)
# ---------------------------------------------------------------------------

_seq_lock = threading.Lock()
_open_seq = 0      # global shard-open attempt counter (fault injection key)
_read_seq = 0      # global record-read counter (fault injection key)


class StreamDataset:
    """Random-access facade over the shard set: index = global record
    id.  Plugs into the existing DataLoader machinery (thread pool,
    spawn workers + shm ring, device prefetch) unchanged; the
    ``sample_batch`` hook additionally carries the corrupt-record
    policy, which per-item ``__getitem__`` cannot express (a skipped
    record must shrink the batch, not return a placeholder)."""

    def __init__(self, manifest, transform=None):
        self._manifest = _as_manifest(manifest)
        self._transform = transform
        self._readers = {}
        self._lock = threading.Lock()

    @property
    def manifest(self):
        return self._manifest

    def __len__(self):
        return self._manifest.total_records

    # readers are per-process: spawn workers re-open lazily
    def __getstate__(self):
        d = dict(self.__dict__)
        d["_readers"] = {}
        d["_lock"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.Lock()

    def _open(self, shard_idx):
        """Open (and cache) one shard reader, with bounded
        retry-with-backoff; exhaustion escalates :class:`ShardUnreadable`
        — a structured failure, never a hang."""
        global _open_seq
        rdr = self._readers.get(shard_idx)
        if rdr is not None:
            return rdr
        name = self._manifest.shards[shard_idx]["rec"]
        retries = max(0, int(_config.get("stream.open_retries")))
        backoff = float(_config.get("stream.open_backoff"))
        last = None
        for attempt in range(1, retries + 2):
            with _seq_lock:
                _open_seq += 1
                seq = _open_seq
            try:
                if _fault._active and _fault.fire("stream.shard_unreadable",
                                                  step=seq):
                    raise OSError(f"injected open failure for {name} "
                                  "(stream.shard_unreadable)")
                with _trace.span("stream.shard_open", category="stream",
                                 shard=name, attempt=attempt):
                    rdr = MXIndexedRecordIO(self._manifest.idx_path(shard_idx),
                                            self._manifest.rec_path(shard_idx),
                                            "r")
                self._readers[shard_idx] = rdr
                return rdr
            except OSError as e:
                last = e
                if attempt <= retries:
                    _count("stream.open_retries_total")
                    time.sleep(backoff * attempt)
        _fault.record("stream.shard_lost")
        raise ShardUnreadable(shard=name, rank=0, attempts=retries + 1,
                              last=last)

    def _read(self, gid):
        """Read + validate one record; returns ``(record_id, payload)``."""
        global _read_seq
        gid = int(gid)
        if not 0 <= gid < self._manifest.total_records:
            raise MXNetError(f"record id {gid} outside "
                             f"[0, {self._manifest.total_records})")
        shard_idx = gid % self._manifest.num_shards
        key = gid // self._manifest.num_shards
        name = self._manifest.shards[shard_idx]["rec"]
        rdr = self._open(shard_idx)
        with self._lock:     # readers seek: one reader position per process
            if _fault._active:
                with _seq_lock:
                    _read_seq += 1
                    seq = _read_seq
                torn = _fault.fire("stream.torn_record", step=seq)
            else:
                torn = False
            try:
                buf = rdr.read_idx(key)
            except KeyError:
                raise CorruptRecord(name, gid, "missing",
                                    "key absent from shard index")
        if torn and buf and len(buf) > _REC_SIZE:
            # flip one payload byte BEFORE verification: the checksum,
            # not the injection, is what must catch it
            pos = _REC_SIZE + (gid % (len(buf) - _REC_SIZE))
            buf = buf[:pos] + bytes([buf[pos] ^ 0xFF]) + buf[pos + 1:]
        rid, payload = decode_record(buf, shard=name, expect_id=gid)
        _note_served(1)
        return rid, payload

    def __getitem__(self, gid):
        """Per-item access always raises on corruption — the skip policy
        needs batch context (see :meth:`sample_batch`)."""
        payload = self._read(gid)[1]
        return self._transform(payload) if self._transform else payload

    def sample_batch(self, gids):
        """Batch fetch with the ``stream.on_corrupt`` policy applied:
        ``skip`` drops corrupt records (counted), ``raise`` escalates the
        structured :class:`CorruptRecord`."""
        policy = _config.get("stream.on_corrupt")
        out = []
        for gid in gids:
            try:
                payload = self._read(gid)[1]
            except CorruptRecord:
                if policy != "skip":
                    raise
                _count("stream.records_skipped_total")
                _fault.record("stream.record_skipped")
                continue
            out.append(self._transform(payload) if self._transform
                       else payload)
        if gids and not out:
            raise CorruptRecord(None, None, "checksum",
                                f"all {len(gids)} records of the batch "
                                "corrupt under skip policy")
        return out


# ---------------------------------------------------------------------------
# cursor publication (shared dir, HealthPlane-lease idiom)
# ---------------------------------------------------------------------------

CURSOR_PREFIX = "stream-"


def _cursor_path(cursor_dir, rank):
    return os.path.join(cursor_dir, f"{CURSOR_PREFIX}{int(rank)}.json")


def read_cursor(cursor_dir, rank):
    """A host's last published cursor, or None (absent or torn —
    readers never see a partial file thanks to the tmp+replace write,
    but a missing one is normal before the first checkpoint)."""
    try:
        with open(_cursor_path(cursor_dir, rank)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def remaining_items(manifest, state):
    """Roll a published cursor forward: the ``[shard, offset]`` work its
    owner had NOT yet served when the cursor was taken.  The cursor's
    ``consumed`` record count (falling back to ``cursor * batch_size``
    for pre-field cursors) is walked through the item list in order."""
    m = _as_manifest(manifest)
    consumed = int(state.get(
        "consumed", int(state["cursor"]) * int(state["batch_size"])))
    out = []
    for shard, off in state["items"]:
        avail = m.records(int(shard)) - int(off)
        take = min(avail, consumed)
        consumed -= take
        if take < avail:
            out.append([int(shard), int(off) + take])
    return out


# ---------------------------------------------------------------------------
# the streaming batch sampler (the cursor lives here)
# ---------------------------------------------------------------------------

class StreamSampler:
    """DataLoader batch sampler over this host's shard assignment.

    The epoch's work is a list of ``[shard, start_offset]`` items walked
    in order, batches spanning shard boundaries; the cursor is exactly
    ``(shard list, seed, offset)``: ``state_dict(cursor=k)`` records the
    epoch-start items, ``k`` served batches and the record count those
    batches held, and resume regenerates the identical epoch and skips
    that many *records* — bitwise batch parity with the uninterrupted
    run, and exact multiplicity even when shards were adopted after a
    partial tail batch.  The DataLoader drives the
    ``cursor=`` argument with its consumer-side served count, so the
    cursor that lands in the TrainState bundle never counts prefetched-
    but-unconsumed batches.
    """

    def __init__(self, manifest, batch_size, seed=0, dp=1, rank=0,
                 last_batch="keep", cursor_dir=None):
        if batch_size < 1:
            raise MXNetError(f"batch_size={batch_size} must be >= 1")
        if not 0 <= int(rank) < max(1, int(dp)):
            raise MXNetError(f"rank={rank} outside dp={dp}")
        if last_batch not in ("keep", "discard"):
            raise MXNetError(f"last_batch={last_batch!r} not in "
                             "('keep', 'discard')")
        self._manifest = _as_manifest(manifest)
        self._bs = int(batch_size)
        self._seed = int(seed)
        self._dp = max(1, int(dp))
        self._rank = int(rank)
        self._last_batch = last_batch
        self._cursor_dir = cursor_dir
        self._epoch = 0
        self._resume = None
        self._epoch_items = []   # [[shard, start_offset], ...] at epoch start
        self._pending = []       # live queue: [[shard, next_offset], ...]
        self._emitted = 0        # batches generated this epoch
        self._k0 = 0             # batches the current epoch resumed past
        self._cum = [0]          # records consumed after k0+j batches
        self._adopted = set()    # (epoch, shard) pairs taken over — once
        self._lock = threading.Lock()

    # -- epoch generation -------------------------------------------------

    def _fresh_items(self, epoch, rank=None, dp=None):
        plan = EpochPlan(self._manifest, self._seed, epoch)
        shards = plan.host_shards(self._rank if rank is None else rank,
                                  self._dp if dp is None else dp)
        return [[s, 0] for s in shards]

    def __iter__(self):
        if self._resume is not None:
            st, self._resume = self._resume, None
            self._epoch = int(st["epoch"])
            k0 = int(st.get("cursor", 0))
            to_skip = int(st.get("consumed", k0 * self._bs))
            items = [[int(s), int(o)] for s, o in st["items"]]
        else:
            self._epoch += 1
            k0, to_skip = 0, 0
            items = self._fresh_items(self._epoch)
        plan = EpochPlan(self._manifest, self._seed, self._epoch)
        with self._lock:
            self._epoch_items = [list(it) for it in items]
            self._pending = [list(it) for it in items]
            self._emitted = k0
            self._k0 = k0
            self._cum = [to_skip]
        _gauge("stream.shards_assigned", len(items))
        batch = []

        def _emit(b):
            with self._lock:
                self._emitted += 1
                self._cum.append(self._cum[-1] + len(b))

        while True:
            with self._lock:
                if not self._pending:
                    break
                shard, off = self._pending[0]
            order = plan.shard_records(shard)
            if to_skip:
                # resume skips RECORDS, not batches: batch boundaries may
                # legitimately shift when shards were adopted after this
                # host's own tail batch, but record multiplicity never does
                step = min(to_skip, len(order) - off)
                to_skip -= step
                off += step
                with self._lock:
                    self._pending[0][1] = off
            for i in range(off, len(order)):
                batch.append(order[i])
                with self._lock:
                    self._pending[0][1] = i + 1
                if len(batch) == self._bs:
                    _emit(batch)
                    yield batch
                    batch = []
            with self._lock:
                self._pending.pop(0)
            _count("stream.shards_completed_total")
            _gauge("stream.shards_assigned", len(self._pending))
        if batch and self._last_batch == "keep":
            _emit(batch)
            yield batch

    def __len__(self):
        # next epoch's assignment (or the pending resume's items)
        if self._resume is not None:
            items = self._resume["items"]
            consumed = int(self._resume.get(
                "consumed", int(self._resume.get("cursor", 0)) * self._bs))
        else:
            items = self._fresh_items(self._epoch + 1)
            consumed = 0
        n = sum(self._manifest.records(int(s)) - int(o) for s, o in items)
        n = max(0, n - consumed)
        return ((n + self._bs - 1) // self._bs if self._last_batch == "keep"
                else n // self._bs)

    # -- elastic resume (the TrainState bundle contract) ------------------

    def state_dict(self, cursor=None):
        with self._lock:
            items = [list(it) for it in self._epoch_items]
            cum = list(self._cum)
            k0 = self._k0
            emitted = self._emitted
        k = emitted if cursor is None else int(cursor)
        j = min(max(k - k0, 0), len(cum) - 1)
        consumed = cum[j] if k >= k0 else k * self._bs
        return {"seed": self._seed, "epoch": self._epoch, "cursor": k,
                "consumed": consumed, "batch_size": self._bs,
                "dp": self._dp, "rank": self._rank, "items": items}

    def load_state_dict(self, state):
        if int(state.get("batch_size", self._bs)) != self._bs:
            raise MXNetError(
                f"cursor batch_size {state.get('batch_size')} != sampler "
                f"batch_size {self._bs}: batch boundaries would shift and "
                "the bitwise-replay contract breaks")
        if int(state.get("seed", self._seed)) != self._seed:
            raise MXNetError(
                f"cursor seed {state.get('seed')} != sampler seed "
                f"{self._seed}: the epoch plans differ")
        k = int(state.get("cursor", 0))
        self._resume = {"epoch": int(state["epoch"]), "cursor": k,
                        "consumed": int(state.get("consumed", k * self._bs)),
                        "items": [[int(s), int(o)]
                                  for s, o in state["items"]]}

    def resume_cursor(self):
        """Batches a pending resume will skip (0 when none is pending)."""
        return int(self._resume["cursor"]) if self._resume else 0

    # -- fleet integration: publish + exactly-once take-over --------------

    def publish_cursor(self, cursor=None, cursor_dir=None, rank=None):
        """Atomically publish this host's cursor as
        ``stream-<rank>.json`` next to the heartbeat leases (tmp +
        os.replace, the HealthPlane idiom) so survivors can resume a
        dead host's shards from its last *checkpointed* position.
        Returns the path, or None without a cursor dir."""
        d = cursor_dir or self._cursor_dir or _config.get("fleet.lease_dir")
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        path = _cursor_path(d, self._rank if rank is None else rank)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(self.state_dict(cursor=cursor)))
        os.replace(tmp, path)
        return path

    def take_over_host(self, dead_rank, survivors=None, cursor_dir=None):
        """Adopt this host's share of a dead host's unfinished shards.

        The dead host's remaining work is rolled forward from its last
        published cursor (no cursor = no durable progress: its whole
        epoch share restarts at offset 0).  Work item ``j`` goes to
        ``survivors[j % len(survivors)]`` — every survivor runs the same
        deterministic split, so each shard lands on exactly one of them;
        a per-epoch adopted-set makes re-entry (double lose_host, two
        supervisors racing) a no-op.  Returns the number of shards
        adopted locally."""
        dead_rank = int(dead_rank)
        d = cursor_dir or self._cursor_dir or _config.get("fleet.lease_dir")
        st = read_cursor(d, dead_rank) if d else None
        if (st is not None and int(st.get("epoch", -1)) == self._epoch
                and int(st.get("seed", self._seed)) == self._seed):
            items = remaining_items(self._manifest, st)
        else:
            # pre-checkpoint death (or another epoch's stale cursor):
            # nothing it served was durable, re-serve its share in full
            items = self._fresh_items(
                self._epoch, rank=dead_rank,
                dp=int(st["dp"]) if st else self._dp)
        alive = sorted(h for h in (survivors if survivors is not None
                                   else [self._rank]) if h != dead_rank)
        if self._rank not in alive:
            return 0
        mine = [it for j, it in enumerate(items)
                if alive[j % len(alive)] == self._rank]
        adopted = 0
        with self._lock:
            for shard, off in mine:
                key = (self._epoch, int(shard))
                if key in self._adopted:
                    continue     # exactly once
                self._adopted.add(key)
                self._pending.append([int(shard), int(off)])
                self._epoch_items.append([int(shard), int(off)])
                adopted += 1
            assigned = len(self._pending)
        if adopted:
            _count("stream.shards_reassigned_total", adopted)
            _gauge("stream.shards_assigned", assigned)
            with _trace.span("stream.reassign", category="stream",
                             dead_host=dead_rank, shards=adopted,
                             survivor=self._rank):
                pass
        _fault.record("stream.take_over")
        return adopted

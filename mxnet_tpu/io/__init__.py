"""mx.io — legacy data iterators.

Reference parity: python/mxnet/io/io.py (DataIter/DataBatch/NDArrayIter,
MXDataIter wrapping the C++ threaded iterators of src/io/). The Gluon
DataLoader is the modern path; these iterators exist for MXNet-1.x-style
training loops (Module-era scripts and the estimator).
"""
from __future__ import annotations

import collections

import numpy as onp

from .. import numpy as _np
from ..base import MXNetError
from ..numpy.multiarray import ndarray

DataDesc = collections.namedtuple("DataDesc", ["name", "shape"])


class DataBatch:
    """Reference: io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Reference: io.py DataIter."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    __next__ = next

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


class NDArrayIter(DataIter):
    """Reference: io.py NDArrayIter (dict/list/array data, shuffle,
    last_batch_handle pad/discard/roll_over)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self.idx = onp.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]))
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, v in arrays:
            lo = self.cursor
            hi = min(self.cursor + self.batch_size, self.num_data)
            sel = self.idx[lo:hi]
            part = v[sel]
            if hi - lo < self.batch_size and self.last_batch_handle == "pad":
                extra = self.batch_size - (hi - lo)
                pad_sel = self.idx[:extra]
                part = onp.concatenate([part, v[pad_sel]])
            out.append(_np.array(part))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise MXNetError("data required")
        return []
    if isinstance(data, (onp.ndarray, ndarray)):
        data = {default_name: data}
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}_{i}" if i else default_name: d
                for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        arr = v.asnumpy() if isinstance(v, ndarray) else onp.asarray(v)
        out.append((k, arr))
    return out


class ResizeIter(DataIter):
    """Reference: io.py ResizeIter (epoch-resize wrapper)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()

    __next__ = next


class PrefetchingIter(DataIter):
    """Reference: io.py PrefetchingIter (threaded prefetch)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import queue
        import threading
        self.iters = iters if isinstance(iters, list) else [iters]
        super().__init__(self.iters[0].batch_size)
        self._queue = queue.Queue(maxsize=2)
        self._stop = False
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def _worker():
            try:
                for batch in self.iters[0]:
                    if self._stop:
                        return
                    self._queue.put(batch)
            finally:
                self._queue.put(None)
        self._thread = threading.Thread(target=_worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop = True
        while not self._queue.empty():
            self._queue.get()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._stop = False
        for it in self.iters:
            it.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    __next__ = next

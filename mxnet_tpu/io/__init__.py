"""mx.io — legacy data iterators.

Reference parity: python/mxnet/io/io.py (DataIter/DataBatch/NDArrayIter,
MXDataIter wrapping the C++ threaded iterators of src/io/). The Gluon
DataLoader is the modern path; these iterators exist for MXNet-1.x-style
training loops (Module-era scripts and the estimator).
"""
from __future__ import annotations

import collections

import numpy as onp

from .. import numpy as _np
from ..base import MXNetError
from ..numpy.multiarray import ndarray

DataDesc = collections.namedtuple("DataDesc", ["name", "shape"])


class DataBatch:
    """Reference: io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Reference: io.py DataIter."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    __next__ = next

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


class NDArrayIter(DataIter):
    """Reference: io.py NDArrayIter (dict/list/array data, shuffle,
    last_batch_handle pad/discard/roll_over)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self.idx = onp.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]))
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, v in arrays:
            lo = self.cursor
            hi = min(self.cursor + self.batch_size, self.num_data)
            sel = self.idx[lo:hi]
            part = v[sel]
            if hi - lo < self.batch_size and self.last_batch_handle == "pad":
                extra = self.batch_size - (hi - lo)
                pad_sel = self.idx[:extra]
                part = onp.concatenate([part, v[pad_sel]])
            out.append(_np.array(part))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise MXNetError("data required")
        return []
    if isinstance(data, (onp.ndarray, ndarray)):
        data = {default_name: data}
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}_{i}" if i else default_name: d
                for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        arr = v.asnumpy() if isinstance(v, ndarray) else onp.asarray(v)
        out.append((k, arr))
    return out


class ResizeIter(DataIter):
    """Reference: io.py ResizeIter (epoch-resize wrapper)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()

    __next__ = next


class PrefetchingIter(DataIter):
    """Reference: io.py PrefetchingIter (threaded prefetch)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import queue
        import threading
        self.iters = iters if isinstance(iters, list) else [iters]
        super().__init__(self.iters[0].batch_size)
        self._queue = queue.Queue(maxsize=2)
        self._stop = False
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def _worker():
            try:
                for batch in self.iters[0]:
                    if self._stop:
                        return
                    self._queue.put(batch)
            finally:
                self._queue.put(None)
        self._thread = threading.Thread(target=_worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop = True
        while not self._queue.empty():
            self._queue.get()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._stop = False
        for it in self.iters:
            it.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    __next__ = next


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/iter_csv.cc, exposed as
    mx.io.CSVIter).  Loads the csv eagerly (host memory) and batches;
    `round_batch` wraps the tail batch with rows from the start, like the
    reference's default behavior."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 dtype="float32", **kwargs):
        super().__init__(batch_size)
        import numpy as onp
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        self._data = onp.loadtxt(data_csv, delimiter=",",
                                 dtype=dtype, ndmin=2)
        n = len(self._data)
        self._data = self._data.reshape((n,) + self.data_shape)
        if label_csv is not None:
            self._label = onp.loadtxt(label_csv, delimiter=",",
                                      dtype="float32", ndmin=2)
            self._label = self._label.reshape((n,) + self.label_shape)
        else:
            self._label = onp.zeros((n,) + self.label_shape, "float32")
        self._round = round_batch
        self._cursor = 0

    @property
    def provide_data(self):
        return [("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [("label", (self.batch_size,) + self.label_shape)]

    def reset(self):
        self._cursor = 0

    def next(self):
        import numpy as onp
        from ..numpy import array
        n = len(self._data)
        if self._cursor >= n:
            raise StopIteration
        idx = onp.arange(self._cursor, self._cursor + self.batch_size)
        self._cursor += self.batch_size
        pad = int(max(0, idx[-1] + 1 - n))
        if pad and not self._round:
            # short tail batch with no padding rows present
            idx, pad = idx[idx < n], 0
        idx = idx % n
        return DataBatch([array(self._data[idx])],
                         [array(self._label[idx])], pad=pad)

    __next__ = next


class LibSVMIter(DataIter):
    """LibSVM sparse-format iterator (reference: src/io/iter_libsvm.cc).
    Yields CSR batches via mxnet_tpu.ndarray.sparse.CSRNDArray, matching
    the reference's CSR storage for the data field."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 round_batch=True, **kwargs):
        super().__init__(batch_size)
        import numpy as onp
        self.data_shape = tuple(data_shape)
        indptr, indices, values, labels = [0], [], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    indices.append(int(k))
                    values.append(float(v))
                indptr.append(len(indices))
        self._indptr = onp.asarray(indptr, "int64")
        self._indices = onp.asarray(indices, "int64")
        self._values = onp.asarray(values, "float32")
        self._labels = onp.asarray(labels, "float32")
        self._round = round_batch
        self._cursor = 0

    @property
    def provide_data(self):
        return [("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [("label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        import numpy as onp
        from ..ndarray import sparse as _sp
        from ..numpy import array
        n = len(self._labels)
        if self._cursor >= n:
            raise StopIteration
        rows = onp.arange(self._cursor, self._cursor + self.batch_size)
        self._cursor += self.batch_size
        pad = int(max(0, rows[-1] + 1 - n))
        if pad and not self._round:
            # short tail batch with no wrapped rows
            rows, pad = rows[rows < n], 0
        rows = rows % n
        ptr = [0]
        idxs, vals = [], []
        for r in rows:
            lo, hi = self._indptr[r], self._indptr[r + 1]
            idxs.append(self._indices[lo:hi])
            vals.append(self._values[lo:hi])
            ptr.append(ptr[-1] + (hi - lo))
        data = _sp.csr_matrix(
            (onp.concatenate(vals) if vals else onp.zeros(0, "float32"),
             onp.concatenate(idxs) if idxs else onp.zeros(0, "int64"),
             onp.asarray(ptr, "int64")),
            shape=(len(rows),) + self.data_shape)
        return DataBatch([data], [array(self._labels[rows])], pad=pad)

    __next__ = next


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc).
    Reads local `image` / `label` idx(.gz) files."""

    def __init__(self, image, label, batch_size=1, shuffle=False,
                 flat=False, seed=0, **kwargs):
        super().__init__(batch_size)
        import gzip
        import struct as _struct
        import numpy as onp

        def read_idx(path):
            op = gzip.open if path.endswith(".gz") else open
            with op(path, "rb") as f:
                raw = f.read()
            magic, = _struct.unpack(">I", raw[:4])
            ndim = magic & 0xFF
            dims = _struct.unpack(">" + "I" * ndim, raw[4:4 + 4 * ndim])
            return onp.frombuffer(raw, onp.uint8,
                                  offset=4 + 4 * ndim).reshape(dims)

        self._images = read_idx(image).astype("float32") / 255.0
        self._labels = read_idx(label).astype("float32")
        if flat:
            self._images = self._images.reshape(len(self._images), -1)
        else:
            self._images = self._images[:, None, :, :]  # NCHW
        self._order = onp.arange(len(self._images))
        self._shuffle = shuffle
        self._sample_shape = self._images.shape[1:]
        self._rng = onp.random.RandomState(seed)
        self._cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [("data", (self.batch_size,) + self._sample_shape)]

    @property
    def provide_label(self):
        return [("label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            self._rng.shuffle(self._order)

    def next(self):
        from ..numpy import array
        n = len(self._order)
        if self._cursor + self.batch_size > n:
            raise StopIteration
        idx = self._order[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        return DataBatch([array(self._images[idx])],
                         [array(self._labels[idx])], pad=0)

    __next__ = next


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=1,
                    shuffle=False, label_width=1, resize=0, rand_crop=False,
                    rand_mirror=False, mean_r=0, mean_g=0, mean_b=0,
                    std_r=0, std_g=0, std_b=0, preprocess_threads=0,
                    **kwargs):
    """RecordIO image iterator with the C++ iterator's kwargs surface
    (reference: src/io/iter_image_recordio_2.cc, registered as
    mx.io.ImageRecordIter).  Maps decode -> augment -> batch onto
    image.ImageIter + CreateAugmenter; the native RecordIO reader
    (native/mxtpu_io.cc) provides the mmap + prefetch underneath."""
    import numpy as onp
    from .. import image as img_mod
    mean = (onp.array([mean_r, mean_g, mean_b], "float32")
            if (mean_r or mean_g or mean_b) else None)
    # unset std channels default to 1 (reference defaults), never 0
    std = (onp.array([std_r or 1.0, std_g or 1.0, std_b or 1.0], "float32")
           if (std_r or std_g or std_b) else None)
    aug = img_mod.CreateAugmenter(
        data_shape, resize=resize, rand_crop=rand_crop,
        rand_mirror=rand_mirror, mean=mean, std=std)
    return img_mod.ImageIter(batch_size, data_shape,
                             label_width=label_width,
                             path_imgrec=path_imgrec, shuffle=shuffle,
                             aug_list=aug, **kwargs)


def ImageDetRecordIter(path_imgrec=None, data_shape=None, batch_size=1,
                       shuffle=False, mean_r=0, mean_g=0, mean_b=0,
                       std_r=0, std_g=0, std_b=0, **kwargs):
    """Detection RecordIO iterator with the C++ iterator's kwargs surface
    (reference: src/io/iter_image_det_recordio.cc, registered as
    mx.io.ImageDetRecordIter). Maps onto image.ImageDetIter (packed
    detection labels, Det* augmenter chain)."""
    import numpy as onp
    from ..image_detection import ImageDetIter
    mean = (True if (mean_r or mean_g or mean_b) else None)
    if mean is True:
        mean = onp.array([mean_r, mean_g, mean_b], "float32")
    std = (onp.array([std_r or 1.0, std_g or 1.0, std_b or 1.0], "float32")
           if (std_r or std_g or std_b) else None)
    return ImageDetIter(batch_size, data_shape, path_imgrec=path_imgrec,
                        shuffle=shuffle, mean=mean, std=std, **kwargs)


def ImageRecordUInt8Iter(**kwargs):
    """uint8-output variant (reference: iter_image_recordio_2.cc alias);
    pixel values stay 0-255 with no normalization."""
    kwargs.pop("mean_r", None), kwargs.pop("std_r", None)
    return ImageRecordIter(**kwargs)


ImageRecordInt8Iter = ImageRecordUInt8Iter
ImageRecordIter_v1 = ImageRecordIter
ImageRecordUInt8Iter_v1 = ImageRecordUInt8Iter

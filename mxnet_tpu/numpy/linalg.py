"""mx.np.linalg (reference: python/mxnet/numpy/linalg.py over _npi linalg ops).

Lazily wraps jax.numpy.linalg; every function dispatches through _invoke so
autograd recording and async dispatch apply.

General (non-symmetric) eigendecomposition has no TPU lowering in XLA —
the reference kept exactly this family CPU-only too (LAPACK geev via
src/operator/numpy/linalg/np_eig.cc, FComputeEx on cpu). On accelerator
backends `eig`/`eigvals` run on the host: eagerly as a device→CPU→device
round-trip (exactly the reference's CPU-only FCompute cost), and under a
jit trace through `jax.pure_callback` where the PJRT runtime supports
host callbacks (the axon tunnel does not; there a traced call raises).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# names with no accelerator lowering: host round-trip like the reference
_HOST_ONLY = ("eig", "eigvals")


def _host_eig_impl(name, a):
    """Run numpy's geev on host, with stable complex output dtype.

    numpy returns a *real* array when every eigenvalue is real, so the
    result is cast to the promised complex dtype unconditionally.
    """
    import numpy as onp

    cdt = (jnp.complex128 if a.dtype in (jnp.float64, jnp.complex128)
           else jnp.complex64)
    n_batch = a.shape[:-2]
    w_spec = jax.ShapeDtypeStruct(n_batch + a.shape[-1:], cdt)
    v_spec = jax.ShapeDtypeStruct(a.shape, cdt)

    if name == "eig":
        def host(x):
            w, v = onp.linalg.eig(onp.asarray(x))
            return w.astype(cdt), v.astype(cdt)
        specs = (w_spec, v_spec)
    else:
        def host(x):
            return onp.linalg.eigvals(onp.asarray(x)).astype(cdt)
        specs = w_spec

    if isinstance(a, jax.core.Tracer):
        # inside a jit trace the host hop must be a callback op
        return jax.pure_callback(host, specs, a)
    # eager: plain round-trip; results live on the CPU backend, exactly
    # like the reference's CPU-only geev outputs lived on cpu context
    # (accelerator runtimes need not support complex storage at all)
    cpu = jax.devices("cpu")[0]
    out = host(jax.device_get(a))
    if name == "eig":
        return (jax.device_put(out[0], cpu), jax.device_put(out[1], cpu))
    return jax.device_put(out, cpu)


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    target = getattr(jnp.linalg, name, None)
    if target is None:
        raise AttributeError(f"linalg has no attribute {name!r}")
    if callable(target):
        from .multiarray import _invoke

        if name in _HOST_ONLY:
            jnp_target = target

            def target(a, _name=name, _jnp=jnp_target):
                if jax.default_backend() == "cpu":
                    return _jnp(a)  # XLA has a CPU lowering; keep it
                return _host_eig_impl(_name, a)

            def op(*args, _name=name, _target=target, **kwargs):
                if jax.default_backend() != "cpu":
                    from .. import autograd
                    from .multiarray import ndarray, _wrap_out
                    if autograd.is_recording():
                        # geev has no gradient anywhere (reference
                        # np_eig.cc registers no backward; jax defines
                        # no eig JVP/JVP-of-callback) — under record()
                        # compute values OUTSIDE the tape rather than
                        # letting jax.vjp trace into the host hop.
                        # Tracer inputs (hybridized re-trace) route to
                        # pure_callback inside _host_eig_impl.
                        raws = [a._data if isinstance(a, ndarray) else a
                                for a in args]
                        return _wrap_out(_host_eig_impl(_name, *raws))
                return _invoke(_target, args, kwargs,
                               name=f"linalg.{_name}")
            op.__name__ = name
            globals()[name] = op
            return op

        def op(*args, **kwargs):
            return _invoke(target, args, kwargs, name=f"linalg.{name}")
        op.__name__ = name
        globals()[name] = op
        return op
    return target

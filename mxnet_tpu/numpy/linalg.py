"""mx.np.linalg (reference: python/mxnet/numpy/linalg.py over _npi linalg ops).

Lazily wraps jax.numpy.linalg; every function dispatches through _invoke so
autograd recording and async dispatch apply.
"""
from __future__ import annotations

import jax.numpy as jnp


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    target = getattr(jnp.linalg, name, None)
    if target is None:
        raise AttributeError(f"linalg has no attribute {name!r}")
    if callable(target):
        from .multiarray import _invoke

        def op(*args, **kwargs):
            return _invoke(target, args, kwargs, name=f"linalg.{name}")
        op.__name__ = name
        globals()[name] = op
        return op
    return target

"""mx.np ndarray: the framework's tensor.

Reference parity: python/mxnet/numpy/multiarray.py (class ndarray(NDArray) at
:272) over include/mxnet/ndarray.h + src/ndarray/ndarray.cc.

TPU-native design: an ndarray wraps a jax.Array. MXNet's Chunk (Storage handle
+ engine var + delayed alloc) maps onto the PJRT buffer a jax.Array owns;
MXNet's per-array engine variable + version maps onto JAX's async futures —
dispatch returns immediately, ``wait_to_read`` is ``block_until_ready``, and
the ``_version`` counter preserves the reference's versioned-var semantics for
in-place rebinding (``a[:] = ...`` swaps the underlying buffer, same wrapper).

Every op goes through ``_invoke``: unwrap -> jnp/lax primitive -> wrap, and
when ``autograd.record()`` is active and an input carries a tape entry, the
op's VJP closure is captured via ``jax.vjp`` (the analog of
Imperative::RecordOp, src/imperative/imperative.cc:235).
"""
from __future__ import annotations

import contextlib
import weakref

import jax
import jax.numpy as jnp
import numpy as onp

from .. import autograd
from .. import engine
from .. import fault as _fault
from .. import pipeline as _pipeline
from .. import telemetry as _telemetry
from .._jax_compat import enable_x64 as _enable_x64
from ..base import MXNetError, np_dtype
from ..context import Context, current_context

__all__ = ["ndarray", "array", "zeros", "ones", "empty", "full", "arange",
           "linspace", "logspace", "eye", "identity", "zeros_like",
           "ones_like", "full_like", "empty_like", "fromnumpy", "from_dlpack",
           "newaxis", "pi", "e", "inf", "nan", "euler_gamma"]

newaxis = None
pi = onp.pi
e = onp.e
inf = onp.inf
nan = onp.nan
euler_gamma = onp.euler_gamma


_inexact_cache: dict = {}


def _is_inexact(x):
    # dispatch hot path: issubdtype walks the numpy type lattice every
    # call — memoize per dtype (a handful of distinct dtypes per process)
    dt = x.dtype
    r = _inexact_cache.get(dt)
    if r is None:
        r = _inexact_cache[dt] = bool(jnp.issubdtype(dt, jnp.inexact))
    return r


_64BIT = frozenset(("int64", "uint64", "float64", "complex128"))

# ops that jax.vjp cannot linearize fall back to record-without-grad;
# the exception set is version-dependent (jax 0.9 dropped TracerError)
_VJP_FALLBACK_ERRORS = tuple(
    e for e in (TypeError,
                NotImplementedError,
                getattr(jax.errors, "TracerError", None),
                getattr(jax.errors, "TracerArrayConversionError", None),
                getattr(jax.errors, "ConcretizationTypeError", None))
    if e is not None)


def _wants_x64(dt):
    """True when a dtype spec names a 64-bit type that JAX's default
    32-bit canonicalization would truncate (the reference builds with
    MXNET_USE_INT64_TENSOR_SIZE; here 64-bit ops run in a scoped x64
    mode, see util.int64_tensor_size)."""
    if dt is None:
        return False
    try:
        return onp.dtype(dt).name in _64BIT
    except TypeError:
        return False


def _writeback(out, res):
    """Write an op result through an ``out=`` destination array.

    Reference: generated wrappers accept ``out`` and the engine writes the
    result into its buffer (python/mxnet/ndarray/register.py:171). Here the
    destination wrapper is rebound to the new buffer (cast to its dtype) so
    aliases observe the update; the autograd entry moves with it so
    recording through ``out=`` stays correct.
    """
    if out is None:
        return res
    if isinstance(out, (tuple, list)):
        if not isinstance(res, (tuple, list)) or len(res) != len(out):
            raise ValueError("out= arity does not match op outputs")
        return type(out)(_writeback(o, r) for o, r in zip(out, res))
    if not isinstance(out, ndarray):
        raise TypeError(f"out= must be an mxnet ndarray, got {type(out)}")
    if not isinstance(res, ndarray):
        raise TypeError("op returned a non-array; cannot write through out=")
    if tuple(out.shape) != tuple(res.shape):
        raise ValueError(
            f"out= shape mismatch: destination {out.shape} vs result {res.shape}")
    if isinstance(res._data, jax.core.Tracer) and \
            not isinstance(out._data, jax.core.Tracer):
        # a hybridized trace must not leak a tracer into a persistent
        # eager array (it would be corrupted forever)
        raise MXNetError(
            "out= cannot write a traced (hybridized) result into an array "
            "created outside the trace; allocate the destination inside "
            "the hybrid forward or drop out=")
    out._rebind(res._data.astype(out.dtype))
    out._entry = res._entry
    return out


def _wrap(raw, ctx=None):
    """Wrap a raw jax array into an ndarray without copying."""
    out = ndarray.__new__(ndarray)
    out._data = raw
    out._grad = None
    out._grad_req = "null"
    out._entry = None
    out._version = 0
    engine._track(raw)
    return out


def _wrap_out(out):
    """Wrap an op result which may be an array or a pytree of arrays."""
    if isinstance(out, (jnp.ndarray, jax.Array)):
        return _wrap(out)
    if isinstance(out, tuple) and hasattr(out, "_fields"):
        # NamedTuple results (jnp.linalg QRResult/SVDResult/...)
        return type(out)(*[_wrap_out(o) for o in out])
    if isinstance(out, (tuple, list)):
        return type(out)(_wrap_out(o) for o in out)
    return out


_profiler_mod = None
_amp_mod = None


def _invoke(prim, args, kwargs=None, name=None, x64=False):
    """Dispatch one op: the eager hot path.

    Reference analog: FFI glue -> Imperative::Invoke -> Engine::PushAsync
    (src/imperative/imperative.cc:49-140). Here: jnp call (async PJRT
    dispatch); under recording additionally capture the VJP with jax.vjp.
    When the profiler runs, every dispatch is recorded as a host span and
    an Xprof TraceAnnotation — the analog of the engine-integrated
    ProfileOperator (src/engine/threaded_engine.h:356-367).
    """
    global _profiler_mod
    _profiler = _profiler_mod
    if _profiler is None:  # late-bound once (import cycle at module load)
        from .. import profiler as _profiler
        _profiler_mod = _profiler
    if _profiler._state["running"] and _profiler._config["profile_imperative"]:
        with _profiler.span(name or getattr(prim, "__name__", "op"),
                            "operator"):
            out = _invoke_impl(prim, args, kwargs, name, x64)
    else:
        out = _invoke_impl(prim, args, kwargs, name, x64)
    # fault hook (disabled cost: one module-attr read + branch): every
    # dispatch probes invoke.nan_output; a hit turns the op's result into
    # all-NaN, emulating a kernel/overflow fault the trainer guard and
    # AMP scaler must absorb (docs/FAULT_TOLERANCE.md)
    if _fault._active and _fault.fire("invoke.nan_output"):
        _nan_corrupt(out)
    # telemetry hook, same disabled cost contract as the fault hook (the
    # CI telemetry stage bounds it at <2% of a tight eager loop)
    if _telemetry._active:
        _telemetry.inc("invoke.ops_total")
    return out


def _nan_corrupt(out):
    """Rebind the first inexact, concrete (non-tracer) output leaf to
    all-NaN.  Tracer leaves are left alone — corrupting a trace would
    bake the NaN into a compiled executable and replay it forever, which
    is not the transient fault being modeled."""
    leaves = jax.tree_util.tree_leaves(
        out, is_leaf=lambda x: isinstance(x, ndarray))
    for leaf in leaves:
        if isinstance(leaf, ndarray) and _is_inexact(leaf) \
                and not isinstance(leaf._data, jax.core.Tracer):
            leaf._rebind(jnp.full(leaf._data.shape, jnp.nan,
                                  leaf._data.dtype))
            return True
    return False


_64bit_cache: dict = {}


def _leaf_is_64bit(x):
    # dtype.name builds a python string per call — memoize per dtype
    dt = getattr(x, "dtype", None)
    if dt is None:
        return False
    r = _64bit_cache.get(dt)
    if r is None:
        r = _64bit_cache[dt] = getattr(dt, "name", "") in _64BIT
    return r


def _invoke_impl(prim, args, kwargs=None, name=None, x64=False):
    kwargs = kwargs or {}
    global _amp_mod
    _amp = _amp_mod
    if _amp is None:
        from .. import amp as _amp
        _amp_mod = _amp
    amp_dt = (_amp._op_cast_dtype(name or getattr(prim, "__name__", ""))
              if _amp.is_active() else None)
    # flat fast path (the eager hot loop, SURVEY §7 hard part #1): no
    # kwargs and no nested containers means tree_flatten/unflatten and
    # the container-aware closure are pure overhead
    if not kwargs and not any(isinstance(a, (tuple, list, dict))
                              for a in args):
        return _invoke_flat(prim, args, name, x64, amp_dt)
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, ndarray))
    # differentiable inputs: inexact-dtype ndarrays; others are unwrapped
    # in place (bool masks / int indices stay concrete for eager indexing).
    # 64-bit dtype on an mx array input or an explicit dtype request ->
    # scoped x64 so JAX does not truncate (raw host-numpy operands do NOT
    # trigger it: numpy's default float64/int64 would otherwise drag every
    # mixed op into x64; they keep the 32-bit canonicalization).
    use_x64 = x64 or _wants_x64(kwargs.get("dtype"))
    arr_pos, diff_arrays = [], []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, ndarray):
            use_x64 = use_x64 or _leaf_is_64bit(leaf)
            if _is_inexact(leaf):
                arr_pos.append(i)
                diff_arrays.append(leaf)
            else:
                leaves[i] = leaf._data

    def fn(*xs):
        if amp_dt is not None:
            # cast inside the traced fn: the cast's VJP upcasts cotangents
            # back to the caller's dtype, and _CachedGraph tracing re-enters
            # here so hybrid forward gets the same policy (amp.init()).
            xs = [x.astype(amp_dt)
                  if jnp.issubdtype(x.dtype, jnp.floating)
                  and x.dtype != amp_dt else x for x in xs]
        ls = list(leaves)
        for p, x in zip(arr_pos, xs):
            ls[p] = x
        a, kw = jax.tree_util.tree_unflatten(treedef, ls)
        return prim(*a, **kw)

    raws = [a._data for a in diff_arrays]
    recording = (autograd.is_recording()
                 and any(a._entry is not None for a in diff_arrays))
    x64_scope = _enable_x64(True) if use_x64 else contextlib.nullcontext()
    with x64_scope:
        if recording:
            try:
                out, vjp_fn = jax.vjp(fn, *raws)
            except _VJP_FALLBACK_ERRORS:
                recording = False
                out = fn(*raws)
        else:
            out = fn(*raws)
    if recording and use_x64:
        _inner_vjp = vjp_fn

        def vjp_fn(ct, _inner=_inner_vjp):
            with _enable_x64(True):
                return _inner(ct)

    wrapped = _wrap_out(out)
    if recording:
        out_leaves = [w for w in jax.tree_util.tree_leaves(
            wrapped, is_leaf=lambda x: isinstance(x, ndarray))
            if isinstance(w, ndarray)]
        # NOTE: must not rebind `treedef` — fn closes over the input treedef
        out_td = jax.tree_util.tree_structure(out)
        autograd._record_op(
            vjp_fn, diff_arrays, out_leaves,
            name or getattr(prim, "__name__", "op"),
            # only trustworthy when every pytree leaf is a wrapped array
            out_treedef=out_td if out_td.num_leaves == len(out_leaves)
            else None,
            # pure fn + primals: create_graph re-linearizes through these
            fun=fn, raw_args=tuple(raws), x64=use_x64)
    return wrapped


def _invoke_flat(prim, args, name, x64, amp_dt):
    """Dispatch with flat positional args only — semantics identical to
    the generic path (amp cast, scoped x64, vjp recording), minus the
    pytree walk and container-aware closure."""
    use_x64 = x64
    arr_pos = []
    diff_arrays = []
    leaves = list(args)
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, ndarray):
            if not use_x64 and _leaf_is_64bit(leaf._data):
                use_x64 = True
            if _is_inexact(leaf):
                arr_pos.append(i)
                diff_arrays.append(leaf)
            else:
                leaves[i] = leaf._data

    def fn(*xs):
        if amp_dt is not None:
            xs = [x.astype(amp_dt)
                  if jnp.issubdtype(x.dtype, jnp.floating)
                  and x.dtype != amp_dt else x for x in xs]
        ls = list(leaves)
        for p, x in zip(arr_pos, xs):
            ls[p] = x
        return prim(*ls)

    raws = [a._data for a in diff_arrays]
    recording = (autograd.is_recording()
                 and any(a._entry is not None for a in diff_arrays))
    x64_scope = _enable_x64(True) if use_x64 else contextlib.nullcontext()
    with x64_scope:
        if recording:
            try:
                out, vjp_fn = jax.vjp(fn, *raws)
            except _VJP_FALLBACK_ERRORS:
                recording = False
                out = fn(*raws)
        elif amp_dt is None and not use_x64:
            # no cast, no scope, nothing recorded: call through directly
            ls = leaves
            if arr_pos:
                ls = list(leaves)
                for p, a in zip(arr_pos, diff_arrays):
                    ls[p] = a._data
            out = prim(*ls)
        else:
            out = fn(*raws)
    if recording and use_x64:
        _inner_vjp = vjp_fn

        def vjp_fn(ct, _inner=_inner_vjp):
            with _enable_x64(True):
                return _inner(ct)

    wrapped = _wrap_out(out)
    if recording:
        out_leaves = [w for w in jax.tree_util.tree_leaves(
            wrapped, is_leaf=lambda x: isinstance(x, ndarray))
            if isinstance(w, ndarray)]
        out_td = jax.tree_util.tree_structure(out)
        autograd._record_op(
            vjp_fn, diff_arrays, out_leaves,
            name or getattr(prim, "__name__", "op"),
            out_treedef=out_td if out_td.num_leaves == len(out_leaves)
            else None,
            fun=fn, raw_args=tuple(raws), x64=use_x64)
    return wrapped


# the reference's generated fluent-method list for NDArray (the same op
# tail Symbol carries), minus names implemented as real methods below
_NDARRAY_FLUENT = frozenset("""
arccos arccosh arcsin arcsinh arctan arctanh argmax_channel
broadcast_axes broadcast_like cbrt ceil cos cosh degrees depth_to_space
diag expm1 fix flip floor log10 log1p log2 log_sigmoid log_softmax mish
nanprod nansum norm one_hot pad pick radians rcbrt reciprocal relu rint
rsqrt shape_array sigmoid sign sin sinh size_array slice_axis slice_like
softmax softmin space_to_depth split_v2 tan tanh tile topk trunc
""".split())
_FLUENT_CACHE: dict = {}  # name -> resolved op fn (name-only resolution)


class ndarray:
    """N-dimensional array on a device (reference: numpy/multiarray.py:272)."""

    __slots__ = ("_data", "_grad", "_grad_req", "_entry", "_version",
                 "__weakref__")

    def __init__(self, data, ctx=None, dtype=None):
        if isinstance(data, ndarray):
            raw = data._data
        else:
            raw = jnp.asarray(data, dtype=np_dtype(dtype))
        if dtype is not None and raw.dtype != np_dtype(dtype):
            raw = raw.astype(np_dtype(dtype))
        if ctx is not None:
            raw = jax.device_put(raw, Context(ctx).jax_device)
        self._data = raw
        self._grad = None
        self._grad_req = "null"
        self._entry = None
        self._version = 0
        engine._track(raw)

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def itemsize(self):
        return self._data.dtype.itemsize

    @property
    def T(self):
        return _invoke(jnp.transpose, (self,))

    @property
    def ctx(self):
        """Context of this array (reference: NDArray.ctx)."""
        dev = None
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            pass
        if dev is None or dev.platform == "cpu":
            return Context("cpu", getattr(dev, "id", 0) or 0)
        return Context("tpu", dev.id)

    context = ctx
    device = ctx

    @property
    def sharding(self):
        return self._data.sharding

    # -- engine / version semantics ---------------------------------------
    @property
    def version(self):
        """Write-version counter (reference: NDArray::version, ndarray.h:413)."""
        return self._version

    def wait_to_read(self):
        """Block until the value is computed (Engine::WaitForVar analog)."""
        if _pipeline._guard_depth:
            _pipeline.note_host_sync("ndarray.wait_to_read")
        self._data.block_until_ready()
        return self

    def _rebind(self, raw):
        """In-place value replacement: same wrapper, new buffer, version+1."""
        self._data = raw
        self._version += 1
        engine._track(raw)

    # -- conversion --------------------------------------------------------
    def asnumpy(self):
        """Host copy with MXNet's contract: C-contiguous and writable.

        device_get is allowed to hand back a strided / read-only view
        (the axon TPU runtime returns non-C-contiguous buffers — a
        `.astype(...).reshape(-1)` then silently copies and in-place
        writes vanish, observed as all-zero finite differences on
        hardware); the reference's asnumpy always yields an owned dense
        buffer (ndarray.cc SyncCopyToCPU), so normalize here.
        """
        if _pipeline._guard_depth:
            _pipeline.note_host_sync("ndarray.asnumpy")
        host = onp.asarray(jax.device_get(self._data))
        if not (host.flags["C_CONTIGUOUS"] and host.flags["WRITEABLE"]):
            host = host.copy(order="C")  # owned, dense, writable
        return host

    def asscalar(self):
        return self.asnumpy().item()

    def item(self, *args):
        if _pipeline._guard_depth:
            _pipeline.note_host_sync("ndarray.item")
        return self._data.item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        dt = np_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return _invoke(lambda x: x.astype(dt), (self,), name="astype",
                       x64=_wants_x64(dt))

    def copy(self):
        return _invoke(jnp.copy, (self,))

    def copyto(self, other):
        """Copy value into another array or context (reference:
        NDArray.copyto / CopyFromTo src/ndarray/ndarray.cc)."""
        if isinstance(other, ndarray):
            if other.shape != self.shape:
                raise MXNetError(f"copyto shape mismatch {self.shape} vs {other.shape}")
            other._rebind(self._data.astype(other.dtype))
            return other
        if isinstance(other, Context):
            return _wrap(jax.device_put(self._data, other.jax_device))
        raise TypeError(type(other))

    def as_in_ctx(self, ctx):
        ctx = Context(ctx)
        return _wrap(jax.device_put(self._data, ctx.jax_device))

    as_in_context = as_in_ctx
    to_device = as_in_ctx

    def as_np_ndarray(self):
        return self

    def as_nd_ndarray(self):
        return self

    # -- NumPy interoperability protocols ---------------------------------
    # Reference: numpy_dispatch_protocol.py + multiarray.py:318-413 —
    # official numpy functions/ufuncs called ON mx arrays dispatch to the
    # mx implementation and return mx arrays (casting table: any mx
    # operand makes the result mx). Fallback to host numpy is allowed
    # only outside autograd recording (grads cannot flow through it).

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__":
            return NotImplemented
        from .. import numpy as _mx_np
        name = ufunc.__name__
        fn = getattr(_mx_np, name, None)
        out = kwargs.pop("out", None)
        if out is not None:
            if isinstance(out, tuple):
                if len(out) != 1:
                    return NotImplemented
                out = out[0]
            kwargs["out"] = out
        ins = tuple(_wrap(jnp.asarray(a)) if isinstance(a, onp.ndarray)
                    else a for a in inputs)
        if fn is None or not callable(fn):
            return self._np_fallback(ufunc, ins, kwargs)
        return fn(*ins, **kwargs)

    def __array_function__(self, func, types, args, kwargs):
        from .. import numpy as _mx_np
        try:
            fn = getattr(_mx_np, func.__name__)
        except AttributeError:
            fn = None
        if fn is None or not callable(fn):
            return self._np_fallback(func, args, kwargs)
        return fn(*args, **kwargs)

    @staticmethod
    def _np_fallback(func, args, kwargs):
        from .. import autograd as _ag
        if _ag.is_recording():
            raise MXNetError(
                f"falling back to official NumPy operator "
                f"{getattr(func, '__name__', func)} under autograd.record() "
                "is not supported (gradients cannot flow through host "
                "numpy); move the call outside the recording scope")

        def to_onp(x):
            return x.asnumpy() if isinstance(x, ndarray) else x
        out = func(*jax.tree_util.tree_map(
            to_onp, args, is_leaf=lambda x: isinstance(x, ndarray)),
            **{k: to_onp(v) for k, v in kwargs.items()})
        return (_wrap(jnp.asarray(out))
                if isinstance(out, onp.ndarray) else out)

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__(stream=stream)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write"):
        """Allocate a gradient buffer and mark as a tape leaf
        (reference: NDArray.attach_grad / mark_variables)."""
        grad = _wrap(jnp.zeros(self.shape, self.dtype))
        self._mark_variable(grad, grad_req)

    def _mark_variable(self, grad, grad_req):
        self._grad = grad
        self._grad_req = grad_req
        self._entry = autograd._Entry(None, 0, weakref.ref(self))

    def _write_grad(self, raw_grad):
        if self._grad_req == "null" or self._grad is None:
            return
        from ..ndarray import sparse as _sp
        if isinstance(raw_grad, _sp.BaseSparseNDArray):
            # row-sparse gradient (embedding sparse_grad): .grad becomes
            # the sparse object, the reference's grad-stype row_sparse
            if self._grad_req == "add":
                if isinstance(self._grad, _sp.BaseSparseNDArray):
                    self._grad = _sp.add(self._grad, raw_grad)
                elif bool(jnp.any(self._grad._data != 0)):
                    # accumulated dense grad present: densify-and-add
                    dense = self._grad._data + \
                        raw_grad.tostype("default")._data
                    self._grad = _wrap(dense.astype(self.dtype))
                else:
                    self._grad = raw_grad.astype(self.dtype)
            else:
                self._grad = raw_grad.astype(self.dtype)
            return
        if isinstance(self._grad, _sp.BaseSparseNDArray):
            # dense grad arriving over a sparse one: densify
            dense = self._grad.tostype("default")._data + raw_grad
            self._grad = _wrap(dense.astype(self.dtype))
            return
        g = raw_grad.astype(self._grad.dtype)
        if self._grad_req == "add":
            self._grad._rebind(self._grad._data + g)
        else:
            self._grad._rebind(g)

    def zero_grad(self):
        if self._grad is None:
            return
        from ..ndarray import sparse as _sp
        if isinstance(self._grad, _sp.BaseSparseNDArray):
            # back to a dense zero buffer; the next sparse backward
            # replaces it wholesale
            self._grad = _wrap(jnp.zeros(self.shape, self.dtype))
            return
        self._grad._rebind(jnp.zeros_like(self._grad._data))

    @property
    def grad(self):
        return self._grad

    def detach(self):
        out = _wrap(self._data)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad], retain_graph, train_mode)

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, key):
        key = _unwrap_key(key)
        return _invoke(lambda x: x[key], (self,), name="getitem",
                       x64=_key_is_64bit(key))

    def __setitem__(self, key, value):
        if isinstance(value, ndarray):
            value = value._data
        key = _unwrap_key(key)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(value, self.dtype), self.shape)
        else:
            new = self._data.at[key].set(jnp.asarray(value).astype(self.dtype))
        if autograd.is_recording() and self._entry is not None:
            # functional set: records like any op, entry moves to new version
            old = self
            res = _invoke(lambda x, v: jnp.broadcast_to(v, x.shape) if key is Ellipsis
                          else x.at[key].set(v.astype(x.dtype)),
                          (self, _wrap(jnp.asarray(value))), name="setitem")
            self._data = res._data
            self._entry = res._entry
            self._version += 1
            return
        self._rebind(new)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of 0-d array")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __contains__(self, x):
        return bool((self._data == (x._data if isinstance(x, ndarray) else x)).any())

    # -- python scalar protocol -------------------------------------------
    def _scalar(self):
        if self.size != 1:
            raise TypeError(
                f"only size-1 arrays convert to python scalars, got {self.shape}")
        return jax.device_get(self._data).reshape(())

    def __bool__(self):
        if self.size == 1:
            return bool(self._scalar())
        return bool(self._data)  # raises the standard ambiguity error

    def __float__(self):
        return float(self._scalar())

    def __int__(self):
        return int(self._scalar())

    def __index__(self):
        return int(self._scalar())

    def __hash__(self):
        return id(self)

    def __reduce__(self):
        # pickle as host numpy (DataLoader workers, Trainer state dumps);
        # the reference pickles NDArrays via shared memory (dataloader.py:28)
        # — device buffers always round-trip through host here
        return (_from_numpy_reduce, (self.asnumpy(),))

    def __repr__(self):
        try:
            return f"array({onp.array2string(self.asnumpy(), separator=', ')}, dtype={self.dtype})"
        except Exception:
            return f"ndarray(shape={self.shape}, dtype={self.dtype})"

    # -- arithmetic --------------------------------------------------------
    def _binop(self, other, fn, reflexive=False):
        if isinstance(other, (list, tuple, onp.ndarray)):
            other = _wrap(jnp.asarray(other))
        if reflexive:
            return _invoke(fn, (other, self))
        return _invoke(fn, (self, other))

    def __add__(self, o): return self._binop(o, jnp.add)
    def __radd__(self, o): return self._binop(o, jnp.add, True)
    def __sub__(self, o): return self._binop(o, jnp.subtract)
    def __rsub__(self, o): return self._binop(o, jnp.subtract, True)
    def __mul__(self, o): return self._binop(o, jnp.multiply)
    def __rmul__(self, o): return self._binop(o, jnp.multiply, True)
    def __truediv__(self, o): return self._binop(o, jnp.true_divide)
    def __rtruediv__(self, o): return self._binop(o, jnp.true_divide, True)
    def __floordiv__(self, o): return self._binop(o, jnp.floor_divide)
    def __rfloordiv__(self, o): return self._binop(o, jnp.floor_divide, True)
    def __mod__(self, o): return self._binop(o, jnp.mod)
    def __rmod__(self, o): return self._binop(o, jnp.mod, True)
    def __pow__(self, o): return self._binop(o, jnp.power)
    def __rpow__(self, o): return self._binop(o, jnp.power, True)
    def __matmul__(self, o): return self._binop(o, jnp.matmul)
    def __rmatmul__(self, o): return self._binop(o, jnp.matmul, True)
    def __neg__(self): return _invoke(jnp.negative, (self,))
    def __pos__(self): return self
    def __abs__(self): return _invoke(jnp.abs, (self,))
    def __invert__(self): return _invoke(jnp.invert, (self,))
    def __and__(self, o): return self._binop(o, jnp.bitwise_and)
    def __or__(self, o): return self._binop(o, jnp.bitwise_or)
    def __xor__(self, o): return self._binop(o, jnp.bitwise_xor)
    def __lshift__(self, o): return self._binop(o, jnp.left_shift)
    def __rshift__(self, o): return self._binop(o, jnp.right_shift)
    def __eq__(self, o): return self._binop(o, jnp.equal)
    def __ne__(self, o): return self._binop(o, jnp.not_equal)
    def __lt__(self, o): return self._binop(o, jnp.less)
    def __le__(self, o): return self._binop(o, jnp.less_equal)
    def __gt__(self, o): return self._binop(o, jnp.greater)
    def __ge__(self, o): return self._binop(o, jnp.greater_equal)

    # in-place: rebind the same wrapper (MXNet mutation semantics)
    def _iop(self, other, fn):
        res = self._binop(other, fn)
        self._data = res._data.astype(self.dtype)
        self._entry = res._entry
        self._version += 1
        return self

    def __iadd__(self, o): return self._iop(o, jnp.add)
    def __isub__(self, o): return self._iop(o, jnp.subtract)
    def __imul__(self, o): return self._iop(o, jnp.multiply)
    def __itruediv__(self, o): return self._iop(o, jnp.true_divide)
    def __ifloordiv__(self, o): return self._iop(o, jnp.floor_divide)
    def __imod__(self, o): return self._iop(o, jnp.mod)
    def __ipow__(self, o): return self._iop(o, jnp.power)

    # -- method forms of ops ----------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = tuple(-1 if s in (-1,) else int(s) for s in shape)
        return _invoke(lambda x: jnp.reshape(x, shape), (self,), name="reshape")

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        axes = axes if axes else None
        return _invoke(lambda x: jnp.transpose(x, axes), (self,), name="transpose")

    def swapaxes(self, a1, a2):
        return _invoke(lambda x: jnp.swapaxes(x, a1, a2), (self,))

    def flatten(self):
        return self.reshape(-1)

    def squeeze(self, axis=None):
        return _invoke(lambda x: jnp.squeeze(x, axis), (self,))

    def expand_dims(self, axis):
        return _invoke(lambda x: jnp.expand_dims(x, axis), (self,))

    def repeat(self, repeats, axis=None):
        return _invoke(lambda x: jnp.repeat(x, repeats, axis), (self,))

    def tile(self, reps):
        return _invoke(lambda x: jnp.tile(x, reps), (self,))

    def broadcast_to(self, shape):
        return _invoke(lambda x: jnp.broadcast_to(x, shape), (self,))

    def split(self, indices_or_sections, axis=0):
        return _invoke(lambda x: jnp.split(x, indices_or_sections, axis), (self,))

    def take(self, indices, axis=None, mode="clip"):
        idx = indices._data if isinstance(indices, ndarray) else indices
        return _invoke(lambda x: jnp.take(x, idx, axis, mode=mode), (self,))

    def clip(self, a_min=None, a_max=None):
        return _invoke(lambda x: jnp.clip(x, a_min, a_max), (self,))

    def round(self, decimals=0):
        return _invoke(lambda x: jnp.round(x, decimals), (self,))

    def _reduce(self, fn, axis=None, keepdims=False, **kw):
        return _invoke(lambda x: fn(x, axis=axis, keepdims=keepdims, **kw), (self,),
                       name=fn.__name__, x64=_wants_x64(kw.get("dtype")))

    def sum(self, axis=None, dtype=None, keepdims=False):
        return self._reduce(jnp.sum, axis, keepdims, dtype=np_dtype(dtype))

    def mean(self, axis=None, dtype=None, keepdims=False):
        return self._reduce(jnp.mean, axis, keepdims, dtype=np_dtype(dtype))

    def prod(self, axis=None, keepdims=False):
        return self._reduce(jnp.prod, axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce(jnp.max, axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce(jnp.min, axis, keepdims)

    def std(self, axis=None, keepdims=False, ddof=0):
        return self._reduce(jnp.std, axis, keepdims, ddof=ddof)

    def var(self, axis=None, keepdims=False, ddof=0):
        return self._reduce(jnp.var, axis, keepdims, ddof=ddof)

    def all(self, axis=None, keepdims=False):
        return self._reduce(jnp.all, axis, keepdims)

    def any(self, axis=None, keepdims=False):
        return self._reduce(jnp.any, axis, keepdims)

    def __getattr__(self, name):
        """Legacy fluent op methods (the reference generates ~80 per-op
        NDArray methods: a.relu(), a.log_softmax(), a.slice_axis(...)).
        Resolution is restricted to the fixed reference list so
        duck-typing probes keep their AttributeError contract; the
        methods call the same np/npx/legacy functions as module
        spellings. __slots__ means every other miss is a genuine
        AttributeError, so hot-path attribute access never lands here."""
        if name in _NDARRAY_FLUENT:
            fn = _FLUENT_CACHE.get(name)
            if fn is None:
                from .. import numpy as _np_mod
                from .. import numpy_extension as _npx_mod
                from ..ndarray import register as _legacy
                # npx/legacy FIRST: mx.np's module __getattr__ falls back
                # to jnp/jax.nn for unknown names, which would shadow the
                # reference-signature npx ops (softmax temperature=,
                # one_hot on_value=, ...)
                fn = _legacy.get(name) or getattr(_npx_mod, name, None) \
                    or getattr(_np_mod, name, None)
                if callable(fn):
                    _FLUENT_CACHE[name] = fn  # name-only resolution
            if callable(fn):
                def method(*args, _fn=fn, **kwargs):
                    return _fn(self, *args, **kwargs)
                method.__name__ = name
                return method
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    def argmax(self, axis=None):
        return _invoke(lambda x: jnp.argmax(x, axis), (self,))

    def argmin(self, axis=None):
        return _invoke(lambda x: jnp.argmin(x, axis), (self,))

    def argsort(self, axis=-1):
        return _invoke(lambda x: jnp.argsort(x, axis), (self,))

    def sort(self, axis=-1):
        return _invoke(lambda x: jnp.sort(x, axis), (self,))

    def cumsum(self, axis=None, dtype=None):
        return _invoke(lambda x: jnp.cumsum(x, axis, dtype=np_dtype(dtype)),
                       (self,), x64=_wants_x64(dtype))

    def dot(self, other):
        return self._binop(other, jnp.dot)

    def abs(self): return _invoke(jnp.abs, (self,))
    def exp(self): return _invoke(jnp.exp, (self,))
    def log(self): return _invoke(jnp.log, (self,))
    def sqrt(self): return _invoke(jnp.sqrt, (self,))
    def square(self): return _invoke(jnp.square, (self,))
    def sigmoid(self): return _invoke(jax.nn.sigmoid, (self,))
    def tanh(self): return _invoke(jnp.tanh, (self,))
    def relu(self): return _invoke(jax.nn.relu, (self,))

    def tostype(self, stype):
        if stype == "default":
            return self
        from ..ndarray import sparse as _sparse
        if stype == "row_sparse":
            return _sparse.row_sparse_array(self)
        if stype == "csr":
            return _sparse.csr_matrix(self)
        raise MXNetError(f"unknown storage type {stype!r}")

    @property
    def stype(self):
        return "default"


def _from_numpy_reduce(arr):
    return _wrap(jnp.asarray(arr))


def _unwrap_key(key):
    if isinstance(key, ndarray):
        return key._data
    if isinstance(key, tuple):
        return tuple(_unwrap_key(k) for k in key)
    if isinstance(key, list):
        return onp.asarray(key)
    return key


def _key_is_64bit(key):
    if isinstance(key, tuple):
        return any(_key_is_64bit(k) for k in key)
    return _leaf_is_64bit(key)


# ---------------------------------------------------------------------------
# creation functions (reference: numpy/multiarray.py zeros/ones/... wrappers)
# ---------------------------------------------------------------------------

def _place(raw, ctx, device):
    ctx = device if device is not None else ctx
    if ctx is not None:
        raw = jax.device_put(raw, Context(ctx).jax_device)
    return _wrap(raw)


def _x64_scope(dt):
    """Scoped x64 mode when a 64-bit dtype is explicitly requested."""
    return _enable_x64(True) if _wants_x64(dt) else contextlib.nullcontext()


def array(obj, dtype=None, ctx=None, device=None):
    if isinstance(obj, ndarray):
        obj = obj._data
    if dtype is None and isinstance(obj, onp.ndarray) and \
            onp.dtype(obj.dtype).name in ("int64", "uint64"):
        # preserve host-numpy 64-bit integer dtypes (index arrays); floats
        # keep the 32-bit TPU-native default unless explicitly requested
        dtype = obj.dtype
    with _x64_scope(dtype):
        raw = jnp.asarray(obj, dtype=np_dtype(dtype))
    return _place(raw, ctx, device)


def fromnumpy(a):
    return array(a)


def from_dlpack(x):
    return _wrap(jnp.from_dlpack(x))


def empty(shape, dtype=None, ctx=None, device=None, order="C"):
    return zeros(shape, dtype, ctx, device)


def zeros(shape, dtype=None, ctx=None, device=None, order="C"):
    with _x64_scope(dtype):
        raw = jnp.zeros(shape, np_dtype(dtype) or jnp.float32)
    return _place(raw, ctx, device)


def ones(shape, dtype=None, ctx=None, device=None, order="C"):
    with _x64_scope(dtype):
        raw = jnp.ones(shape, np_dtype(dtype) or jnp.float32)
    return _place(raw, ctx, device)


def full(shape, fill_value, dtype=None, ctx=None, device=None, order="C"):
    if isinstance(fill_value, ndarray):
        fill_value = fill_value._data
    with _x64_scope(dtype):
        raw = jnp.full(shape, fill_value, np_dtype(dtype))
    return _place(raw, ctx, device)


def zeros_like(a, dtype=None, ctx=None, device=None):
    return _invoke(lambda x: jnp.zeros_like(x, np_dtype(dtype)), (a,),
                   x64=_wants_x64(dtype))


def ones_like(a, dtype=None, ctx=None, device=None):
    return _invoke(lambda x: jnp.ones_like(x, np_dtype(dtype)), (a,),
                   x64=_wants_x64(dtype))


def full_like(a, fill_value, dtype=None, ctx=None, device=None):
    return _invoke(lambda x: jnp.full_like(x, fill_value, np_dtype(dtype)),
                   (a,), x64=_wants_x64(dtype))


def empty_like(a, dtype=None, ctx=None, device=None):
    return zeros_like(a, dtype, ctx, device)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    with _x64_scope(dtype):
        raw = jnp.arange(start, stop, step, np_dtype(dtype))
    return _place(raw, ctx, device)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    with _x64_scope(dtype):
        out = jnp.linspace(start, stop, num, endpoint, retstep,
                           np_dtype(dtype), axis)
    if retstep:
        return _place(out[0], ctx, device), out[1]
    return _place(out, ctx, device)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None, device=None):
    with _x64_scope(dtype):
        raw = jnp.logspace(start, stop, num, endpoint, base,
                           np_dtype(dtype), axis)
    return _place(raw, ctx, device)


def eye(N, M=None, k=0, dtype=None, ctx=None, device=None):
    with _x64_scope(dtype):
        raw = jnp.eye(N, M, k, np_dtype(dtype) or jnp.float32)
    return _place(raw, ctx, device)


def identity(n, dtype=None, ctx=None, device=None):
    return eye(n, dtype=dtype, ctx=ctx, device=device)

"""mx.np — NumPy-compatible array namespace.

Reference parity: python/mxnet/numpy/ (multiarray.py + generated op wrappers;
the reference code-gens a python function per registered op at import via
ndarray/register.py:115-277, and falls back to real NumPy for missing ops via
numpy/fallback.py).

TPU-native design: ops lower straight to jax.numpy. Named functions below are
the explicitly-typed surface; any other NumPy function resolves lazily through
module ``__getattr__`` to a wrapped ``jnp`` equivalent — the analog of both
the generated wrappers and the fallback mechanism, with autograd recording and
async dispatch handled by ``_invoke``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import np_dtype
from .multiarray import (  # noqa: F401
    ndarray, array, zeros, ones, empty, full, arange, linspace, logspace, eye,
    identity, zeros_like, ones_like, full_like, empty_like, fromnumpy,
    from_dlpack, newaxis, pi, e, inf, nan, euler_gamma, _invoke, _wrap,
    _wrap_out, _writeback, _wants_x64,
)
from . import random  # noqa: F401
from . import linalg  # noqa: F401

# dtype objects for parity with `np.float32` style usage
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
bfloat16 = jnp.bfloat16
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
dtype = jnp.dtype

_generated_cache = {}


def _make_op(fn, name):
    @functools.wraps(fn)
    def op(*args, **kwargs):
        kwargs.pop("ctx", None)
        kwargs.pop("device", None)
        out = kwargs.pop("out", None)
        x64 = False
        if "dtype" in kwargs:
            x64 = _wants_x64(kwargs["dtype"])
            kwargs["dtype"] = np_dtype(kwargs["dtype"])
        res = _invoke(fn, args, kwargs, name=name, x64=x64)
        return _writeback(out, res)
    op.__name__ = name
    return op


# jnp.fix is deprecated (slated for removal in jax 0.10); np.fix is
# round-toward-zero == trunc, so bind it explicitly
fix = _make_op(jnp.trunc, "fix")


def histogram(a, bins=10, range=None, weights=None, density=None):
    """jnp.histogram returns float counts; NumPy (and the reference's
    _npi.histogram) return integer counts when unweighted — found by the
    per-op sweep, cast to match."""
    hist, edges = _invoke(
        lambda x: jnp.histogram(x, bins=bins, range=range, weights=weights,
                                density=density), (a,), name="histogram")
    if weights is None and not density:
        hist = hist.astype("int64")
    return hist, edges


def __getattr__(name):
    """Lazy op generation (analog of ndarray/register.py _init_op_module +
    numpy/fallback.py)."""
    if name.startswith("__"):
        raise AttributeError(name)
    if name in _generated_cache:
        return _generated_cache[name]
    target = getattr(jnp, name, None)
    if target is None:
        target = getattr(jax.nn, name, None)
    if target is None:
        raise AttributeError(f"module 'mxnet_tpu.numpy' has no attribute {name!r}")
    if callable(target) and not isinstance(target, type):
        op = _make_op(target, name)
        _generated_cache[name] = op
        globals()[name] = op
        return op
    _generated_cache[name] = target
    return target


# -- a few ops whose reference signature differs from jnp -------------------

def concatenate(seq, axis=0, out=None):
    return _writeback(out, _invoke(lambda *xs: jnp.concatenate(xs, axis=axis),
                                   tuple(seq), name="concatenate"))


concat = concatenate


def stack(arrays, axis=0, out=None):
    return _writeback(out, _invoke(lambda *xs: jnp.stack(xs, axis=axis),
                                   tuple(arrays), name="stack"))


def vstack(arrays):
    return _invoke(lambda *xs: jnp.vstack(xs), tuple(arrays), name="vstack")


def hstack(arrays):
    return _invoke(lambda *xs: jnp.hstack(xs), tuple(arrays), name="hstack")


def dstack(arrays):
    return _invoke(lambda *xs: jnp.dstack(xs), tuple(arrays), name="dstack")


def column_stack(arrays):
    return _invoke(lambda *xs: jnp.column_stack(xs), tuple(arrays),
                   name="column_stack")


def split(ary, indices_or_sections, axis=0):
    return _invoke(lambda x: jnp.split(x, indices_or_sections, axis), (ary,),
                   name="split")


def array_split(ary, indices_or_sections, axis=0):
    return _invoke(lambda x: jnp.array_split(x, indices_or_sections, axis),
                   (ary,), name="array_split")


def meshgrid(*xi, **kwargs):
    return _invoke(lambda *xs: jnp.meshgrid(*xs, **kwargs), xi, name="meshgrid")


def einsum(subscripts, *operands, **kwargs):
    return _invoke(lambda *xs: jnp.einsum(subscripts, *xs, **kwargs), operands,
                   name="einsum")


def may_share_memory(a, b):
    return a is b


def shares_memory(a, b):
    return a is b


def asarray(obj, dtype=None):
    return array(obj, dtype=dtype)


def asnumpy(a):
    return a.asnumpy() if isinstance(a, ndarray) else a


def fill_diagonal(a, val, wrap=False):
    """Functional fill_diagonal (JAX arrays are immutable; returns a copy,
    unlike numpy's in-place reference semantics)."""
    return _invoke(lambda x: jnp.fill_diagonal(x, val, wrap=wrap,
                                               inplace=False),
                   (a,), name="fill_diagonal")


row_stack = vstack

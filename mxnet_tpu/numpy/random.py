"""mx.np.random — global-seed RNG facade over JAX splittable keys.

Reference parity: python/mxnet/numpy/random.py backed by per-device parallel
RNG resources (src/common/random_generator.h, resource kRandom/kParallelRandom).

TPU-native design: a process-global threefry key (mxnet_tpu.random holds it);
every sampler splits off a fresh subkey — the analog of the reference's
resource-managed generator streams, but functional and reproducible under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import np_dtype
from .multiarray import _wrap, ndarray


def _key():
    from .. import random as _r
    return _r._next_key()


def _shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def _fdt(dtype):
    return np_dtype(dtype) or jnp.float32


def seed(s):
    from .. import random as _r
    _r.seed(s)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    low = low._data if isinstance(low, ndarray) else low
    high = high._data if isinstance(high, ndarray) else high
    return _wrap(jax.random.uniform(_key(), _shape(size), _fdt(dtype), low, high))


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    loc = loc._data if isinstance(loc, ndarray) else loc
    scale = scale._data if isinstance(scale, ndarray) else scale
    return _wrap(jax.random.normal(_key(), _shape(size), _fdt(dtype)) * scale + loc)


randn_shape = None


def randn(*size, dtype=None):
    return normal(size=size, dtype=dtype)


def rand(*size, dtype=None):
    return uniform(size=size, dtype=dtype)


def randint(low, high=None, size=None, dtype=None, ctx=None, device=None, out=None):
    if high is None:
        low, high = 0, low
    return _wrap(jax.random.randint(_key(), _shape(size), low, high,
                                    np_dtype(dtype) or jnp.int32))


def choice(a, size=None, replace=True, p=None, ctx=None, device=None, out=None):
    if isinstance(a, ndarray):
        a = a._data
    elif isinstance(a, int):
        a = jnp.arange(a)
    if p is not None and isinstance(p, ndarray):
        p = p._data
    return _wrap(jax.random.choice(_key(), a, _shape(size), replace, p))


def shuffle(x):
    """In-place shuffle along axis 0 (reference: np.random.shuffle)."""
    perm = jax.random.permutation(_key(), x.shape[0])
    x._rebind(x._data[perm])


def permutation(x):
    if isinstance(x, int):
        return _wrap(jax.random.permutation(_key(), x))
    return _wrap(jax.random.permutation(_key(), x._data))


def multinomial(n, pvals, size=None):
    if isinstance(pvals, ndarray):
        pvals = pvals._data
    pvals = jnp.asarray(pvals)
    shape = _shape(size)
    counts = jax.random.multinomial(_key(), n, pvals, shape=shape + pvals.shape if shape else None)
    return _wrap(counts.astype(jnp.int64) if False else counts)


def bernoulli(prob=None, logit=None, size=None, dtype=None):
    if (prob is None) == (logit is None):
        from ..base import MXNetError
        raise MXNetError("pass exactly one of prob or logit")
    if prob is not None:
        p = prob._data if isinstance(prob, ndarray) else prob
    else:
        lg = logit._data if isinstance(logit, ndarray) else logit
        p = jax.nn.sigmoid(lg)
    shape = _shape(size) if size is not None else jnp.shape(p)
    return _wrap(jax.random.bernoulli(_key(), p, shape).astype(_fdt(dtype)))


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    a = shape._data if isinstance(shape, ndarray) else shape
    sc = scale._data if isinstance(scale, ndarray) else scale
    sz = _shape(size) if size is not None else jnp.shape(a)
    return _wrap(jax.random.gamma(_key(), a, sz, _fdt(dtype)) * sc)


def beta(a, b, size=None, dtype=None, ctx=None, device=None):
    a = a._data if isinstance(a, ndarray) else a
    b = b._data if isinstance(b, ndarray) else b
    return _wrap(jax.random.beta(_key(), a, b, _shape(size) or None))


def exponential(scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    sc = scale._data if isinstance(scale, ndarray) else scale
    return _wrap(jax.random.exponential(_key(), _shape(size), _fdt(dtype)) * sc)


def poisson(lam=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    lam = lam._data if isinstance(lam, ndarray) else lam
    return _wrap(jax.random.poisson(_key(), lam, _shape(size) or None))


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    return _wrap(jax.random.laplace(_key(), _shape(size), _fdt(dtype))
                 * (scale._data if isinstance(scale, ndarray) else scale)
                 + (loc._data if isinstance(loc, ndarray) else loc))


def gumbel(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    return _wrap(jax.random.gumbel(_key(), _shape(size), _fdt(dtype))
                 * (scale._data if isinstance(scale, ndarray) else scale)
                 + (loc._data if isinstance(loc, ndarray) else loc))


def lognormal(mean=0.0, sigma=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    return _wrap(jnp.exp(jax.random.normal(_key(), _shape(size), _fdt(dtype))
                         * (sigma._data if isinstance(sigma, ndarray) else sigma)
                         + (mean._data if isinstance(mean, ndarray) else mean)))


def chisquare(df, size=None, dtype=None, ctx=None, device=None):
    df = df._data if isinstance(df, ndarray) else df
    return _wrap(jax.random.chisquare(_key(), df, shape=_shape(size) or None))


def rayleigh(scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    sc = scale._data if isinstance(scale, ndarray) else scale
    u = jax.random.uniform(_key(), _shape(size), _fdt(dtype), 1e-7, 1.0)
    return _wrap(sc * jnp.sqrt(-2.0 * jnp.log(u)))


def weibull(a, size=None, ctx=None, device=None, out=None):
    a = a._data if isinstance(a, ndarray) else a
    return _wrap(jax.random.weibull_min(_key(), 1.0, a, _shape(size) or None))


def pareto(a, size=None, ctx=None, device=None, out=None):
    a = a._data if isinstance(a, ndarray) else a
    return _wrap(jax.random.pareto(_key(), a, shape=_shape(size) or None) - 1.0)


def power(a, size=None, ctx=None, device=None, out=None):
    a = a._data if isinstance(a, ndarray) else a
    u = jax.random.uniform(_key(), _shape(size) or jnp.shape(a))
    return _wrap(u ** (1.0 / a))


def logistic(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None,
             device=None, out=None):
    """Reference: _npi_logistic (src/operator/numpy/random/np_location_scale_op.cc)."""
    loc_ = loc._data if isinstance(loc, ndarray) else loc
    sc = scale._data if isinstance(scale, ndarray) else scale
    return _wrap(jax.random.logistic(_key(), _shape(size), _fdt(dtype))
                 * sc + loc_)


def f(dfnum, dfden, size=None, ctx=None, device=None, out=None):
    """F-distribution via two chi-square draws (reference: np_random f)."""
    dfnum = dfnum._data if isinstance(dfnum, ndarray) else dfnum
    dfden = dfden._data if isinstance(dfden, ndarray) else dfden
    c1 = jax.random.chisquare(_key(), dfnum, shape=_shape(size) or None)
    c2 = jax.random.chisquare(_key(), dfden, shape=_shape(size) or None)
    return _wrap((c1 / dfnum) / (c2 / dfden))


def multivariate_normal(mean, cov, size=None, check_valid=None, tol=None):
    """Reference: numpy/random.py multivariate_normal."""
    mean = mean._data if isinstance(mean, ndarray) else jnp.asarray(mean)
    cov = cov._data if isinstance(cov, ndarray) else jnp.asarray(cov)
    return _wrap(jax.random.multivariate_normal(
        _key(), mean, cov, shape=_shape(size) or None))

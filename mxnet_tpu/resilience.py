"""mx.resilience — elastic training: preemption-safe TrainState bundles,
deterministic mid-epoch resume, and supervised retry-with-rejoin.

Reference parity: none — the reference's checkpointing is epoch-granular
(CheckpointHandler saves parameters + optimizer states) and a SIGTERM or a
dead collective kills the job with whatever was in flight.  On preemptible
Cloud TPU fleets preemption is the *normal* lifecycle event, so this module
closes the inject -> detect -> recover -> continue loop that ``mx.fault``
(PR 1) and ``mx.telemetry`` (PR 2) opened:

- :class:`TrainState` bundles {parameters, optimizer states, loss-scaler,
  sampler cursor, RNG streams, step/epoch counters} into ONE crash-atomic
  checksummed file (the PR-1 ``atomic_write_bytes`` + ``.sha256`` sidecar
  machinery), so resume continues at the *exact next batch* with bitwise-
  identical losses — not at the last epoch boundary.
- Signal handling turns SIGTERM/SIGINT into a cooperative preemption: the
  in-flight step finishes, the bundle is written, and training stops with
  :class:`Preempted` (exit sentinel :data:`RESUME_EXIT_CODE`, the
  ``EX_TEMPFAIL`` convention cluster schedulers treat as "reschedule me").
  The ``resilience.preempt`` injection point drives the same path in chaos
  tests without a real signal.
- :func:`run` supervises a training function: a structured
  :class:`WorkerLost` (escalated by the dist kvstore when its bounded
  collective retries are exhausted) restores the last bundle and re-enters
  the function within ``resilience.max_restarts`` — graceful degradation
  instead of a dead job.

Every recovery event lands in ``mx.fault.stats()`` and (when the metrics
registry is on) as ``resilience.*`` counters in ``mx.telemetry``.
"""
from __future__ import annotations

import os
import pickle
import signal as _signal
import threading
import time

from . import config as _config
from . import fault as _fault
from . import goodput as _goodput
from . import random as _random
from . import serialization as _serialization
from . import telemetry as _telemetry
from .base import MXNetError

__all__ = ["TrainState", "Preempted", "WorkerLost", "RESUME_EXIT_CODE",
           "install_signal_handlers", "uninstall_signal_handlers",
           "preempt_requested", "clear_preempt", "run"]

#: process exit status of a run that stopped on preemption with a bundle on
#: disk — BSD EX_TEMPFAIL, the "transient, retry me" sentinel schedulers
#: and supervisors (systemd, batch wrappers) already understand
RESUME_EXIT_CODE = 75

#: TrainState bundle wire-format version (bundles from a newer format
#: refuse to load instead of silently dropping fields)
BUNDLE_VERSION = 1


def _event(name, **labels):
    """Count a recovery event in mx.fault stats AND as a resilience.*
    telemetry counter (the ISSUE-3 contract: every recovery is visible)."""
    _fault.record("resilience." + name)
    if _telemetry._active:
        _telemetry.inc("resilience." + name + "_total", **labels)


class Preempted(MXNetError):
    """Training stopped cooperatively on a preemption signal (or the
    ``resilience.preempt`` injection); the TrainState bundle at ``path``
    holds everything a restarted process needs to continue."""

    def __init__(self, path=None, step=None, origin="signal"):
        self.path = path
        self.step = step
        self.origin = origin
        at = f" at step {step}" if step is not None else ""
        where = f"; resume bundle: {path}" if path else ""
        super().__init__(
            f"training preempted ({origin}){at}{where}. Restart the job "
            f"and restore the bundle (exit sentinel {RESUME_EXIT_CODE}).")


class WorkerLost(MXNetError):
    """A peer (or the fabric to it) is gone: the dist kvstore exhausted its
    collective retry budget.  Structured so supervisors can dispatch on the
    fields: ``op``/``key`` (the collective that died), ``rank``/``nprocs``,
    ``attempts`` (tries made), ``last`` (the final underlying error)."""

    def __init__(self, op, key, rank, nprocs, attempts, last):
        self.op = op
        self.key = key
        self.rank = rank
        self.nprocs = nprocs
        self.attempts = attempts
        self.last = last
        super().__init__(
            f"worker lost: collective '{op}' for key {key!r} failed "
            f"{attempts}x with rejoin on rank {rank}/{nprocs}; last error: "
            f"{last}")


# ---------------------------------------------------------------------------
# preemption signals
# ---------------------------------------------------------------------------

_preempt_flag = threading.Event()
_prev_handlers: dict[int, object] = {}


def _on_signal(signum, frame):
    _preempt_flag.set()
    _event("preempt_signal", signal=_signal.Signals(signum).name)


def install_signal_handlers(signals=(_signal.SIGTERM, _signal.SIGINT)):
    """Install graceful-shutdown handlers: the signal only sets a flag;
    the training loop observes it via :func:`preempt_requested` after the
    in-flight step, writes the bundle, and stops.  Returns the list of
    signals actually hooked (empty off the main thread, where CPython
    forbids ``signal.signal``)."""
    hooked = []
    for sig in signals:
        try:
            _prev_handlers[sig] = _signal.signal(sig, _on_signal)
            hooked.append(sig)
        except ValueError:       # not the main thread
            break
    return hooked


def uninstall_signal_handlers():
    """Restore whatever handlers were displaced (idempotent)."""
    while _prev_handlers:
        sig, prev = _prev_handlers.popitem()
        try:
            _signal.signal(sig, prev)
        except (ValueError, TypeError):
            pass


def preempt_requested(step=None):
    """True when a preemption signal arrived OR the ``resilience.preempt``
    injection point fires on this probe (one probe per training step, so
    ``resilience.preempt:at=N`` preempts deterministically at step N)."""
    if _preempt_flag.is_set():
        return True
    if _fault._active and _fault.fire("resilience.preempt", step=step):
        _preempt_flag.set()
        return True
    return False


def clear_preempt():
    """Drop a pending preemption flag (after it has been honored)."""
    _preempt_flag.clear()


# ---------------------------------------------------------------------------
# TrainState bundles
# ---------------------------------------------------------------------------

class TrainState:
    """Crash-atomic checksummed bundle of everything a mid-epoch resume
    needs: parameters, optimizer/updater states, loss-scaler, sampler
    cursor, RNG streams, step/epoch counters.

    The object holds live references (``net``/``trainer``/``loader``/
    ``sharded_step`` are all optional — bundle whatever the run has) and
    moves state in place.  A :class:`~mxnet_tpu.parallel.ShardedTrainStep`
    passed as ``sharded_step`` contributes its canonical (gathered,
    topology-independent) state, so dp-sharded and ZeRO-partitioned runs
    resume bitwise even at a different dp size::

        state = mx.resilience.TrainState(net=net, trainer=trainer,
                                         loader=loader, path="run.bundle")
        ...
        state.step += 1            # after every optimizer step
        state.save()               # on preemption (ResilienceHandler does)
        ...
        state.load()               # in the restarted process

    ``save`` writes ONE file via the PR-1 crash-atomic machinery
    (same-dir temp + fsync + ``os.replace``) plus a ``.sha256`` sidecar;
    ``load`` validates the checksum first, so a bundle torn by the very
    preemption it was written under is rejected loudly, never half-loaded.
    """

    def __init__(self, net=None, trainer=None, loader=None, path=None,
                 sharded_step=None):
        self.net = net
        self.trainer = trainer
        self.loader = loader
        self.sharded_step = sharded_step
        self.path = path
        self.step = 0
        self.epoch = 0

    # -- capture -----------------------------------------------------------
    def state_dict(self):
        bundle = {"version": BUNDLE_VERSION, "step": int(self.step),
                  "epoch": int(self.epoch), "rng": _random.get_state(),
                  "saved_unix": time.time()}
        if self.net is not None:
            bundle["params"] = {
                name: p.data().asnumpy()
                for name, p in self.net.collect_params().items()
                if p._data is not None}
        if self.trainer is not None:
            bundle["trainer"] = self.trainer.state_dict()
        if self.loader is not None:
            bundle["loader"] = self.loader.state_dict()
        if self.sharded_step is not None:
            # ShardedTrainStep.state_dict() is already canonical (dp-sharded
            # / ZeRO-partitioned leaves gathered, unpadded and reshaped to
            # weight form), so the bundle stays topology-independent: it can
            # be restored into a step with a different dp size or zero level.
            bundle["sharded_step"] = self.sharded_step.state_dict()
        return bundle

    def save(self, path=None):
        path = path or self.path
        if path is None:
            raise MXNetError("TrainState.save: no bundle path configured")
        tok = _goodput.begin("checkpoint_save") if _goodput._active else None
        try:
            self._save_bundle(path)
        finally:
            _goodput.end(tok)
        return path

    def _save_bundle(self, path):
        blob = pickle.dumps(self.state_dict(),
                            protocol=pickle.HIGHEST_PROTOCOL)
        _serialization.atomic_write_bytes(path, blob)
        _serialization.write_checksum(path)
        _event("bundle_save")
        self._gc(path)
        # streaming loaders additionally publish their cursor to the
        # shared fleet dir at every checkpoint: the bundle owns the
        # cursor for *this* host's restarts, the published copy is what
        # a SURVIVOR rolls forward when this host dies (mx.stream
        # take_over_host). Best-effort: shared storage hiccups must not
        # fail the checkpoint that just landed.
        publish = getattr(self.loader, "publish_cursor", None)
        if publish is not None:
            try:
                publish()
            except OSError:
                pass
        from . import blackbox as _blackbox
        if _blackbox._active:
            # the postmortem names the exact checkpoint generation a
            # replacement host will restore
            _blackbox.note_checkpoint(
                path, self.step,
                generation=f"{path}.g{int(self.step):08d}")
        return path

    # -- retention ---------------------------------------------------------
    @staticmethod
    def _history(path):
        """Existing ``<path>.gN`` generation bundles, oldest step first
        (the zero-padded step number in the name makes lexical order
        chronological)."""
        import glob as _glob
        suffix = _serialization.CHECKSUM_SUFFIX
        return sorted(p for p in _glob.glob(_glob.escape(path) + ".g*")
                      if not p.endswith(suffix))

    def _gc(self, path):
        """Retention GC, run after every successful ``save``: hard-link the
        fresh primary into a ``<path>.gN`` generation (N = step), then
        delete torn generations and everything older than the newest
        ``resilience.keep_bundles`` — the guaranteed-valid fallback chain
        :meth:`load_latest_valid` walks.  ``keep_bundles=0`` keeps the
        primary only (pre-GC behaviour)."""
        keep = _config.get("resilience.keep_bundles")
        if keep <= 0:
            return
        suffix = _serialization.CHECKSUM_SUFFIX
        gen = f"{path}.g{int(self.step):08d}"
        for src, dst in ((path, gen), (path + suffix, gen + suffix)):
            if os.path.exists(dst):
                os.remove(dst)
            try:
                os.link(src, dst)
            except OSError:                # filesystem without hard links
                import shutil
                shutil.copyfile(src, dst)
        survivors = []
        for p in self._history(path):
            try:
                _serialization.verify_checksum(p, required=True)
            except MXNetError:
                self._unlink_gen(p, suffix)
                _event("bundle_gc", reason="torn")
                continue
            survivors.append(p)
        for p in survivors[:-keep]:
            self._unlink_gen(p, suffix)
            _event("bundle_gc", reason="retention")

    @staticmethod
    def _unlink_gen(p, suffix):
        for stale in (p, p + suffix):
            try:
                os.remove(stale)
            except FileNotFoundError:
                pass

    # -- restore -----------------------------------------------------------
    def load(self, path=None):
        """Validate, read and apply the bundle at ``path`` (default: the
        configured path).  Raises :class:`MXNetError` on a missing file,
        checksum mismatch, or a newer bundle format."""
        path = path or self.path
        if path is None or not os.path.exists(path):
            raise MXNetError(f"TrainState.load: no bundle at {path!r}")
        tok = _goodput.begin("restore") if _goodput._active else None
        try:
            _serialization.verify_checksum(path)
            with open(path, "rb") as f:
                try:
                    bundle = pickle.loads(f.read())
                except Exception as e:  # noqa: BLE001 - torn/corrupt pickle
                    raise MXNetError(
                        f"{path}: corrupt TrainState bundle ({e})") from e
            self.restore(bundle)
        finally:
            _goodput.end(tok)
        return bundle

    def load_latest_valid(self, path=None):
        """Restore from the newest bundle that passes validation: the
        primary first, then the retention history (``<path>.gN``,
        newest first).  The fleet degrade path uses this — a host can die
        mid-``save`` and leave the primary torn, and the survivors must
        fall back to the previous generation instead of dying on it.
        Plain :meth:`load` keeps its strict raise-on-torn contract.
        Returns the path actually restored."""
        path = path or self.path
        if path is None:
            raise MXNetError(
                "TrainState.load_latest_valid: no bundle path configured")
        candidates = [path] + list(reversed(self._history(path)))
        last_err = None
        tok = _goodput.begin("restore") if _goodput._active else None
        try:
            for p in candidates:
                if not os.path.exists(p):
                    continue
                try:
                    _serialization.verify_checksum(p)
                    with open(p, "rb") as f:
                        bundle = pickle.loads(f.read())
                except Exception as e:  # noqa: BLE001 - torn: next gen
                    last_err = e
                    continue
                self.restore(bundle)
                return p
        finally:
            _goodput.end(tok)
        raise MXNetError(
            f"TrainState.load_latest_valid: no valid bundle at {path!r} "
            f"or its history; last error: {last_err}")

    def restore(self, bundle):
        """Apply an already-deserialized bundle to the live objects."""
        version = bundle.get("version", 0)
        if version > BUNDLE_VERSION:
            raise MXNetError(
                f"TrainState bundle format v{version} is newer than this "
                f"build's v{BUNDLE_VERSION}; upgrade before resuming")
        params = bundle.get("params")
        if params is not None and self.net is not None:
            from .numpy import array
            mine = self.net.collect_params()
            for name, p in mine.items():
                if name in params:
                    p.set_data(array(params[name]))
                elif p._data is not None:
                    raise MXNetError(
                        f"TrainState bundle is missing parameter {name!r}; "
                        "refusing a silent partial restore")
        if bundle.get("trainer") is not None and self.trainer is not None:
            self.trainer.load_state_dict(bundle["trainer"])
        if bundle.get("loader") is not None and self.loader is not None:
            self.loader.load_state_dict(bundle["loader"])
        if (bundle.get("sharded_step") is not None
                and self.sharded_step is not None):
            self.sharded_step.load_state_dict(bundle["sharded_step"])
        if bundle.get("rng") is not None:
            _random.set_state(bundle["rng"])
        self.step = int(bundle.get("step", 0))
        self.epoch = int(bundle.get("epoch", 0))
        _event("bundle_restore")

    def exists(self, path=None):
        path = path or self.path
        return path is not None and os.path.exists(path)


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def run(train_fn, state=None, max_restarts=None, exit_on_preempt=False,
        resume_on_preempt=False):
    """Supervise ``train_fn`` (a zero-arg callable) against worker loss
    and preemption.

    - :class:`WorkerLost` (the dist kvstore exhausted its collective
      retries): restore the last TrainState bundle (when ``state`` is
      given and a bundle exists) and re-enter ``train_fn``, up to
      ``max_restarts`` times (default: the ``resilience.max_restarts``
      knob); then re-raise.
    - :class:`Preempted`: the bundle was already written by the preempt
      path.  With ``exit_on_preempt=True`` the process exits with
      :data:`RESUME_EXIT_CODE` so the scheduler reschedules it; with
      ``resume_on_preempt=True`` (and a restorable ``state``) the
      supervisor instead restores the bundle in-process and re-enters
      ``train_fn`` against the restart budget — single-host runs where
      the "scheduler" is this very process; otherwise the exception
      propagates to the caller (tests, notebooks).

    Returns whatever ``train_fn`` returns on success.
    """
    budget = (max_restarts if max_restarts is not None
              else _config.get("resilience.max_restarts"))
    window = _config.get("resilience.restart_window_steps")
    restarts = 0
    prev_step = None
    while True:
        try:
            return train_fn()
        except Preempted as e:
            # SystemExit never reaches sys.excepthook, so the exit-75
            # path must freeze its evidence here, before the bundle of
            # record is the only artifact the host leaves behind
            from . import blackbox as _blackbox
            if _blackbox._active:
                _blackbox.dump(trigger="preempt",
                               reason=f"preempted ({e.origin}) at step "
                                      f"{e.step}", step=e.step)
            if exit_on_preempt:
                _event("preempt_exit")
                raise SystemExit(RESUME_EXIT_CODE)
            if resume_on_preempt and state is not None and state.exists():
                if restarts >= budget:
                    _event("restart_budget_exhausted")
                    raise
                restarts += 1
                # the whole resume (bundle restore + re-entry) is
                # restart badput; restart outranks the nested restore
                # claim so the ledger counts the downtime once
                tok = (_goodput.begin("restart")
                       if _goodput._active else None)
                try:
                    state.load_latest_valid()
                    prev_step = state.step
                    _event("preempt_resume")
                    clear_preempt()
                finally:
                    _goodput.end(tok)
                continue
            raise
        except WorkerLost as e:
            from . import blackbox as _blackbox
            if _blackbox._active:
                _blackbox.dump(trigger="worker_lost",
                               reason=f"WorkerLost({e.op}): {e}", exc=e)
            # a healthy-progress window between faults forgives the budget:
            # N transient faults spread over days should not add up to the
            # same death sentence as N faults in a tight crash loop
            cur = state.step if state is not None else None
            if (window > 0 and cur is not None and prev_step is not None
                    and cur - prev_step >= window):
                restarts = 0
                _event("restart_budget_reset")
            if restarts >= budget:
                _event("restart_budget_exhausted")
                raise
            restarts += 1
            _event("worker_lost", op=e.op)
            tok = _goodput.begin("restart") if _goodput._active else None
            try:
                if state is not None and state.exists():
                    state.load()
                    prev_step = state.step
                _event("restart")
                clear_preempt()
            finally:
                _goodput.end(tok)

"""mx.executor — symbol executor (alias module).

Reference parity: python/mxnet/executor.py (Executor produced by
Symbol.bind with forward/backward/arg_dict).  The implementation lives
with the Symbol frontend (mxnet_tpu/symbol/symbol.py Executor); this
module keeps the reference's import location working.
"""
from .symbol.symbol import Executor  # noqa: F401

__all__ = ["Executor"]

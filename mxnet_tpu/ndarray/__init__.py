"""mx.nd — legacy NDArray namespace.

Reference parity: python/mxnet/ndarray/ (23.7k LoC of generated legacy op
wrappers). The new framework is numpy-first (like MXNet 2.0 pushes mx.np);
this module aliases the np implementation and adds the handful of
legacy-named entry points (mx.nd.array, waitall, save/load, NDArray) so
MXNet-1.x-style scripts run.
"""
from __future__ import annotations

from ..numpy import *  # noqa: F401,F403
from ..numpy import ndarray as NDArray, array, zeros, ones, full, arange  # noqa: F401
from ..numpy.multiarray import _wrap, _invoke  # noqa: F401
from ..numpy import random  # noqa: F401
from .. import numpy as _np
from . import sparse  # noqa: F401
from .sparse import RowSparseNDArray, CSRNDArray  # noqa: F401


def waitall():
    from .. import engine
    engine.wait_all()


def save(fname, data):
    """mx.nd.save writes the 1.x legacy NDArray binary format
    (reference: ndarray.py save over NDArray::Save, ndarray.cc:2125) —
    files interchange with Apache MXNet. Use npx.save for npz."""
    from .. import serialization
    from ..base import MXNetError
    if isinstance(data, NDArray):
        data = [data]
    if not isinstance(data, (dict, list, tuple)):
        # a raw numpy/jax array would be iterated row-by-row; reject like
        # the reference (ndarray.py save raises ValueError)
        raise MXNetError(
            "nd.save expects an NDArray, a list of NDArrays, or a "
            f"dict of str->NDArray, got {type(data).__name__}")
    serialization.save_legacy_params(fname, data)


def load(fname):
    """mx.nd.load reads both the legacy binary format and npz
    (reference: ndarray.py load)."""
    from .. import serialization
    if serialization.is_legacy_params(fname):
        loaded = serialization.load_legacy_params(fname)
        if isinstance(loaded, list):
            return [array(v) for v in loaded]
        return {k: array(v) for k, v in loaded.items()}
    from .. import numpy_extension as npx
    return npx.load(fname)


def Custom(*inputs, op_type=None, **kwargs):
    """Invoke a registered python custom op (reference: mx.nd.Custom over
    src/operator/custom/custom.cc; see mx.operator)."""
    from .. import operator as _op
    return _op.Custom(*inputs, op_type=op_type, **kwargs)


def __getattr__(name):
    if name in ("register", "contrib"):  # submodules, not ops
        import importlib
        return importlib.import_module(__name__ + "." + name)
    # 1) the table-driven legacy surface (CamelCase layer ops + legacy
    #    snake_case names like broadcast_add) — see register.py
    import importlib
    _register = importlib.import_module(__name__ + ".register")
    fn = _register.get(name)
    if fn is not None:
        return fn
    # 2) np, then npx (legacy nd exposed both layer and tensor ops)
    try:
        return getattr(_np, name)
    except AttributeError:
        pass
    from .. import numpy_extension as _npx
    fn = getattr(_npx, name, None)
    if fn is not None:
        if callable(fn) and not isinstance(fn, type):
            return _register.with_out(fn)
        return fn
    lowered = name.lower()
    if lowered != name:
        return getattr(_np, lowered)
    raise AttributeError(name)

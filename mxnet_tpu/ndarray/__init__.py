"""mx.nd — legacy NDArray namespace.

Reference parity: python/mxnet/ndarray/ (23.7k LoC of generated legacy op
wrappers). The new framework is numpy-first (like MXNet 2.0 pushes mx.np);
this module aliases the np implementation and adds the handful of
legacy-named entry points (mx.nd.array, waitall, save/load, NDArray) so
MXNet-1.x-style scripts run.
"""
from __future__ import annotations

from ..numpy import *  # noqa: F401,F403
from ..numpy import ndarray as NDArray, array, zeros, ones, full, arange  # noqa: F401
from ..numpy.multiarray import _wrap, _invoke  # noqa: F401
from ..numpy import random  # noqa: F401
from .. import numpy as _np
from . import sparse  # noqa: F401
from .sparse import RowSparseNDArray, CSRNDArray  # noqa: F401


def waitall():
    from .. import engine
    engine.wait_all()


def save(fname, data):
    from .. import numpy_extension as npx
    npx.save(fname, data)


def load(fname):
    from .. import numpy_extension as npx
    return npx.load(fname)


def __getattr__(name):
    # legacy op names are the np names (plus CamelCase op aliases)
    try:
        return getattr(_np, name)
    except AttributeError:
        lowered = name.lower()
        if lowered != name:
            return getattr(_np, lowered)
        raise

"""mx.nd.contrib — contrib operator namespace.

Reference parity: python/mxnet/ndarray/contrib.py (control-flow helpers
foreach/while_loop/cond) plus the contrib C++ ops this build keeps:
FFT (src/operator/contrib/fft-inl.h: real (N, d) -> interleaved
real/imag (N, 2d)), and the DGL graph-sampling family
(src/operator/contrib/dgl_graph.cc).

TPU-native: FFT lowers to jnp.fft (XLA FFT HLO); the DGL samplers are
imperative host ops (data-dependent output shapes, like the reference's
CPU-only implementations).
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..numpy.multiarray import _wrap, ndarray


def _raw(x):
    import jax.numpy as jnp
    return x._data if isinstance(x, ndarray) else jnp.asarray(x)


# -- control flow (reference: ndarray/contrib.py foreach/while_loop/cond) --

def foreach(body, data, init_states):
    from .. import numpy_extension as npx
    return npx.foreach(body, data, init_states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    from .. import numpy_extension as npx
    return npx.while_loop(cond, func, loop_vars,
                          max_iterations=max_iterations)


def cond(pred, then_func, else_func):
    from .. import numpy_extension as npx
    return npx.cond(pred, then_func, else_func)


# -- FFT (reference: src/operator/contrib/fft-inl.h) -----------------------

def fft(data, compute_size=128):
    """1-D FFT over the last axis: real (..., d) -> (..., 2d) interleaved
    [re0, im0, re1, im1, ...] (the reference's cuFFT wire format)."""
    import jax.numpy as jnp
    x = _raw(data)
    spec = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return _wrap(out.reshape(x.shape[:-1] + (2 * x.shape[-1],))
                 .astype(jnp.float32))


def ifft(data, compute_size=128):
    """Inverse of ``fft``: (..., 2d) interleaved -> real (..., d).

    Matches the reference's unnormalized cuFFT inverse (ifft(fft(x)) =
    d * x; callers divide by d, see fft-inl.h docs)."""
    import jax.numpy as jnp
    x = _raw(data)
    d = x.shape[-1] // 2
    pairs = x.reshape(x.shape[:-1] + (d, 2))
    spec = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(spec, axis=-1).real * d  # unnormalized like cuFFT
    return _wrap(out.astype(jnp.float32))


# -- DGL graph sampling (reference: src/operator/contrib/dgl_graph.cc) -----

def dgl_csr_neighbor_uniform_sample(csr, seeds, num_hops=1, num_neighbor=2,
                                    max_num_vertices=100):
    """Uniform neighbor sampling from a CSR graph (reference:
    _contrib_dgl_csr_neighbor_uniform_sample). Returns (sampled_vertices,
    sampled_subgraph_csr, layer_ids); vertices padded with -1 to
    max_num_vertices, with the valid count stored in the last slot."""
    from ..ndarray.sparse import CSRNDArray
    if not isinstance(csr, CSRNDArray):
        raise MXNetError("expects a CSRNDArray graph")
    indptr = onp.asarray(csr.indptr._data)
    indices = onp.asarray(csr.indices._data)
    seed_ids = onp.asarray(_raw(seeds)).astype("int64").ravel()
    seed_ids = seed_ids[seed_ids >= 0]

    cap = max_num_vertices - 1
    # seeds are admitted first and the cap is enforced DURING expansion,
    # so seed vertices can never be truncated out of the sample
    visited = {}
    for v in seed_ids[:cap]:
        visited[int(v)] = 0
    frontier = list(visited)
    rng = onp.random
    for hop in range(1, num_hops + 1):
        nxt = []
        for v in frontier:
            nbrs = indices[indptr[v]:indptr[v + 1]]
            if len(nbrs) == 0:
                continue
            take = min(num_neighbor, len(nbrs))
            chosen = rng.choice(nbrs, size=take, replace=False)
            for u in chosen:
                u = int(u)
                if u not in visited and len(visited) < cap:
                    visited[u] = hop
                    nxt.append(u)
        frontier = nxt
        if len(visited) >= cap:
            break
    verts = sorted(visited)
    n_valid = len(verts)
    out_ids = onp.full((max_num_vertices,), -1, "int64")
    out_ids[:n_valid] = verts
    out_ids[-1] = n_valid  # reference convention: count in the last slot
    layers = onp.full((max_num_vertices,), -1, "int64")
    layers[:n_valid] = [visited[v] for v in verts]

    # induced subgraph CSR over the sampled vertices (relabelled 0..n-1)
    pos = {v: i for i, v in enumerate(verts)}
    sub_rows = []
    for v in verts:
        nbrs = [pos[int(u)] for u in indices[indptr[v]:indptr[v + 1]]
                if int(u) in pos]
        sub_rows.append(sorted(nbrs))
    data, idx, ptr = [], [], [0]
    for r in sub_rows:
        idx.extend(r)
        data.extend([1.0] * len(r))
        ptr.append(len(idx))
    sub = CSRNDArray(onp.asarray(data, "float32"),
                     onp.asarray(idx, "int64"), onp.asarray(ptr, "int64"),
                     (n_valid, n_valid))
    return _wrap_np(out_ids), sub, _wrap_np(layers)


def dgl_adjacency(csr):
    """CSR adjacency with all-ones data (reference: _contrib_dgl_adjacency)."""
    from ..ndarray.sparse import CSRNDArray
    import jax.numpy as jnp
    if not isinstance(csr, CSRNDArray):
        raise MXNetError("expects a CSRNDArray graph")
    return CSRNDArray(jnp.ones_like(csr.data._data, jnp.float32),
                      csr.indices, csr.indptr, csr.shape)


def dgl_subgraph(graph, *vids, return_mapping=False):
    """Induced subgraphs for given vertex sets (reference:
    _contrib_dgl_subgraph)."""
    from ..ndarray.sparse import CSRNDArray
    if not isinstance(graph, CSRNDArray):
        raise MXNetError("expects a CSRNDArray graph")
    indptr = onp.asarray(graph.indptr._data)
    indices = onp.asarray(graph.indices._data)
    outs = []
    for vid in vids:
        ids = onp.asarray(_raw(vid)).astype("int64").ravel()
        ids = ids[ids >= 0]
        pos = {int(v): i for i, v in enumerate(ids)}
        data, idx, ptr = [], [], [0]
        for v in ids:
            nbrs = [pos[int(u)] for u in indices[indptr[v]:indptr[v + 1]]
                    if int(u) in pos]
            idx.extend(sorted(nbrs))
            data.extend([1.0] * len(nbrs))
            ptr.append(len(idx))
        outs.append(CSRNDArray(onp.asarray(data, "float32"),
                               onp.asarray(idx, "int64"),
                               onp.asarray(ptr, "int64"),
                               (len(ids), len(ids))))
    return outs[0] if len(outs) == 1 else outs


def _wrap_np(a):
    import jax.numpy as jnp
    return _wrap(jnp.asarray(a))


_CAMEL = {
    # legacy contrib CamelCase aliases (reference: _contrib_MultiBox* ops
    # surfaced as mx.nd.contrib.MultiBoxPrior etc.)
    "MultiBoxPrior": "multibox_prior",
    "MultiBoxTarget": "multibox_target",
    "MultiBoxDetection": "multibox_detection",
    "BipartiteMatching": "bipartite_matching",
}


def __getattr__(name):
    """Fall back to the npx operator surface: the reference exposes every
    _contrib_* op here (box_nms, box_iou, multibox_*, ...)."""
    from .. import numpy_extension as _npx
    fn = getattr(_npx, _CAMEL.get(name, name), None)
    if fn is not None:
        return fn
    raise AttributeError(f"mxnet.ndarray.contrib has no op '{name}'")
